"""North-star benchmark matrix (BASELINE.md "North-star targets").

Measures the five driver-specified configurations through the REAL
verification paths (types/validation.verify_commit* -> crypto.batch ->
TpuBatchVerifier), not raw kernel calls:

  1. 64-sig BatchVerifier micro-bench
  2. VerifyCommit on a 150-validator commit (e2e latency)
  3. VerifyCommit on a 10k-validator commit (e2e latency; <2ms target
     is device-compute; the e2e number includes host sign-bytes
     encoding and link transfer)
  4. light-header sync: 150-validator commits verified at scale with
     pipelined launches (10k headers modeled; n_run actually measured)
  5. blocksync replay: 1k-validator commits, pipelined (1k blocks
     modeled; mixed ed25519+bls variant lands with the BLS backend)

Prints one JSON line per config and writes BENCH_ALL.json.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


CHAIN_ID = "bench-chain"


def make_commit_fixture(nvals: int):
    """Real valset + commit: every validator signs its canonical
    precommit bytes (the exact messages verify_commit reconstructs)."""
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    keys = [ed.priv_key_from_secret(b"bench%d" % i) for i in range(nvals)]
    vals = ValidatorSet(
        [Validator(k.pub_key(), 10) for k in keys]
    )
    by_addr = {k.pub_key().address(): k for k in keys}
    ordered = [by_addr[v.address] for v in vals.validators]
    h = bytes(range(32))
    bid = BlockID(
        hash=h, part_set_header=PartSetHeader(total=1, hash=h[::-1])
    )
    sigs = []
    for i, k in enumerate(ordered):
        ts = 1_700_000_000_000_000_000 + i
        msg = canonical.vote_sign_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, 1, 0, bid, ts
        )
        sigs.append(
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=k.pub_key().address(),
                timestamp_ns=ts,
                signature=k.sign(msg),
            )
        )
    commit = Commit(height=1, round=0, block_id=bid, signatures=tuple(sigs))
    return vals, commit, bid


def make_bls_aggregate_fixture(nvals: int):
    """A commit carrying ONE BLS aggregate signature over its
    BLOCK_ID_FLAG_COMMIT precommits (types/block.py Commit docstring):
    every validator signs the shared canonical aggregate message, the
    per-validator signature fields stay EMPTY, and verification is one
    pairing-product check — the arXiv:2302.00418 committee shape the
    ``bls_aggregate_150val`` row measures against ``verify_commit_150``."""
    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    keys = [
        bls.priv_key_from_secret(b"agg%d" % i) for i in range(nvals)
    ]
    vals = ValidatorSet([Validator(k.pub_key(), 10) for k in keys])
    by_addr = {k.pub_key().address(): k for k in keys}
    ordered = [by_addr[v.address] for v in vals.validators]
    h = bytes(range(32))
    bid = BlockID(
        hash=h, part_set_header=PartSetHeader(total=1, hash=h[::-1])
    )
    msg = Commit(height=1, round=0, block_id=bid).aggregate_sign_bytes(
        CHAIN_ID
    )
    agg = bls.aggregate_signatures([k.sign(msg) for k in ordered])
    sigs = tuple(
        CommitSig(
            block_id_flag=BLOCK_ID_FLAG_COMMIT,
            validator_address=k.pub_key().address(),
            timestamp_ns=0,
            signature=b"",
        )
        for k in ordered
    )
    commit = Commit(
        height=1, round=0, block_id=bid, signatures=sigs,
        agg_signature=agg,
    )
    return vals, commit, bid


def make_mixed_commit_fixture(n_ed: int, n_bls: int):
    """A commit signed by n_ed ed25519 + n_bls bls12_381 validators
    (BASELINE config 5's mega-commit shape)."""
    from cometbft_tpu.crypto import bls12381 as bls
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.types import canonical
    from cometbft_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT,
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    keys = [
        ed.priv_key_from_secret(b"med%d" % i) for i in range(n_ed)
    ] + [
        bls.priv_key_from_secret(b"mbls%d" % i) for i in range(n_bls)
    ]
    vals = ValidatorSet([Validator(k.pub_key(), 10) for k in keys])
    by_addr = {k.pub_key().address(): k for k in keys}
    ordered = [by_addr[v.address] for v in vals.validators]
    h = bytes(range(32))
    bid = BlockID(
        hash=h, part_set_header=PartSetHeader(total=1, hash=h[::-1])
    )
    sigs = []
    for i, k in enumerate(ordered):
        ts = 1_700_000_000_000_000_000 + i
        msg = canonical.vote_sign_bytes(
            CHAIN_ID, canonical.PRECOMMIT_TYPE, 1, 0, bid, ts
        )
        sigs.append(
            CommitSig(
                block_id_flag=BLOCK_ID_FLAG_COMMIT,
                validator_address=k.pub_key().address(),
                timestamp_ns=ts,
                signature=k.sign(msg),
            )
        )
    commit = Commit(height=1, round=0, block_id=bid, signatures=tuple(sigs))
    return vals, commit, bid


def merge_results(
    path: str, results: list[dict], replace_if=None, **doc_fields
) -> None:
    """Merge ``results`` into a BENCH_ALL-shaped JSON file atomically.

    Existing entries are kept unless ``replace_if(existing_row)`` says
    this write owns them (default: same config name). ONE
    implementation for every bench tool — bench_all, loadtime, and the
    host-baseline tool all write the same file."""
    if replace_if is None:
        ours = {r["config"] for r in results}

        def replace_if(row):  # noqa: F811 — default policy
            return row.get("config") in ours

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"results": []}
    doc["results"] = [
        r for r in doc.get("results", []) if not replace_if(r)
    ] + results
    doc.update(doc_fields)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def timed(fn, warmups: int = 1, iters: int = 3) -> float:
    for _ in range(warmups):
        fn()
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    import jax

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.metrics import CryptoMetrics, install_crypto_metrics
    from cometbft_tpu.ops.ed25519_verify import (
        TpuBatchVerifier,
        verify_stream,
    )
    from cometbft_tpu.types import validation
    from cometbft_tpu.utils.metrics import Registry

    # live crypto metrics for the run: every row's provenance records
    # the dispatch tier(s) the config ACTUALLY hit (keyed_mesh / keyed
    # / generic / host) — BENCH_ALL previously couldn't tell a keyed
    # measurement from a generic one, which is how the perf trajectory
    # kept quoting the generic kernel by accident
    from cometbft_tpu.ops import jitguard as _jg

    cm = CryptoMetrics(Registry())
    install_crypto_metrics(cm)
    tier_seen: dict[str, float] = {}
    compiles_seen: dict[str, int] = {}

    def compiles_delta() -> dict[str, int]:
        # per-seam jit compiles since the last record: a nonzero delta
        # on a row measured AFTER its warmup means the "steady state"
        # recompiled mid-measurement (docs/device_contracts.md)
        now = _jg.compile_counts()
        delta = {
            s: int(c - compiles_seen.get(s, 0))
            for s, c in now.items()
            if c > compiles_seen.get(s, 0)
        }
        compiles_seen.clear()
        compiles_seen.update(now)
        return delta

    def tier_delta() -> dict[str, int]:
        now = {
            k[0]: c.get() for k, c in cm.dispatch_tier.children().items()
        }
        delta = {
            t: int(v - tier_seen.get(t, 0))
            for t, v in now.items()
            if v > tier_seen.get(t, 0)
        }
        tier_seen.clear()
        tier_seen.update(now)
        return delta

    dev = jax.devices()[0]
    on_cpu = dev.platform == "cpu"
    log(f"device: {dev}")
    results = []
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_ALL.json"
    )

    def checkpoint():
        # merge-write after every config: a mid-run death (r3 lost the
        # mixed-megacommit entry this way) keeps what was measured.
        # Entries other tools own are preserved: loadtime_* by config
        # name, and the host-dispatch rows (host_path) even when they
        # share a config name with a device measurement
        ours = {r["config"] for r in results}
        merge_results(
            path, results,
            replace_if=lambda r: (
                r.get("config") in ours and not r.get("host_path")
            ),
            device=str(dev),
        )

    # attribution plane: sample the whole matrix once, window each
    # row's hotspots to the interval since the previous record — the
    # row's provenance says what the host CPU ran while it measured
    prof = None
    try:
        from cometbft_tpu.utils.profiler import SamplingProfiler

        prof = SamplingProfiler(hz=97, capacity=8192)
        prof.start()
    except Exception as exc:  # noqa: BLE001 — provenance only
        log(f"profiler unavailable (continuing without): {exc}")
    last_record = [time.time()]

    def record(config: str, value: float, unit: str, **extra):
        row = {"config": config, "value": round(value, 2), "unit": unit}
        row.update(extra)
        # winning tier = the most-hit tier since the last record; the
        # stream configs dispatch outside the verifier seam and pass an
        # explicit dispatch_tier instead
        tiers = tier_delta()
        if tiers and "dispatch_tier" not in row:
            row["dispatch_tier"] = max(tiers, key=tiers.get)
            row["dispatch_tiers"] = tiers
        compiles = compiles_delta()
        if compiles:
            row["jit_compiles"] = compiles
        if prof is not None:
            try:
                window = max(time.time() - last_record[0], 0.0)
                hot = prof.top_functions(5, seconds=window)
                if hot:
                    row["hotspots"] = hot
            except Exception:  # noqa: BLE001 — provenance only
                pass
        last_record[0] = time.time()
        row["measured"] = time.strftime("round 6, %Y-%m-%d")
        results.append(row)
        print(json.dumps(row), flush=True)
        checkpoint()
        # every measured row lands in the perf ledger with its
        # provenance (tier, compiles, hotspots) — the regression
        # gate's input
        from tools import perfledger

        perfledger.append_rows([row], source="bench_all")

    # ---- config 1: 64-sig micro-bench --------------------------------
    # PRODUCTION dispatch: the runtime threshold routes a 64-sig batch
    # wherever a real caller's batch would go (on a high-RTT link
    # that's the host batch verifier — measuring the forced-device
    # path here would record a path no caller takes; r4 verdict #3)
    rng = np.random.RandomState(7)
    priv = ed.gen_priv_key()
    msgs64 = [rng.bytes(120) for _ in range(64)]
    sigs64 = [priv.sign(m) for m in msgs64]
    pub = priv.pub_key()

    def micro():
        bv = TpuBatchVerifier()
        for m, s in zip(msgs64, sigs64):
            bv.add(pub, m, s)
        ok, bits = bv.verify()
        assert ok, "micro-bench sigs must verify"

    from cometbft_tpu.ops.ed25519_verify import runtime_device_min_batch

    threshold = runtime_device_min_batch()
    dt = timed(micro)
    record(
        "micro_64sig", 64 / dt, "sigs/sec", latency_ms=round(dt * 1e3, 2),
        dispatch=(
            "host batch verifier" if 64 < threshold else "device kernel"
        ),
        device_min_batch=threshold if threshold < (1 << 30) else "inf",
    )

    # forced-device variant: kernel+link progress stays visible even
    # when the production router prefers the CPU at this size.  The
    # cost router (ISSUE 14) is pinned OFF for this row — its whole
    # point is rerouting a slower device tier to host, which would
    # turn this row into a second host measurement and starve the
    # ledger of the device trajectory (and of the very per-bucket
    # device estimates the router seeds from)
    from cometbft_tpu.crypto import dispatch as _dispatch

    def forced_device(fn):
        prior = os.environ.get("CMT_TPU_ROUTE")
        os.environ["CMT_TPU_ROUTE"] = "0"
        _dispatch.reset_for_tests()
        try:
            return fn()
        finally:
            if prior is None:
                os.environ.pop("CMT_TPU_ROUTE", None)
            else:
                os.environ["CMT_TPU_ROUTE"] = prior
            _dispatch.reset_for_tests()

    def micro_device():
        bv = TpuBatchVerifier(device_min_batch=1)
        for m, s in zip(msgs64, sigs64):
            bv.add(pub, m, s)
        ok, _ = bv.verify()
        assert ok

    dt = forced_device(lambda: timed(micro_device))
    record(
        "micro_64sig_device", 64 / dt, "sigs/sec",
        latency_ms=round(dt * 1e3, 2),
    )

    # ---- config 2: VerifyCommit @ 150 validators ---------------------
    t0 = time.time()
    vals150, commit150, bid150 = make_commit_fixture(150)
    log(f"150-val fixture in {time.time() - t0:.1f}s")

    def vc150():
        validation.verify_commit(CHAIN_ID, vals150, bid150, 1, commit150)

    # production routing: the runtime dispatch threshold decides (on a
    # high-RTT link a single 150-sig commit stays on the CPU batch
    # path — types/validation.go:15 shouldBatchVerify semantics)
    dt = timed(vc150)
    record(
        "verify_commit_150", dt * 1e3, "ms",
        sigs_per_sec=round(150 / dt, 1),
    )
    # device-forced variant: kernel+link progress stays visible even
    # while the production router prefers the CPU at this size (cost
    # router pinned off, same rationale as micro_64sig_device)
    prior = os.environ.get("CMT_TPU_DEVICE_MIN_BATCH")
    os.environ["CMT_TPU_DEVICE_MIN_BATCH"] = "1"
    try:
        dt = forced_device(lambda: timed(vc150))
        record(
            "verify_commit_150_device", dt * 1e3, "ms",
            sigs_per_sec=round(150 / dt, 1),
        )
    finally:
        if prior is None:
            del os.environ["CMT_TPU_DEVICE_MIN_BATCH"]
        else:
            os.environ["CMT_TPU_DEVICE_MIN_BATCH"] = prior

    # warm-table variant: the device-forced run above built the
    # 150-val set's comb tables, so PRODUCTION routing now takes the
    # keyed tier even below the generic batch threshold (the
    # keyed-by-default promotion; reason=keyed_warm) — on a no-device
    # box the row honestly records tier=host instead
    dt = timed(vc150)
    record(
        "verify_commit_150_warm", dt * 1e3, "ms",
        sigs_per_sec=round(150 / dt, 1),
    )

    # ---- config 2b: BLS aggregate commit @ 150 validators ------------
    # The side-by-side the ISSUE 13 acceptance pins: the SAME 150-vote
    # commit shape, carried as one BLS aggregate signature instead of
    # 150 ed25519 signatures — one pairing-product check
    # (crypto/bls_dispatch.py, e(agg_pk, H(m)) == e(g1, agg_sig))
    # against verify_commit_150's batch.  timed()'s warmup builds the
    # native lib and warms the aggregate-pubkey LRU, so the measured
    # steady state is the serving-plane shape: repeated commits from a
    # stable validator set, each paying exactly one pairing.
    t0 = time.time()
    vals_agg, commit_agg, bid_agg = make_bls_aggregate_fixture(150)
    log(f"150-val BLS aggregate fixture in {time.time() - t0:.1f}s")

    def vc_agg():
        validation.verify_commit(CHAIN_ID, vals_agg, bid_agg, 1, commit_agg)

    dt = timed(vc_agg)
    record(
        "bls_aggregate_150val", dt * 1e3, "ms",
        sigs_per_sec=round(150 / dt, 1),
        pairing_checks=1,
        baseline="verify_commit_150",
    )

    # ---- config 3: VerifyCommit @ 10k validators ---------------------
    nbig = 1000 if on_cpu else 10_000
    t0 = time.time()
    vals10k, commit10k, bid10k = make_commit_fixture(nbig)
    log(f"{nbig}-val fixture in {time.time() - t0:.1f}s")

    def vc10k():
        validation.verify_commit(CHAIN_ID, vals10k, bid10k, 1, commit10k)

    dt = timed(vc10k)
    record(
        f"verify_commit_{nbig}", dt * 1e3, "ms",
        sigs_per_sec=round(nbig / dt, 1), target_ms=2.0,
    )

    # ---- configs 4+5: pipelined multi-commit throughput --------------
    # The replay planes (light sync, blocksync) verify many independent
    # commits; the node drives them through verify_stream so launches
    # overlap.  Jobs are grouped to fill device batches.
    def stream_config(name, vals, commit, n_commits, modeled):
        from cometbft_tpu.ops import precompute as PR
        from cometbft_tpu.ops.ed25519_verify import (
            verify_arrays_keyed_async,
        )

        nsig = commit.size()
        pub_bytes = [
            vals.get_by_index(i).pub_key.bytes() for i in range(nsig)
        ]
        pubs = np.stack(
            [np.frombuffer(p, dtype=np.uint8) for p in pub_bytes]
        )
        sigs = np.stack(
            [
                np.frombuffer(cs.signature, dtype=np.uint8)
                for cs in commit.signatures
            ]
        )
        msgs = [
            commit.vote_sign_bytes(CHAIN_ID, i) for i in range(nsig)
        ]
        group = max(1, 4096 // nsig)  # commits per launch

        # stream through the per-validator precomputed tables — the
        # same hot path a replaying node gets via the batch seam; the
        # one-time table build happens before the clock starts.
        dispatch = None
        entry = PR.TABLE_CACHE.lookup_or_build(pub_bytes)
        if entry is not None:
            key_ids1 = entry.key_ids(pub_bytes)

            def dispatch(pub, sig, ms, _e=entry, _k=key_ids1):
                k = len(ms) // nsig
                return verify_arrays_keyed_async(
                    _e, np.concatenate([_k] * k), pub, sig, ms
                )

        def jobs():
            done = 0
            while done < n_commits:
                k = min(group, n_commits - done)
                yield (
                    np.concatenate([pubs] * k),
                    np.concatenate([sigs] * k),
                    msgs * k,
                )
                done += k

        t0 = time.perf_counter()
        total = 0
        for res in verify_stream(jobs(), max_in_flight=8,
                                 dispatch=dispatch):
            assert bool(res.all())
            total += len(res)
        dt = time.perf_counter() - t0
        extra = dict(
            commits_per_sec=round(n_commits / dt, 1),
            n_commits_run=n_commits,
            path="keyed" if dispatch is not None else "generic",
            # the stream path dispatches below the verifier seam, so
            # its tier is declared rather than metric-derived
            dispatch_tier="keyed" if dispatch is not None else "generic",
        )
        if modeled != n_commits:
            # only a CPU smoke run extrapolates; a device run measures
            # the full count and carries no modeling caveat
            extra["n_commits_modeled"] = modeled
        record(name, total / dt, "sigs/sec", **extra)

    # full modeled counts on the accelerator — nothing extrapolated
    n4 = 64 if on_cpu else 10_000
    stream_config("light_sync_150val", vals150, commit150, n4, 10_000)
    vals1k, commit1k, bid1k = make_commit_fixture(
        128 if on_cpu else 1000
    )
    n5 = 16 if on_cpu else 1000
    stream_config("blocksync_replay_1kval", vals1k, commit1k, n5, 1000)

    # ---- configs 4b+5b: the same replay workloads through the verify
    # queue (crypto/verify_queue.py) — commits submitted as batched
    # requests, the collector's host prep (prehash + plan/pack)
    # overlapping the launcher's in-flight batch.  The sync stream rows
    # above are the baselines tools/perfdiff.py gates these against;
    # the tier is metric-derived (the queue dispatches through the
    # production verifier seam) and the overlap ratio rides along.
    from cometbft_tpu.crypto import verify_queue as vqmod

    def queue_config(name, vals, commit, n_commits):
        nsig = commit.size()
        pks = [vals.get_by_index(i).pub_key for i in range(nsig)]
        msgs = [
            commit.vote_sign_bytes(CHAIN_ID, i) for i in range(nsig)
        ]
        items = [
            (pk, m, cs.signature)
            for pk, m, cs in zip(pks, msgs, commit.signatures)
        ]
        # cache OFF: every submitted commit re-verifies honestly;
        # max_batch = one commit per buffer so the measured shape IS
        # the double-buffered pipeline
        q = vqmod.VerifyQueue(use_cache=False, max_batch=nsig)
        q.start()
        try:
            t0 = time.perf_counter()
            futs = []
            for _ in range(n_commits):
                futs.extend(q.submit_many(items))
            assert all(f.result(600) for f in futs), (
                "queue bench sigs must verify"
            )
            dt = time.perf_counter() - t0
            overlap = q.stats()["overlap_ratio"]
        finally:
            q.stop()
        record(
            name, nsig * n_commits / dt, "sigs/sec",
            commits_per_sec=round(n_commits / dt, 1),
            n_commits_run=n_commits,
            overlap_ratio=overlap,
        )

    queue_config("light_sync_150val_pipelined", vals150, commit150, n4)
    queue_config(
        "blocksync_replay_1kval_pipelined", vals1k, commit1k, n5
    )

    # ---- config 4c: dispatch_shape_mix — static walk vs cost-ordered
    # routing on the SAME mixed-shape workload (ISSUE 14).  Interleaved
    # 64-sig micro-batches and 150-sig commit batches, device-forced
    # (device_min_batch=1, the *_device convention) so the static walk
    # pays the device tier for every batch; the cost arm seeds the
    # TierCostModel from THIS run's ledger rows (configs 1/2/4b above
    # appended host + device measurements at both shape buckets
    # moments ago) and routes each shape by measured throughput.  On a
    # box where the ledger contradicts the static order (r05: host
    # beats the device path) the cost arm reroutes and wins; on a box
    # where the device genuinely leads, the arms converge — parity,
    # not regression.  Both rows land in the ledger; perfdiff gates
    # the cost row run over run.
    def shape_mix_batches():
        wide_pks = [
            vals150.get_by_index(i).pub_key for i in range(150)
        ]
        wide_msgs = [
            commit150.vote_sign_bytes(CHAIN_ID, i) for i in range(150)
        ]
        wide_sigs = [cs.signature for cs in commit150.signatures]
        batches = []
        for r in range(4):
            small = TpuBatchVerifier(device_min_batch=1)
            for m, s in zip(msgs64, sigs64):
                small.add(pub, m, s)
            wide = TpuBatchVerifier(device_min_batch=1)
            for pk, m, s in zip(wide_pks, wide_msgs, wide_sigs):
                wide.add(pk, m, s)
            batches += [small, wide]
        return batches

    def shape_mix_arm(route_on: bool):
        os.environ["CMT_TPU_ROUTE"] = "1" if route_on else "0"
        _dispatch.reset_for_tests()  # fresh ladder + (re-)seeded model
        batches = shape_mix_batches()  # signing outside the clock
        nsigs = sum(len(b._pubs) for b in batches)
        tiers_used: dict[str, int] = {}
        t0 = time.perf_counter()
        for bv in batches:
            ok, _ = bv.verify()
            assert ok, "shape-mix sigs must verify"
            tiers_used[bv._last_tier] = (
                tiers_used.get(bv._last_tier, 0) + 1
            )
        dt = time.perf_counter() - t0
        snap = _dispatch.LADDER.cost_snapshot()
        reorders = sum(o["reorders"] for o in snap["orders"])
        return nsigs / dt, tiers_used, reorders

    prior_route = os.environ.get("CMT_TPU_ROUTE")
    try:
        static_rate, static_tiers, _ = shape_mix_arm(False)
        record(
            "dispatch_shape_mix_static", static_rate, "sigs/sec",
            shapes=[64, 150], batches_per_shape=4,
            tiers_used=static_tiers, route="static",
            # a mixed-workload rate is not single-batch tier
            # throughput: never a routing seed, and dispatch_tier=None
            # suppresses record()'s majority-tier auto-stamp so the
            # tier-level measured_tier_throughput map (last row per
            # tier wins) keeps the tier's genuine measurement instead
            # of this interleaved aggregate
            route_seed=False,
            dispatch_tier=None,
        )
        cost_rate, cost_tiers, reorders = shape_mix_arm(True)
        record(
            "dispatch_shape_mix", cost_rate, "sigs/sec",
            shapes=[64, 150], batches_per_shape=4,
            tiers_used=cost_tiers, route="cost",
            route_reorders=reorders,
            baseline="dispatch_shape_mix_static",
            speedup_vs_static=round(cost_rate / static_rate, 2),
            route_seed=False,
            dispatch_tier=None,
        )
    finally:
        if prior_route is None:
            os.environ.pop("CMT_TPU_ROUTE", None)
        else:
            os.environ["CMT_TPU_ROUTE"] = prior_route
        _dispatch.reset_for_tests()

    # ---- configs 6a-c: device-batched CheckTx admission (ISSUE 10) ---
    # The ingest plane end to end: signed-envelope txs through
    # CListMempool.check_tx, once with the VerifyQueue OFF (the inline
    # host baseline — one pubkey.verify_signature per tx) and once
    # with the queue's ingest micro-batcher coalescing concurrent
    # admissions into DispatchLadder launches.  perfdiff gates
    # checktx_batched against checktx_host from the ledger; the
    # sustained row records what the closed-loop harness achieves at
    # saturation with admission latency percentiles.
    from cometbft_tpu.abci.types import CheckTxResponse as _CTResp
    from cometbft_tpu.loadtime import SustainedLoader
    from cometbft_tpu.mempool import CListMempool
    from cometbft_tpu.mempool import ingest as mingest

    class _NullProxy:
        """Admission-only app: the rows measure the mempool's own
        plane (cache, signature, bookkeeping), not kvstore parsing."""

        def check_tx(self, req):
            return _CTResp(gas_wanted=1)

    ct_privs = [
        ed.priv_key_from_secret(b"bench-checktx-%d" % i)
        for i in range(16)
    ]

    def signed_txs(n, tag):
        return [
            mingest.make_signed_tx(
                ct_privs[i % len(ct_privs)], b"%s-%d=v" % (tag, i)
            )
            for i in range(n)
        ]

    def fresh_mempool(capacity):
        return CListMempool(
            _NullProxy(), size=capacity + 16,
            cache_size=2 * capacity + 32,
        )

    # 6a: inline host baseline (queue not installed)
    n_host = 64 if on_cpu else 4096
    host_txs = signed_txs(n_host, b"host")
    mp = fresh_mempool(n_host)
    t0 = time.perf_counter()
    for txb in host_txs:
        mp.check_tx(txb)
    dt = time.perf_counter() - t0
    record(
        "checktx_host", n_host / dt, "tx/sec",
        n_txs=n_host, latency_ms=round(dt / n_host * 1e3, 3),
        dispatch="inline pubkey.verify_signature per tx",
    )

    # 6b: the ingest lane — concurrent submitters, coalesced launches.
    # Each submitter blocks on its own CheckTx (the RPC thread shape),
    # so the achievable coalesce width IS the submitter count; a 25 ms
    # accumulation window lets batches fill to where the per-launch
    # seam cost amortizes (the production 5 ms default favors latency;
    # the row records the knob it measured)
    n_batched = 1024 if on_cpu else 16384
    ct_wait_ms = 25
    batched_txs = signed_txs(n_batched, b"batched")
    mp = fresh_mempool(n_batched)
    q = vqmod.VerifyQueue(checktx_wait_ms=ct_wait_ms)
    q.start()
    vqmod.install_queue(q)
    try:
        import queue as _queue

        work: _queue.SimpleQueue = _queue.SimpleQueue()
        for txb in batched_txs:
            work.put(txb)
        errors: list = []

        def drain():
            while True:
                try:
                    txb = work.get_nowait()
                except _queue.Empty:
                    return
                try:
                    mp.check_tx(txb)
                except Exception as exc:  # noqa: BLE001
                    errors.append(exc)

        nworkers = 128
        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=drain, daemon=True)
            for _ in range(nworkers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        assert not errors, f"checktx_batched rejected txs: {errors[:3]}"
        assert mp.size() == n_batched
        qstats = q.stats()
    finally:
        q.stop()
    record(
        "checktx_batched", n_batched / dt, "tx/sec",
        n_txs=n_batched, workers=nworkers,
        checktx_wait_ms=ct_wait_ms,
        ingest_batches=qstats["launched_batches"],
        avg_ingest_batch=round(
            qstats["launched_sigs"]
            / max(1, qstats["launched_batches"]), 1,
        ),
    )

    # 6c: the closed-loop sustained harness at saturation
    mp = fresh_mempool(1 << 20)
    q = vqmod.VerifyQueue()
    q.start()
    vqmod.install_queue(q)
    try:
        loader = SustainedLoader(
            submit=mp.check_tx, workers=8, signed=True,
        )
        rep = loader.run([(0, 2.0 if on_cpu else 10.0)])
    finally:
        q.stop()
    record(
        "checktx_sustained", rep["accepted_per_sec"], "tx/sec",
        shed=rep["shed"], errors=rep["errors"],
        latency_p50_ms=round(rep["latency_p50_s"] * 1e3, 2),
        latency_p95_ms=round(rep["latency_p95_s"] * 1e3, 2),
    )

    # ---- config 7: the light-client serving plane at 10k clients -----
    # The ISSUE 13 heavy-traffic scenario end to end: a header chain
    # served through light/serve.LightHeaderServer with the verify
    # queue's light_client lane underneath (micro-batched cross-client
    # coalescing) and the trust-period-aware header cache in front,
    # driven by loadtime.LightSyncLoader simulating 10k client
    # sessions.  The first pass verifies every header (launches); the
    # sustained phase measures the serving shape — repeat syncs riding
    # the header cache — with p50/p95 per request and headers/s.
    from cometbft_tpu.light.provider import Provider as _Provider
    from cometbft_tpu.light.serve import LightHeaderServer
    from cometbft_tpu.loadtime import LightSyncLoader
    from cometbft_tpu.metrics import LightMetrics, install_light_metrics
    from cometbft_tpu.types.block import (
        BLOCK_ID_FLAG_COMMIT as _FLAG_COMMIT,
        BlockID as _BlockID,
        Commit as _Commit,
        CommitSig as _CommitSig,
        Header as _Header,
        PartSetHeader as _PSH,
    )
    from cometbft_tpu.types.light_block import (
        LightBlock as _LightBlock,
        SignedHeader as _SignedHeader,
    )
    from cometbft_tpu.types import canonical as _canonical
    from cometbft_tpu.types.validator import (
        Validator as _Validator,
        ValidatorSet as _ValidatorSet,
    )

    lm = LightMetrics(Registry())
    install_light_metrics(lm)
    n_heights = 6 if on_cpu else 32
    n_lvals = 20 if on_cpu else 150
    t0 = time.time()
    lkeys = [
        ed.priv_key_from_secret(b"light%d" % i) for i in range(n_lvals)
    ]
    lvals = _ValidatorSet([_Validator(k.pub_key(), 10) for k in lkeys])
    l_by_addr = {k.pub_key().address(): k for k in lkeys}
    l_ordered = [l_by_addr[v.address] for v in lvals.validators]
    lvh = lvals.hash()
    now_ns_ = time.time_ns()
    lblocks = {}
    for hh in range(1, n_heights + 1):
        hdr = _Header(
            chain_id=CHAIN_ID, height=hh,
            time_ns=now_ns_ - (n_heights - hh) * 1_000_000_000,
            validators_hash=lvh, next_validators_hash=lvh,
            proposer_address=l_ordered[0].pub_key().address(),
        )
        hhash = hdr.hash()
        lbid = _BlockID(
            hash=hhash, part_set_header=_PSH(total=1, hash=hhash[:32])
        )
        lsigs = []
        for i, k in enumerate(l_ordered):
            ts = now_ns_ + i
            m = _canonical.vote_sign_bytes(
                CHAIN_ID, _canonical.PRECOMMIT_TYPE, hh, 0, lbid, ts
            )
            lsigs.append(
                _CommitSig(
                    block_id_flag=_FLAG_COMMIT,
                    validator_address=k.pub_key().address(),
                    timestamp_ns=ts, signature=k.sign(m),
                )
            )
        lblocks[hh] = _LightBlock(
            signed_header=_SignedHeader(
                header=hdr,
                commit=_Commit(
                    height=hh, round=0, block_id=lbid,
                    signatures=tuple(lsigs),
                ),
            ),
            validator_set=lvals,
        )
    log(
        f"light chain fixture ({n_heights}h x {n_lvals}v) "
        f"in {time.time() - t0:.1f}s"
    )

    class _FixtureProvider(_Provider):
        def chain_id(self):
            return CHAIN_ID

        def light_block(self, height):
            return lblocks[height]

    q = vqmod.VerifyQueue(light_wait_ms=3)
    q.start()
    vqmod.install_queue(q)
    try:
        server = LightHeaderServer(CHAIN_ID, _FixtureProvider())
        loader = LightSyncLoader(
            sync=server.sync_range, clients=10_000, workers=16,
            span=4, chain_from=1, chain_to=n_heights,
        )
        rep = loader.run(3.0 if on_cpu else 10.0)
        qstats = q.stats()
    finally:
        q.stop()
    assert rep["errors"] == 0, (
        f"light_serve_sustained loader errors: {rep['errors']}"
    )
    record(
        "light_serve_sustained", rep["headers_per_sec"], "headers/sec",
        clients=rep["clients"], workers=rep["workers"],
        requests=rep["requests"], errors=rep["errors"],
        latency_p50_ms=round(rep["latency_p50_s"] * 1e3, 3),
        latency_p95_ms=round(rep["latency_p95_s"] * 1e3, 3),
        cache_hit_rate=rep["cache_hit_rate"],
        light_lane_submitted=qstats["submitted"]["light_client"],
        n_heights=n_heights, n_validators=n_lvals,
    )

    # ---- config 5: mixed ed25519 + bls12381 mega-commit --------------
    # One commit whose validators mix both key types; verify_commit's
    # per-key-type grouping sends ed25519 votes to the batch kernel and
    # BLS votes through the RLC multi-pairing (one shared Miller loop).
    # The BLS plane is host-side Python (tower pairing,
    # crypto/bls12381.py), so this measures the real deliverable — no
    # extrapolation: ONE full verification is timed.
    total_mixed = 100 if on_cpu else 10_000
    n_bls = min(
        total_mixed,
        int(os.environ.get("CMT_BENCH_BLS_N", "16" if on_cpu else "1000")),
    )
    n_ed = total_mixed - n_bls
    t0 = time.time()
    vals_mixed, commit_mixed, bid_mixed = make_mixed_commit_fixture(
        n_ed, n_bls
    )
    log(
        f"mixed fixture ({n_ed} ed25519 + {n_bls} bls) "
        f"in {time.time() - t0:.1f}s"
    )
    t0 = time.perf_counter()
    validation.verify_commit(
        CHAIN_ID, vals_mixed, bid_mixed, 1, commit_mixed
    )
    dt = time.perf_counter() - t0
    record(
        "mixed_megacommit", dt * 1e3, "ms",
        n_ed25519=n_ed, n_bls=n_bls,
        sigs_per_sec=round((n_ed + n_bls) / dt, 1),
    )

    checkpoint()
    if prof is not None:
        prof.stop()
    log("wrote BENCH_ALL.json")


if __name__ == "__main__":
    main()
