"""Micro-benchmark harness for the host-side subsystems
(BASELINE.md "Benchmark harnesses with no published values":
crypto/ed25519/bench_test.go, merkle/tmhash bench_test.go,
mempool/bench_test.go + cache_bench_test.go, store/bench_test.go,
txindex kv_bench_test.go, pubsub query/bench_test.go,
pex/bench_test.go).

Prints one JSON line per benchmark and writes BENCH_MICRO.json.
These are the CPU planes — the device plane is bench.py/bench_all.py.

    python tools/bench_micro.py            # all
    python tools/bench_micro.py mempool    # name filter
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULTS: list[dict] = []


def bench(name: str, fn, n_ops: int, repeats: int = 3) -> None:
    if len(sys.argv) > 1 and sys.argv[1] not in name:
        return
    fn()  # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    row = {
        "bench": name,
        "ops": n_ops,
        "ns_per_op": round(best / n_ops * 1e9, 1),
        "ops_per_sec": round(n_ops / best, 1),
    }
    RESULTS.append(row)
    print(json.dumps(row), flush=True)


def main() -> None:
    import numpy as np

    rng = np.random.RandomState(11)

    # ---- crypto: ed25519 sign/verify/batch (bench_test.go:14-50) -----
    from cometbft_tpu.crypto import ed25519 as ed

    priv = ed.priv_key_from_secret(b"bench")
    pub = priv.pub_key()
    msg = rng.bytes(120)
    sig = priv.sign(msg)
    bench("crypto/ed25519/sign", lambda: [priv.sign(msg) for _ in range(64)], 64)
    bench(
        "crypto/ed25519/verify_single",
        lambda: [pub.verify_signature(msg, sig) for _ in range(64)],
        64,
    )
    msgs64 = [rng.bytes(120) for _ in range(64)]
    sigs64 = [priv.sign(m) for m in msgs64]

    def batch64():
        bv = ed.CpuBatchVerifier()
        for m, s in zip(msgs64, sigs64):
            bv.add(pub, m, s)
        ok, _ = bv.verify()
        assert ok

    bench("crypto/ed25519/cpu_batch_verify_64", batch64, 64)

    # ---- merkle + tmhash (merkle/bench_test.go) ----------------------
    from cometbft_tpu.crypto import merkle, tmhash

    items = [rng.bytes(64) for _ in range(1024)]
    bench(
        "crypto/merkle/root_1024x64B",
        lambda: merkle.hash_from_byte_slices(items),
        1024,
    )
    root, proofs = merkle.proofs_from_byte_slices(items)
    bench(
        "crypto/merkle/verify_proof",
        lambda: [
            proofs[i].verify(root, items[i]) for i in range(0, 1024, 8)
        ],
        128,
    )
    blob = rng.bytes(1024)
    bench(
        "crypto/tmhash/sum_1KB",
        lambda: [tmhash.sum256(blob) for _ in range(1000)],
        1000,
    )

    # ---- mempool CheckTx + cache (mempool/bench_test.go) -------------
    from cometbft_tpu.abci.kvstore import KVStoreApp
    from cometbft_tpu.mempool import CListMempool, TxCache
    from cometbft_tpu.proxy import AppConns, local_client_creator

    proxy = AppConns(local_client_creator(KVStoreApp()))
    proxy.start()
    mp = CListMempool(proxy.mempool, height=1)
    txs = [b"k%d=v%d" % (i, i) for i in range(2000)]

    def checktx():
        for tx in txs:
            mp.check_tx(tx)
        mp.flush()

    bench("mempool/check_tx_2000", checktx, 2000)
    cache = TxCache(10_000)

    def cache_push():
        for tx in txs:
            cache.push(tx)
        for tx in txs:
            cache.push(tx)  # hit path

    bench("mempool/cache_push_4000", cache_push, 4000)
    proxy.stop()

    # ---- block store (store/bench_test.go) ---------------------------
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.utils.db import MemDB
    from tests.helpers import make_block_id, make_commit, make_val_set

    from cometbft_tpu.types.block import Block, Data, Header
    from cometbft_tpu.types.params import BLOCK_PART_SIZE_BYTES

    vals, keys = make_val_set(4)
    bid = make_block_id()
    commit = make_commit(vals, keys, bid)
    header = Header(
        chain_id="bench", height=1, validators_hash=vals.hash(),
        next_validators_hash=vals.hash(),
        proposer_address=vals.validators[0].address,
    )
    block = Block(
        header=header,
        data=Data(txs=tuple(rng.bytes(256) for _ in range(64))),
        last_commit=commit,
    )
    def save_load():
        store = BlockStore(MemDB())
        for h in range(1, 33):
            blk = Block(
                header=Header(
                    chain_id="bench", height=h,
                    validators_hash=vals.hash(),
                    next_validators_hash=vals.hash(),
                    proposer_address=vals.validators[0].address,
                ),
                data=block.data,
                last_commit=commit,
            )
            ps = blk.make_part_set(BLOCK_PART_SIZE_BYTES)
            store.save_block(blk, ps, commit)
            store.load_block(h)

    bench("store/save_load_32_blocks_64tx", save_load, 64)

    # ---- tx indexer (txindex/kv_bench_test.go) -----------------------
    from cometbft_tpu.abci.types import ExecTxResult
    from cometbft_tpu.state.txindex import TxIndexer

    idx = TxIndexer(MemDB())

    def index_txs():
        for i, tx in enumerate(txs[:500]):
            idx.index(1, i, tx, ExecTxResult(code=0))

    bench("txindex/index_500", index_txs, 500)

    # ---- pubsub query DSL (pubsub/query/bench_test.go) ---------------
    from cometbft_tpu.utils.pubsub import Query

    q = Query.parse(
        "tm.event = 'Tx' AND tx.height > 5 AND transfer.amount > 100"
    )
    events = {
        "tm.event": ["Tx"],
        "tx.height": ["12"],
        "transfer.amount": ["250"],
    }
    bench(
        "pubsub/query_match",
        lambda: [q.matches(events) for _ in range(10_000)],
        10_000,
    )
    bench(
        "pubsub/query_parse",
        lambda: [
            Query.parse("tm.event = 'NewBlock' AND block.height > 1")
            for _ in range(2000)
        ],
        2000,
    )

    # ---- pex addrbook (pex/bench_test.go) ----------------------------
    from cometbft_tpu.p2p.netaddr import NetAddress
    from cometbft_tpu.p2p.pex.addrbook import AddrBook

    book = AddrBook(file_path="", strict=False)
    addrs = [
        NetAddress(
            id=("%040x" % i),
            host=f"10.{i >> 8 & 255}.{i & 255}.{(i * 7) % 255 + 1}",
            port=26656,
        )
        for i in range(1000)
    ]
    src = NetAddress(id="b" * 40, host="1.2.3.4", port=26656)

    def book_ops():
        for a in addrs:
            book.add_address(a, src)
        for _ in range(1000):
            book.pick_address(30)

    bench("pex/addrbook_add_pick_1000", book_ops, 2000)

    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_MICRO.json",
    )
    with open(out, "w") as f:
        json.dump({"results": RESULTS}, f, indent=1)
    print(f"wrote {out}", file=sys.stderr)


if __name__ == "__main__":
    main()
