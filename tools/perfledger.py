"""perfledger: ONE merged record of every perf measurement this repo
has ever taken, with provenance.

The perf trajectory (22k -> 36k -> 103k sigs/s) lived scattered across
nine BENCH_*/MULTICHIP_* files plus docs/data/kernel_ab_*.json, each
with its own shape — comparing two rounds meant re-reading five
formats by hand, and nothing could gate a regression.  This tool
normalizes all of them into ``docs/data/perf_ledger.json``::

    {"schema": 1,
     "entries": [{"config", "value", "unit", "source", "measured",
                  "round"?, "dispatch_tier"?, "jit_compiles"?,
                  "steady_retraces"?, "platform"?, ...}, ...]}

Each entry is one measured point: what was measured (``config``), the
number (``value``/``unit``), where it came from (``source`` file or
tool), when, and the device-path provenance that makes the number
interpretable — the dispatch tier that actually ran, per-seam jit
compile counts, and steady-state retraces (a nonzero retrace means the
"steady state" wasn't).

Writers:
- ``bench.py`` and ``bench_all.py`` append every measured row
  automatically (source ``bench`` / ``bench_all``).
- ``tools/device_campaign.py`` appends each campaign step (replacing
  its ad-hoc MULTICHIP scraping as the merged store of record).
- ``python tools/perfledger.py --harvest`` back-fills from the
  historical BENCH_*/MULTICHIP_*/kernel_ab files.

Readers: ``tools/perfdiff.py`` (the regression gate, ``make
perf-gate``) and the ``/debug/perf`` route, which serves the ledger
tail next to live tier health (cometbft_tpu/crypto/health.py).

Dedup key: (source, config, round, measured) — re-running a harvest
or a bench replaces its own point instead of duplicating it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SCHEMA = 1

#: provenance keys carried through from source rows verbatim when
#: present — everything a reader needs to interpret the number
PROVENANCE_KEYS = (
    "dispatch_tier", "dispatch_tiers", "jit_compiles", "steady_retraces",
    "warmup_compiles", "platform", "ndev", "per_chip_sigs_per_sec",
    "sigs_per_sec_per_chip", "sigs_per_sec", "latency_ms",
    "commits_per_sec", "nval", "batch", "note", "path", "vs_baseline",
    "target_ms", "rc",
    # attribution plane: the row's top-k leaf-frame hotspots sampled
    # while it was measured (utils/profiler.py) — what the number was
    # spending its host CPU on
    "hotspots",
)


def default_path() -> str:
    from cometbft_tpu.crypto.health import perf_ledger_path

    return perf_ledger_path()


def load(path: str | None = None) -> dict:
    path = path or default_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"schema": SCHEMA, "entries": []}
    doc.setdefault("schema", SCHEMA)
    doc.setdefault("entries", [])
    return doc


def entry_key(e: dict) -> tuple:
    return (
        e.get("source"), e.get("config"), e.get("round"), e.get("measured")
    )


def append(entries: list[dict], path: str | None = None) -> dict:
    """Atomically merge ``entries`` into the ledger.  A same-key entry
    REPLACES its predecessor and moves to the END of the list — append
    order IS recency (perfdiff's latest-per-config and the
    /debug/perf ledger tail both read positionally, so an in-place
    replace would leave a stale harvest entry looking newest)."""
    path = path or default_path()
    doc = load(path)
    merged: dict[tuple, dict] = {}  # insertion-ordered: last write last
    for e in entries:
        merged[entry_key(e)] = e
    doc["entries"] = [
        e for e in doc["entries"] if entry_key(e) not in merged
    ] + list(merged.values())
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)
    return doc


def tail(n: int = 10, path: str | None = None) -> list[dict]:
    return load(path)["entries"][-n:]


def make_entry(
    config: str, value, unit: str, source: str, row: dict | None = None,
    **extra,
) -> dict:
    """Normalize one measured point; ``row`` contributes whatever
    PROVENANCE_KEYS it carries."""
    e: dict = {"config": config, "value": value, "unit": unit,
               "source": source}
    row = row or {}
    e["measured"] = (
        extra.pop("measured", None)
        or row.get("measured")
        or row.get("measured_at")
    )
    for k in PROVENANCE_KEYS:
        if k in row and k not in e:
            e[k] = row[k]
    e.update(extra)
    return e


# -- bench-side helpers (called by bench.py / bench_all.py) ---------------

def headline_entry(result: dict, source: str = "bench") -> dict:
    """bench.py's headline JSON -> one ledger entry (provenance: tier
    and compile counts when the device path ran)."""
    e = make_entry(
        result.get("metric", "ed25519_batch_verify_throughput"),
        result.get("value"), result.get("unit", "sigs/sec"), source,
        row=result,
    )
    for k in ("generic_sigs_per_sec", "keyed_sigs_per_sec",
              "keyed_cols_impl", "partial", "error"):
        if k in result:
            e[k] = result[k]
    return e


def append_rows(
    rows: list[dict], source: str, path: str | None = None,
) -> None:
    """BENCH_ALL-shaped rows (config/value/unit + extras) -> ledger.
    Best-effort by design: the ledger must never fail a bench."""
    try:
        append(
            [
                make_entry(
                    r.get("config", r.get("metric", "unknown")),
                    r.get("value"), r.get("unit", ""), source, row=r,
                )
                for r in rows
            ],
            path,
        )
    except Exception as exc:  # noqa: BLE001 — provenance only
        print(f"perfledger append failed (ignored): {exc}",
              file=sys.stderr)


# -- the historical harvest ----------------------------------------------

def _read(path: str):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def harvest(repo: str = REPO) -> list[dict]:
    """Normalize every historical BENCH_*/MULTICHIP_*/kernel_ab file
    into ledger entries (idempotent: stable keys, so re-harvesting
    replaces rather than duplicates)."""
    entries: list[dict] = []

    # BENCH_rNN.json: driver transcripts with a parsed headline
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        doc = _read(path)
        if not doc:
            continue
        rnd = doc.get("n")
        parsed = doc.get("parsed") or {}
        if "value" in parsed:
            entries.append(
                make_entry(
                    parsed.get("metric", "ed25519_batch_verify_throughput"),
                    parsed.get("value"), parsed.get("unit", "sigs/sec"),
                    os.path.basename(path), row=parsed, round=rnd,
                )
            )
    # MULTICHIP_rNN.json: dryrun provenance — device count per round
    # (0 recorded honestly for the rounds the tunnel was down)
    for path in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json"))):
        doc = _read(path)
        if not doc:
            continue
        m = re.search(r"MULTICHIP_r(\d+)", path)
        rnd = int(m.group(1)) if m else None
        entries.append(
            make_entry(
                "multichip_dryrun",
                doc.get("n_devices", 0) if doc.get("ok") else 0,
                "devices", os.path.basename(path),
                round=rnd, rc=doc.get("rc"),
            )
        )
    # BENCH_ALL.json / MULTICHIP_KEYED.json: config rows
    for name in ("BENCH_ALL.json", "MULTICHIP_KEYED.json"):
        doc = _read(os.path.join(repo, name))
        if not doc:
            continue
        for row in doc.get("results", []):
            entries.append(
                make_entry(
                    row.get("config", row.get("metric", "unknown")),
                    row.get("value"), row.get("unit", ""), name, row=row,
                )
            )
    # BENCH_MICRO.json: host micro-bench rows
    doc = _read(os.path.join(repo, "BENCH_MICRO.json"))
    if doc:
        for row in doc.get("results", []):
            entries.append(
                make_entry(
                    row.get("bench", "unknown"), row.get("ops_per_sec"),
                    "ops/sec", "BENCH_MICRO.json",
                    ns_per_op=row.get("ns_per_op"),
                )
            )
    # docs/data/kernel_ab_*.json: campaign step results
    for path in sorted(
        glob.glob(os.path.join(repo, "docs", "data", "kernel_ab_*.json"))
    ):
        doc = _read(path)
        if not doc:
            continue
        for step, row in (doc.get("results") or {}).items():
            if not isinstance(row, dict):
                continue
            value = row.get("sigs_per_sec_device") or row.get(
                "sigs_per_sec_aggregate"
            )
            if value is None:
                continue
            entries.append(
                make_entry(
                    step, value, "sigs/sec", os.path.basename(path),
                    row=row,
                    measured=row.get("measured_at"),
                )
            )
    return entries


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--path", help="ledger file (default: "
                    "docs/data/perf_ledger.json / CMT_TPU_PERF_LEDGER)")
    ap.add_argument("--harvest", action="store_true",
                    help="merge the historical BENCH_*/MULTICHIP_* "
                    "files into the ledger")
    ap.add_argument("--tail", type=int, metavar="N",
                    help="print the last N entries")
    args = ap.parse_args(argv)
    path = args.path or default_path()
    if args.harvest:
        doc = append(harvest(), path)
        print(f"perfledger: {len(doc['entries'])} entries in {path}",
              file=sys.stderr)
    if args.tail:
        print(json.dumps(tail(args.tail, path), indent=1))
    if not args.harvest and not args.tail:
        doc = load(path)
        print(f"perfledger: {len(doc['entries'])} entries in {path} "
              "(use --harvest / --tail N)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
