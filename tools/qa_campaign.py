"""QA macro campaign: saturation sweep + latency CDF + resource
envelope + per-component CPU profile, as a reproducible artifact.

The reference's performance claims are a written methodology with
published numbers (docs/references/qa/CometBFT-QA-v1.md:137 — the
200-node saturation point at 400 tx/s of 1 KB txs, latency CDFs, and
resource envelopes).  This driver produces the same artifact shape for
this framework at localnet scale:

    python tools/qa_campaign.py                      # full sweep
    python tools/qa_campaign.py --rates 100,200      # subset
    python tools/qa_campaign.py --profile --rates 400  # + cProfile

Per offered rate it runs a FRESH 4-validator localnet, drives the
loadtime Loader for --duration seconds, and records committed tx/s,
latency percentiles (from tx-embedded timestamps via the loadtime
reporter), block cadence, and the per-node RSS envelope sampled during
load.  With --profile, node0 runs under cProfile and the dump is
aggregated into a per-component CPU breakdown (consensus / abci+codec /
p2p+frames / store / rpc / crypto).

Writes docs/qa/data/qa_localnet_r05.json incrementally (one entry per
rate, so a killed sweep keeps what it measured).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "docs", "qa", "data", "qa_localnet_r05.json")
BASE_PORT = 28300
N_NODES = 4

#: repo-module prefixes -> report component (profile aggregation)
COMPONENTS = {
    "cometbft_tpu/consensus": "consensus",
    "cometbft_tpu/abci": "abci_codec",
    "cometbft_tpu/proxy": "abci_codec",
    "cometbft_tpu/p2p": "p2p_frames",
    "cometbft_tpu/store": "storage",
    "cometbft_tpu/state": "storage",
    "cometbft_tpu/wal": "storage",
    "cometbft_tpu/utils/db": "storage",
    "cometbft_tpu/rpc": "rpc",
    "cometbft_tpu/crypto": "crypto",
    "cometbft_tpu/ops": "crypto",
    "cometbft_tpu/mempool": "mempool",
    "cometbft_tpu/types": "types_hashing",
}


def log(msg: str) -> None:
    print(f"[qa] {msg}", file=sys.stderr, flush=True)


def _rpc_port(i: int) -> int:
    return BASE_PORT + 2 * i + 1


def _height(port: int) -> int:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/status", timeout=3
    ) as resp:
        return int(
            json.load(resp)["result"]["sync_info"]["latest_block_height"]
        )


def _node_env() -> dict:
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        CMT_TPU_DISABLE_DEVICE_VERIFY="1",
    )
    from cometbft_tpu.utils.device_env import scrub_plugin_env

    scrub_plugin_env(env)
    return env


class ResourceSampler(threading.Thread):
    """Samples VmRSS and cumulative CPU (utime+stime) of the node pids
    every couple of seconds — the CPU series turns "the localnet is
    slower than the reference's 200-node testnet" into a measurable
    statement about how much of the single core each node got."""

    CLK = os.sysconf("SC_CLK_TCK")

    def __init__(self, pids: list[int], period: float = 2.0):
        super().__init__(daemon=True)
        self.pids = pids
        self.period = period
        self.samples: dict[int, list[int]] = {p: [] for p in pids}
        self.cpu0: dict[int, float] = {}
        self.cpu1: dict[int, float] = {}
        self.t0 = time.monotonic()
        # NB: must not be named _stop — that shadows Thread._stop,
        # which join() calls internally
        self._halt = threading.Event()

    def _cpu_s(self, pid: int) -> float | None:
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().rsplit(")", 1)[1].split()
            # fields 14/15 (1-based utime/stime) land at 11/12 here
            return (int(parts[11]) + int(parts[12])) / self.CLK
        except (OSError, IndexError, ValueError):
            return None

    def run(self) -> None:
        for pid in self.pids:
            c = self._cpu_s(pid)
            if c is not None:
                self.cpu0[pid] = c
        while not self._halt.wait(self.period):
            for pid in self.pids:
                try:
                    with open(f"/proc/{pid}/status") as f:
                        for line in f:
                            if line.startswith("VmRSS:"):
                                kb = int(line.split()[1])
                                self.samples[pid].append(kb)
                                break
                except OSError:
                    pass
                c = self._cpu_s(pid)
                if c is not None:
                    self.cpu1[pid] = c

    def stop(self) -> dict:
        self._halt.set()
        self.join(timeout=5)
        wall = max(time.monotonic() - self.t0, 1e-9)
        flat = [s for per in self.samples.values() for s in per]
        per_node_peak = [max(s) if s else 0 for s in self.samples.values()]
        cpu_per_node = [
            round(
                (self.cpu1.get(p, self.cpu0.get(p, 0.0))
                 - self.cpu0.get(p, 0.0)) / wall,
                3,
            )
            for p in self.pids
        ]
        return {
            "rss_peak_mb": round(max(flat) / 1024, 1) if flat else None,
            "rss_mean_mb": round(
                sum(flat) / len(flat) / 1024, 1
            ) if flat else None,
            "rss_peak_per_node_mb": [
                round(p / 1024, 1) for p in per_node_peak
            ],
            "cpu_cores_per_node": cpu_per_node,
            "cpu_cores_total": round(sum(cpu_per_node), 3),
        }


def aggregate_profile(pstats_path: str) -> dict:
    """cProfile dump -> per-component tottime shares."""
    import pstats

    st = pstats.Stats(pstats_path)
    total = 0.0
    by_comp: dict[str, float] = {}
    for (fname, _lineno, _fn), (
        _cc, _nc, tottime, _cum, _callers
    ) in st.stats.items():
        total += tottime
        comp = "other"
        norm = fname.replace("\\", "/")
        for prefix, name in COMPONENTS.items():
            if prefix in norm:
                comp = name
                break
        else:
            if "/python3" in norm or norm.startswith("<"):
                comp = "stdlib_interp"
        by_comp[comp] = by_comp.get(comp, 0.0) + tottime
    shares = {
        k: round(v / total, 4)
        for k, v in sorted(by_comp.items(), key=lambda kv: -kv[1])
    }
    return {"total_cpu_s": round(total, 1), "tottime_share": shares}


def run_rate(
    rate: int, duration: float, size: int, connections: int,
    profile: bool,
) -> dict:
    env = _node_env()
    root = tempfile.mkdtemp(prefix=f"cmt-qa-{rate}-")
    subprocess.run(
        [
            sys.executable, "-m", "cometbft_tpu", "testnet",
            "--v", str(N_NODES), "--o", root,
            "--chain-id", "qa-chain",
            "--starting-port", str(BASE_PORT),
        ],
        env=env, check=True, capture_output=True, cwd=REPO,
    )
    procs = []
    prof_path = os.path.join(root, "node0.pstats")
    for i in range(N_NODES):
        argv = [sys.executable]
        if profile and i == 0:
            argv += ["-m", "cProfile", "-o", prof_path]
            # cProfile -o + -m cometbft_tpu: profile the module run
            argv += [
                os.path.join(REPO, "cometbft_tpu", "__main__.py"),
            ]
        else:
            argv += ["-m", "cometbft_tpu"]
        argv += ["--home", os.path.join(root, f"node{i}"), "start"]
        logf = open(os.path.join(root, f"node{i}.log"), "ab", buffering=0)
        procs.append(
            subprocess.Popen(
                argv, env=env, stdout=subprocess.DEVNULL, stderr=logf,
                cwd=REPO,
            )
        )
    entry: dict = {
        "offered_rate": rate,
        "tx_bytes": size,
        "connections": connections,
        "nodes": N_NODES,
    }
    try:
        deadline = time.monotonic() + 150
        while True:
            try:
                if all(
                    _height(_rpc_port(i)) >= 3 for i in range(N_NODES)
                ):
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise RuntimeError("localnet failed to reach height 3")
            time.sleep(1.0)
        log(f"rate {rate}: localnet up, loading {duration:.0f}s")
        from cometbft_tpu.loadtime import Loader

        sampler = ResourceSampler([p.pid for p in procs])
        sampler.start()
        loader = Loader(
            endpoints=[
                f"http://127.0.0.1:{_rpc_port(i)}" for i in range(N_NODES)
            ],
            rate=rate,
            size=size,
            connections=connections,
        )
        t0 = time.time()
        summary = loader.run(duration)
        load_wall = time.time() - t0
        time.sleep(5)  # tail commit
        entry.update(sampler.stop())
        entry["duration_s"] = round(load_wall, 1)
        # offered vs actually-sent vs committed: distinguishes a
        # client-side send shortfall / RPC rejections from consensus
        # throughput when reading the saturation knee
        entry["sent"] = summary.get("sent")
        entry["send_errors"] = summary.get("errors")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()

    from cometbft_tpu.config import Config
    from cometbft_tpu.loadtime import (
        block_interval_stats,
        report_from_home,
    )
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.utils.db import open_db

    home0 = os.path.join(root, "node0")
    reports = report_from_home(home0)
    rep = reports[0].as_dict() if reports else {}
    cfg = Config.load(home0)
    db = open_db("blockstore", cfg.base.db_backend, cfg.db_dir)
    try:
        stats = block_interval_stats(BlockStore(db), last_n=500)
    finally:
        db.close()
    committed = rep.get("count", 0)
    entry.update(
        committed_tx_per_s=round(committed / entry["duration_s"], 1),
        committed_total=committed,
        latency_s={
            k: round(rep[k], 3)
            for k in ("min_s", "avg_s", "p50_s", "p95_s", "max_s")
            if k in rep
        },
        blocks_per_min=stats.get("blocks_per_min"),
        mean_block_interval_s=stats.get("mean_interval_s"),
    )
    if profile and os.path.exists(prof_path):
        entry["profile"] = aggregate_profile(prof_path)
        entry["profile_dump"] = prof_path
    return entry


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default="50,100,200,300,400")
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--size", type=int, default=1024)
    ap.add_argument("--connections", type=int, default=1)
    ap.add_argument("--profile", action="store_true",
                    help="run node0 under cProfile")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    try:
        with open(args.out) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {
            "methodology": (
                "fresh 4-validator localnet per offered rate; loadtime "
                "Loader with tx-embedded timestamps; latency from the "
                "reporter over node0's block store; RSS sampled from "
                "/proc every 2 s during load; single host, 1 CPU core "
                "(all validators + load clients share it)"
            ),
            "reference_baseline": (
                "400 tx/s saturation, <=4 s latency "
                "(200-node DO testnet, CometBFT-QA-v1.md:137)"
            ),
            "results": [],
        }
    for rate in [int(r) for r in args.rates.split(",") if r]:
        entry = run_rate(
            rate, args.duration, args.size, args.connections, args.profile
        )
        entry["measured"] = time.strftime("round 5, %Y-%m-%d %H:%M")
        doc["results"] = [
            r
            for r in doc["results"]
            if (
                r["offered_rate"],
                r.get("connections"),
                bool(r.get("profile")),
            )
            != (rate, args.connections, args.profile)
        ] + [entry]
        doc["results"].sort(key=lambda r: r["offered_rate"])
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, args.out)
        log(
            f"rate {rate}: committed {entry['committed_tx_per_s']} tx/s, "
            f"p95 {entry['latency_s'].get('p95_s')}s, "
            f"rss peak {entry.get('rss_peak_mb')} MB"
        )
    print(json.dumps(doc["results"], indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
