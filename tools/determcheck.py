"""determcheck: static replay-determinism lint — the compile-time half
of the determinism toolchain (runtime half: CMT_TPU_DETERMINISM in
cometbft_tpu/state/determinism.py; docs/determinism.md is the manual).

PR 3 gave the thread plane lockcheck, PR 4 gave the device plane
jitcheck; this completes the trilogy for the consensus plane.  The BFT
contract requires the state transition machine to be a pure function
of (block, prior state): the same decided block must produce bit-equal
results on every node, under WAL replay, handshake recovery, and
speculative execution.  This lint walks the intra-repo call graph from
the registered transition roots (``DETERMINISM_ROOTS``: apply_block /
update_state / process_proposal / WAL replay / handshake / evidence
verification / the in-repo ABCI app) and flags nondeterminism
*sources* in everything reachable:

* **wall clock** — ``time.time()``, ``now_ns()``, ``datetime.now()``…
  (block time comes from the header / median-time, never the host);
* **randomness** — ``random``, ``secrets``, ``uuid``, ``os.urandom``;
* **environment reads** — ``os.environ`` / ``os.getenv`` (two nodes
  with different env must not execute differently);
* **set iteration** — set literals/comprehensions/``set()`` locals
  iterated directly: element order depends on PYTHONHASHSEED, so it
  diverges *across processes* (dict iteration is insertion-ordered in
  the Pythons we support and is deliberately NOT flagged; ``sorted()``
  launders a set back to determinism);
* **float division** — ``/`` on the transition path (IEEE rounding is
  deterministic per-op but invites drift through reordering; integer
  consensus math uses ``//``);
* **identity hashing** — ``id()`` / ``hash()`` (PYTHONHASHSEED again).

A site is silenced by an audited trailing ``# deterministic: <reason>``
waiver (the lockcheck grammar); a waiver on a line with no flagged
site is a STALE-WAIVER error.  The call graph is a name-matching
over-approximation (see tools/lintlib.py CallGraph): everything truly
reachable is covered, at the cost of some extra reachable functions —
bounded by ``GRAPH_STOPS`` (diagnostics planes that never feed state)
and the package boundary (``crypto/``/``ops/`` are out of scope: their
*results* are deterministic by the verify contract, their *routing*
is timing-based by design and billed to the dispatch plane).

Known static limits (the runtime guard covers these): sets reached
through attributes or returned from helpers, nondeterminism behind
``getattr`` indirection, and C-extension behavior are not seen;
CMT_TPU_DETERMINISM=1 catches them as a transition-digest mismatch at
the exact height and field.

    python tools/determcheck.py         # exit 0 clean, 1 with a report
    python tools/determcheck.py -v      # also list waivers

Run in the tier-1 flow via tests/test_determcheck.py and standalone
via ``make determcheck``; tools/metrics_lint.py main() gates on it.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    CallGraph,
    Violation,
    Waiver,
    check_stale_waivers,
    comments_by_line,
    dotted,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

#: packages whose call graph the walk covers.  crypto/ops/parallel are
#: an audited boundary (result-deterministic by contract, timing-based
#: inside); utils/ is host plumbing that never computes state.
SCAN_DIRS = (
    "cometbft_tpu/abci",
    "cometbft_tpu/consensus",
    "cometbft_tpu/evidence",
    "cometbft_tpu/mempool",
    "cometbft_tpu/state",
    "cometbft_tpu/store",
    "cometbft_tpu/types",
    "cometbft_tpu/wal",
)

#: the registered transition roots: every way replayed/recovered/
#: re-proposed state enters the machine.  check_tree errors if one of
#: these stops resolving, so the root set cannot silently rot.
DETERMINISM_ROOTS = (
    ("cometbft_tpu/state/execution.py", "BlockExecutor.apply_block"),
    ("cometbft_tpu/state/execution.py", "BlockExecutor.process_proposal"),
    ("cometbft_tpu/state/execution.py", "update_state"),
    ("cometbft_tpu/state/execution.py", "validate_block"),
    ("cometbft_tpu/consensus/replay.py", "Handshaker.handshake"),
    ("cometbft_tpu/consensus/state.py", "ConsensusState._catchup_replay"),
    ("cometbft_tpu/evidence/pool.py", "Pool.verify"),
    ("cometbft_tpu/evidence/pool.py", "Pool.check_evidence"),
    ("cometbft_tpu/abci/kvstore.py", "KVStoreApp.finalize_block"),
    ("cometbft_tpu/abci/kvstore.py", "KVStoreApp.process_proposal"),
    ("cometbft_tpu/wal/__init__.py", "decode_records"),
)

#: callee names the walk never follows — diagnostics planes whose
#: output never feeds state (flight/trace/metrics/log/events), plus
#: service lifecycle.  Each entry is an audited boundary: adding one
#: asserts "nothing behind this name computes consensus state".
GRAPH_STOPS = frozenset(
    {
        # flight recorder / tracer / metrics / logger
        "record", "format_tail", "span", "add_complete", "observe",
        "observe_height", "inc", "dec", "set", "labels", "remove",
        "info", "debug", "error", "warning", "with_fields",
        # event bus + pubsub fan-out (subscribers are off-path)
        "publish", "publish_new_block", "publish_new_block_events",
        "publish_tx_event", "publish_validator_set_updates", "fire",
        # service lifecycle + thread plumbing
        "start", "stop", "is_running", "quit_event", "wait",
        # stdlib-ish names that would wildly over-match
        "get", "put", "append", "extend", "pop", "items", "keys",
        "values", "join", "split", "strip", "encode_varint", "read",
        "write", "close", "flush",
    }
)

_WAIVER_RE = waiver_re("deterministic")

#: dotted call names that read the host wall clock.  Duration clocks
#: (perf_counter/monotonic) are deliberately absent: they can only
#: express *intervals*, which feed metrics, not state — and if one
#: ever did escape into state, the runtime digest guard names the
#: height and field.
_WALL_CLOCK = frozenset(
    {
        "time.time", "time.time_ns", "now_ns", "now", "utcnow",
        "datetime.now", "datetime.utcnow", "datetime.datetime.now",
        "datetime.datetime.utcnow", "date.today",
    }
)

#: dotted prefixes that produce randomness
_RANDOM_PREFIXES = ("random.", "secrets.", "uuid.")


@dataclass
class Report(lintlib.Report):
    roots: int = 0
    reachable: int = 0
    sites: int = 0


def _detect_sites(fn: ast.AST) -> list[tuple[int, str]]:
    """All nondeterminism sites in one function body (nested defs
    included — a deferred closure still runs on the replay path)."""
    sites: list[tuple[int, str]] = []

    # one-level local taint: names assigned from set constructions
    set_vars: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            v = node.value
            is_set = isinstance(v, (ast.Set, ast.SetComp)) or (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Name)
                and v.func.id in ("set", "frozenset")
            )
            if is_set:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        set_vars.add(tgt.id)

    def is_set_expr(e: ast.expr) -> bool:
        if isinstance(e, (ast.Set, ast.SetComp)):
            return True
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Name):
            return e.func.id in ("set", "frozenset")
        return isinstance(e, ast.Name) and e.id in set_vars

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            base = d.split(".")[-1] if d else ""
            if d in _WALL_CLOCK:
                sites.append((node.lineno, f"wall-clock read {d}()"))
            elif d.startswith(_RANDOM_PREFIXES) or d == "os.urandom":
                sites.append((node.lineno, f"randomness source {d}()"))
            elif d in ("os.getenv", "os.environ.get", "getenv"):
                sites.append((node.lineno, f"environment read {d}()"))
            elif (
                isinstance(node.func, ast.Name)
                and node.func.id in ("id", "hash")
                and node.args
            ):
                sites.append(
                    (node.lineno,
                     f"identity/{node.func.id}() keying "
                     "(PYTHONHASHSEED-dependent)")
                )
        elif isinstance(node, ast.Subscript):
            if dotted(node.value) == "os.environ":
                sites.append(
                    (node.lineno, "environment read os.environ[...]")
                )
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            sites.append((node.lineno, "float division '/'"))
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if is_set_expr(node.iter):
                sites.append(
                    (node.lineno,
                     "iteration over a set (order is "
                     "PYTHONHASHSEED-dependent; sort first)")
                )
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                if is_set_expr(gen.iter):
                    sites.append(
                        (gen.iter.lineno,
                         "comprehension over a set (order is "
                         "PYTHONHASHSEED-dependent; sort first)")
                    )
    return sites


def _check_files(files: list[tuple[str, str]], report: Report) -> None:
    graph = CallGraph(files)
    roots = [r for r in DETERMINISM_ROOTS if r in graph.funcs]
    report.roots += len(roots)
    parents = graph.reachable(roots, stops=GRAPH_STOPS)
    report.reachable += len(parents)

    comments = {rel: comments_by_line(src) for rel, src in files}
    flagged: dict[str, set[int]] = {rel: set() for rel, _ in files}
    waived: dict[str, set[int]] = {rel: set() for rel, _ in files}

    for key, info in graph.funcs.items():
        sites = _detect_sites(info.node)
        if not sites:
            continue
        flagged[info.rel].update(line for line, _ in sites)
        if key not in parents:
            continue  # pattern present but not replay-reachable
        for line, site in sites:
            report.sites += 1
            m = _WAIVER_RE.search(comments[info.rel].get(line, ""))
            if m:
                if line not in waived[info.rel]:
                    waived[info.rel].add(line)
                    report.waivers.append(
                        Waiver(info.rel, line, site, m.group(1).strip())
                    )
                continue
            report.violations.append(
                Violation(
                    info.rel, line,
                    f"{site} in {info.qualname}() on the replay path "
                    f"({graph.chain(parents, key)}) — the state "
                    "transition must be a pure function of (block, "
                    "prior state); derive the value from the block/"
                    "state or waive with '# deterministic: <reason>'",
                )
            )

    for rel, _src in files:
        check_stale_waivers(
            comments[rel], flagged[rel], _WAIVER_RE, rel, report,
            "deterministic",
        )


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source (fixtures): roots are matched against
    ``rel``, so a fixture posing as cometbft_tpu/state/execution.py
    with a ``def update_state`` exercises the real root set."""
    report = Report()
    _check_files([(rel, source)], report)
    return report


def check_tree(root: str | None = None) -> Report:
    report = Report()
    files: list[tuple[str, str]] = []
    if root is not None:
        files = list(iter_py_files(root))
    else:
        for d in SCAN_DIRS:
            files.extend(iter_py_files(d))
    seen = {rel for rel, _ in files}
    for rel, qual in DETERMINISM_ROOTS:
        if rel not in seen:
            report.violations.append(
                Violation(rel, 0, f"DETERMINISM_ROOTS file missing "
                                  f"(root {qual})")
            )
    _check_files(files, report)
    graph_roots = {
        (rel, qual) for rel, qual in DETERMINISM_ROOTS if rel in seen
    }
    resolved = CallGraph(files).funcs.keys()
    for key in sorted(graph_roots):
        if key not in resolved:
            report.violations.append(
                Violation(
                    key[0], 0,
                    f"determinism root {key[1]} no longer resolves — "
                    "update DETERMINISM_ROOTS (tools/determcheck.py) "
                    "to the renamed transition entrypoint",
                )
            )
    return report


def _summary(report: Report) -> str:
    return (
        f"{report.reachable} functions reachable from {report.roots} "
        f"transition roots; {report.sites} nondeterminism sites "
        f"({len(report.waivers)} audited waivers)"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("determcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
