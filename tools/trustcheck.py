"""trustcheck: wire-ingress taint lint — the compile-time half of the
byzantine trust boundary (runtime half: CMT_TPU_TRUSTGUARD in
cometbft_tpu/utils/trustguard.py; docs/trust_boundary.md is the
manual).

Every byzantine byte enters the node through a small set of seams: the
seven ``Reactor.receive`` implementations, the consensus message
decoder, the secret-connection frame decode, the statesync chunk
apply, the RPC tx ingress, and the remote-ABCI response read.  The BFT
contract requires that network-derived values pass a *validator*
(``validate_basic``, signature verify, commit verify) before they
touch consensus state.  Nothing enforced that mechanically until now —
this is the sixth lint in the lintlib family (lockcheck, jitcheck,
determcheck, hotpathcheck, envcheck) and it closes the last un-linted
plane: the wire.

**Pass 1 — taint walk.**  BFS the intra-repo call graph from the
registered ``INGRESS_ROOTS``; every reachable function is *tainted*
(may be holding attacker-controlled values).  Inside tainted
functions, flag each call whose basename matches a registered sink
(``SINKS``: vote admission, part admission, mempool entry, evidence
add, block/state store writes, apply_block).  A flagged site passes
when:

* the sink **self-validates** — a registered validator is reachable
  from the sink's own definition (``VoteSet.add_vote`` reaches
  ``VoteSet._verify`` through ``_add_vote_locked``); or
* the **caller validates** — the tainted function's own body calls a
  registered validator (blocksync verifies the commit light before
  applying); or
* the line carries an audited ``# trusted: <validator> — <reason>``
  waiver whose first token names a registered validator (the
  hotpathcheck mirrored-registry convention — a waiver cannot cite a
  validator that does not exist).

**Pass 2 — decode-bounds discipline.**  Inside tainted functions, a
sequence-repeat allocation whose size comes from a bare
name/attribute (``[None] * total``, ``b"\\x00" * n``) is the classic
pre-consensus DoS when the size is a hostile length prefix.  The site
passes when the function dominates it with a cap — an upper-bound
comparison on the size, a ``min(size, CAP)`` clamp, or a
``read_uvarint_from(..., max_value=...)`` producer — or carries a
``# bounded: <cap> — <reason>`` waiver whose first token names a cap
in ``KNOWN_CAPS``.  (``bytes(x)``/``bytearray(x)`` calls are NOT
flagged: statically they are overwhelmingly buffer *copies* of data
already in memory, not length-prefix preallocations.)

Registries are pure literals; an entry that stops resolving fails the
gate loudly (determcheck's root-set convention) so the boundary cannot
silently rot.  Both waiver tags get the stale-waiver inverse check.

The taint walk STOPS at registered validators: a validator is the
audited boundary — everything behind ``verify_signature`` /
``Pool.verify`` is the crypto plane, designed for hostile input and
out of scope here (determcheck draws the same line for its plane).

Known static limits (the runtime guard covers these): taint through
queues is modeled by registering both seam ends as roots
(``ConsensusReactor.receive`` enqueues, ``ConsensusState._handle_msg``
dequeues); dynamic dispatch behind ``getattr`` is not seen.
CMT_TPU_TRUSTGUARD=1 stamps provenance on decoded envelopes at the
reactor seam and asserts at each registered sink that validation ran,
tripping ``consensus_trust_guard_trips_total{sink}`` plus a flight
event before raising.

    python tools/trustcheck.py         # exit 0 clean, 1 with a report
    python tools/trustcheck.py -v      # also list waivers

Run in the tier-1 flow via tests/test_trustcheck.py and standalone via
``make trustcheck``; tools/metrics_lint.py main() gates on it.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    CallGraph,
    Violation,
    Waiver,
    check_stale_waivers,
    comments_by_line,
    dotted,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

#: packages the taint walk covers — everything a wire byte can reach.
#: crypto/ is IN scope here (unlike determcheck): signature
#: verification is the validator plane this lint pivots on.
SCAN_DIRS = (
    "cometbft_tpu/abci",
    "cometbft_tpu/blocksync",
    "cometbft_tpu/consensus",
    "cometbft_tpu/crypto",
    "cometbft_tpu/evidence",
    "cometbft_tpu/mempool",
    "cometbft_tpu/p2p",
    "cometbft_tpu/rpc",
    "cometbft_tpu/state",
    "cometbft_tpu/statesync",
    "cometbft_tpu/store",
    "cometbft_tpu/types",
    "cometbft_tpu/wal",
)

#: every seam where attacker-controlled bytes enter the process.  The
#: consensus seam is registered at BOTH ends of its queue (receive
#: enqueues MsgInfo, _handle_msg dequeues it) because the name-matching
#: graph cannot follow values through a queue.  check_tree errors if
#: an entry stops resolving.
INGRESS_ROOTS = (
    ("cometbft_tpu/consensus/reactor.py", "ConsensusReactor.receive"),
    ("cometbft_tpu/consensus/state.py", "ConsensusState._handle_msg"),
    ("cometbft_tpu/blocksync/reactor.py", "BlocksyncReactor.receive"),
    ("cometbft_tpu/mempool/reactor.py", "MempoolReactor.receive"),
    ("cometbft_tpu/statesync/reactor.py", "StatesyncReactor.receive"),
    ("cometbft_tpu/statesync/syncer.py", "Syncer.add_chunk"),
    ("cometbft_tpu/evidence/reactor.py", "EvidenceReactor.receive"),
    ("cometbft_tpu/p2p/pex/reactor.py", "PexReactor.receive"),
    ("cometbft_tpu/p2p/base_reactor.py", "Reactor.receive"),
    ("cometbft_tpu/consensus/messages.py", "decode_message_traced"),
    ("cometbft_tpu/p2p/conn/secret_connection.py", "SecretConnection.read"),
    ("cometbft_tpu/rpc/core.py", "Environment.broadcast_tx_async"),
    ("cometbft_tpu/rpc/core.py", "Environment.broadcast_tx_sync"),
    ("cometbft_tpu/rpc/core.py", "Environment.broadcast_tx_commit"),
    ("cometbft_tpu/rpc/core.py", "Environment.broadcast_evidence"),
    ("cometbft_tpu/abci/client.py", "SocketClient._read_response"),
)

#: the validation plane: a flagged sink call passes when one of these
#: is reachable from the sink def, called by the flagged caller, or
#: named by a ``# trusted:`` waiver.  check_tree errors if an entry
#: stops resolving.
VALIDATORS = (
    ("cometbft_tpu/types/vote_set.py", "VoteSet._verify"),
    ("cometbft_tpu/types/part_set.py", "Part.validate_basic"),
    ("cometbft_tpu/types/validation.py", "verify_commit"),
    ("cometbft_tpu/types/validation.py", "verify_commit_light"),
    ("cometbft_tpu/types/validation.py", "verify_commit_light_trusting"),
    ("cometbft_tpu/state/execution.py", "validate_block"),
    ("cometbft_tpu/evidence/pool.py", "Pool.verify"),
    ("cometbft_tpu/evidence/pool.py", "Pool.check_evidence"),
    ("cometbft_tpu/mempool/__init__.py", "CListMempool._verify_tx_signature"),
    ("cometbft_tpu/crypto/verify_queue.py", "verify_or_fallback"),
    ("cometbft_tpu/crypto/verify_queue.py", "checktx_verify_or_fallback"),
    ("cometbft_tpu/crypto/ed25519.py", "Ed25519PubKey.verify_signature"),
)

#: consensus-state mutation points a tainted value must not reach
#: unvalidated.  check_tree errors if an entry stops resolving.
SINKS = (
    ("cometbft_tpu/types/vote_set.py", "VoteSet.add_vote"),
    ("cometbft_tpu/types/part_set.py", "PartSet.add_part"),
    ("cometbft_tpu/mempool/__init__.py", "CListMempool.check_tx"),
    ("cometbft_tpu/evidence/pool.py", "Pool.add_evidence"),
    ("cometbft_tpu/store/__init__.py", "BlockStore.save_block"),
    ("cometbft_tpu/state/__init__.py", "Store.save"),
    ("cometbft_tpu/state/execution.py", "BlockExecutor.apply_block"),
)

#: size-cap names a ``# bounded: <cap>`` waiver may cite — the
#: mirrored-registry convention: a waiver cannot invent a cap.
KNOWN_CAPS = frozenset(
    {
        "MAX_MSG_SIZE",
        "DATA_MAX_SIZE",
        "TOTAL_FRAME_SIZE",
        "_MAX_BIT_ARRAY_BITS",
        "BLOCK_PART_SIZE_BYTES",
        "MAX_PART_SET_TOTAL",
        "MAX_RANGE",
        "_MAX_MSG_BYTES",
        "max_packet_msg_payload_size",
        "recv_message_capacity",
        "_MAX_ADDRS_PER_MSG",
        "MAX_PACKET_PAYLOAD",
        "MAX_CHUNK_SIZE",
        "read_uvarint_from",
    }
)

#: callee names the walk never follows — diagnostics planes whose
#: output never feeds state, service lifecycle, and stdlib-ish names
#: that would wildly over-match (the determcheck convention; each
#: entry asserts "nothing behind this name admits wire data to
#: consensus state").
GRAPH_STOPS = frozenset(
    {
        # flight recorder / tracer / metrics / logger
        "record", "format_tail", "span", "add_complete", "observe",
        "observe_height", "inc", "dec", "set", "labels", "remove",
        "info", "debug", "error", "warning", "with_fields",
        # event bus + pubsub fan-out (subscribers are off-path)
        "publish", "publish_new_block", "publish_new_block_events",
        "publish_tx_event", "publish_validator_set_updates", "fire",
        # service lifecycle + thread plumbing
        "start", "stop", "is_running", "quit_event", "wait",
        # stdlib-ish names that would wildly over-match
        "get", "put", "append", "extend", "pop", "items", "keys",
        "values", "join", "split", "strip", "encode_varint",
        "write", "close", "flush", "add",
    }
)

_TRUSTED_RE = waiver_re("trusted")
_BOUNDED_RE = waiver_re("bounded")


@dataclass
class Report(lintlib.Report):
    roots: int = 0
    validators: int = 0
    sinks: int = 0
    tainted: int = 0
    sink_sites: int = 0
    alloc_sites: int = 0


def _sink_calls(fn: ast.AST, sink_names: set[str]) -> list[tuple[int, str]]:
    """Call sites in ``fn`` whose basename matches a registered sink."""
    out: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        else:
            continue
        if name in sink_names:
            out.append((node.lineno, name))
    return out


def _size_token(e: ast.expr) -> str:
    """The textual identity of a size operand when it is a bare
    name/attribute ("" otherwise — constants and len() results are
    not attacker-controlled lengths)."""
    if isinstance(e, (ast.Name, ast.Attribute)):
        return dotted(e)
    return ""


def _alloc_sites(fn: ast.AST) -> list[tuple[int, str, str]]:
    """(line, size-token, description) for each sequence-repeat
    allocation sized by a bare name/attribute: ``[x] * n``,
    ``b".." * n``."""
    sites: list[tuple[int, str, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            for seq, size in ((node.left, node.right),
                              (node.right, node.left)):
                is_seq = isinstance(seq, (ast.List, ast.Tuple)) or (
                    isinstance(seq, ast.Constant)
                    and isinstance(seq.value, (bytes, str))
                )
                tok = _size_token(size)
                if is_seq and tok:
                    sites.append(
                        (node.lineno, tok,
                         f"sequence allocation sized by '{tok}'")
                    )
    return sites


def _capped_tokens(fn: ast.AST) -> set[str]:
    """Size tokens the function dominates with a cap: an upper-bound
    comparison mentioning the token, a ``min(...)`` assignment, or a
    ``read_uvarint_from(...)`` producer (which rejects past
    ``max_value`` before allocating)."""
    capped: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            if any(
                isinstance(op, (ast.Lt, ast.LtE, ast.Gt, ast.GtE))
                for op in node.ops
            ):
                for e in [node.left, *node.comparators]:
                    tok = _size_token(e)
                    if tok:
                        capped.add(tok)
        elif isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            callee = dotted(node.value.func).split(".")[-1]
            # len() of an in-memory collection is already materialized
            # — it cannot be a hostile length *prefix*
            if callee in ("min", "len", "read_uvarint_from"):
                for tgt in node.targets:
                    tok = _size_token(tgt)
                    if tok:
                        capped.add(tok)
    return capped


def _check_files(files: list[tuple[str, str]], report: Report) -> None:
    graph = CallGraph(files)

    roots = [r for r in INGRESS_ROOTS if r in graph.funcs]
    validators = [v for v in VALIDATORS if v in graph.funcs]
    sinks = [s for s in SINKS if s in graph.funcs]
    report.roots += len(roots)
    report.validators += len(validators)
    report.sinks += len(sinks)

    validator_names = {q.rsplit(".", 1)[-1] for _, q in VALIDATORS}
    sink_names = {q.rsplit(".", 1)[-1] for _, q in SINKS}

    # a sink self-validates when a registered validator is reachable
    # from the sink's own definition (add_vote reaches _verify through
    # _add_vote_locked — function granularity would miss it)
    validator_keys = set(validators)
    self_validating: set[str] = set()
    for key in sinks:
        closure = graph.reachable([key], stops=GRAPH_STOPS)
        if validator_keys & set(closure):
            self_validating.add(key[1].rsplit(".", 1)[-1])

    # the taint walk stops AT validators: they are the audited
    # boundary, their internals are the crypto plane
    taint_stops = frozenset(GRAPH_STOPS | validator_names)
    parents = graph.reachable(roots, stops=taint_stops)
    report.tainted += len(parents)

    comments = {rel: comments_by_line(src) for rel, src in files}
    flagged: dict[str, set[int]] = {rel: set() for rel, _ in files}
    bflagged: dict[str, set[int]] = {rel: set() for rel, _ in files}

    for key, info in graph.funcs.items():
        scalls = _sink_calls(info.node, sink_names)
        allocs = _alloc_sites(info.node)
        if not scalls and not allocs:
            continue
        flagged[info.rel].update(line for line, _ in scalls)
        bflagged[info.rel].update(line for line, _, _ in allocs)
        if key not in parents:
            continue  # pattern present but not wire-reachable

        caller_validates = bool(info.calls & validator_names)
        for line, sname in scalls:
            report.sink_sites += 1
            if sname in self_validating or caller_validates:
                continue
            m = _TRUSTED_RE.search(comments[info.rel].get(line, ""))
            if m:
                reason = m.group(1).strip()
                cited = reason.split()[0].rstrip(":—-") if reason else ""
                if cited not in validator_names:
                    report.violations.append(
                        Violation(
                            info.rel, line,
                            f"'# trusted: {cited}' does not name a "
                            "registered validator "
                            f"({', '.join(sorted(validator_names))})",
                        )
                    )
                else:
                    report.waivers.append(
                        Waiver(info.rel, line, f"sink {sname}", reason)
                    )
                continue
            report.violations.append(
                Violation(
                    info.rel, line,
                    f"wire-tainted call to sink {sname}() in "
                    f"{info.qualname}() "
                    f"({graph.chain(parents, key)}) with no validator "
                    "on the path — route through a registered "
                    "validator or waive with "
                    "'# trusted: <validator> — <reason>'",
                )
            )

        capped = _capped_tokens(info.node)
        for line, tok, desc in allocs:
            report.alloc_sites += 1
            if tok in capped:
                continue
            m = _BOUNDED_RE.search(comments[info.rel].get(line, ""))
            if m:
                reason = m.group(1).strip()
                cited = reason.split()[0].rstrip(":—-") if reason else ""
                if cited not in KNOWN_CAPS:
                    report.violations.append(
                        Violation(
                            info.rel, line,
                            f"'# bounded: {cited}' does not name a "
                            "registered cap (KNOWN_CAPS in "
                            "tools/trustcheck.py)",
                        )
                    )
                else:
                    report.waivers.append(
                        Waiver(info.rel, line, desc, reason)
                    )
                continue
            report.violations.append(
                Violation(
                    info.rel, line,
                    f"{desc} in wire-tainted {info.qualname}() "
                    f"({graph.chain(parents, key)}) with no dominating "
                    "size cap — a hostile length prefix is an "
                    "unbounded-allocation DoS; cap the size or waive "
                    "with '# bounded: <cap> — <reason>'",
                )
            )

    for rel, _src in files:
        check_stale_waivers(
            comments[rel], flagged[rel], _TRUSTED_RE, rel, report,
            "trusted",
        )
        check_stale_waivers(
            comments[rel], bflagged[rel], _BOUNDED_RE, rel, report,
            "bounded",
        )


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source (fixtures): registries are matched
    against ``rel``, so a fixture posing as
    cometbft_tpu/mempool/reactor.py with a ``def receive`` exercises
    the real root set."""
    report = Report()
    _check_files([(rel, source)], report)
    return report


def check_tree(root: str | None = None) -> Report:
    report = Report()
    files: list[tuple[str, str]] = []
    if root is not None:
        files = list(iter_py_files(root))
    else:
        for d in SCAN_DIRS:
            files.extend(iter_py_files(d))
    seen = {rel for rel, _ in files}
    registries = (
        ("INGRESS_ROOTS", "ingress root", INGRESS_ROOTS),
        ("VALIDATORS", "validator", VALIDATORS),
        ("SINKS", "sink", SINKS),
    )
    for regname, kind, entries in registries:
        for rel, qual in entries:
            if rel not in seen:
                report.violations.append(
                    Violation(
                        rel, 0,
                        f"{regname} file missing ({kind} {qual})",
                    )
                )
    _check_files(files, report)
    resolved = CallGraph(files).funcs.keys()
    for regname, kind, entries in registries:
        for key in sorted(set(entries)):
            if key[0] in seen and key not in resolved:
                report.violations.append(
                    Violation(
                        key[0], 0,
                        f"{kind} {key[1]} no longer resolves — update "
                        f"{regname} (tools/trustcheck.py) to the "
                        "renamed boundary entrypoint",
                    )
                )
    return report


def _summary(report: Report) -> str:
    return (
        f"{report.tainted} functions tainted from {report.roots} "
        f"ingress roots; {report.sink_sites} sink sites checked "
        f"against {report.validators} validators / {report.sinks} "
        f"sinks, {report.alloc_sites} wire allocation sites "
        f"({len(report.waivers)} audited waivers)"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("trustcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
