"""lint_all: every static lint in ONE process, each file parsed once.

The six AST lints — lockcheck (guarded-by), jitcheck (device plane),
determcheck (replay determinism), hotpathcheck (critical-path
blocking), envcheck (knob registry), and trustcheck (wire-ingress
taint) — each walk the same ``cometbft_tpu`` tree.  Run as six
processes (`make lockcheck && make jitcheck && ...`) every one of
them re-reads, re-parses, and re-tokenizes every file.  Run here,
lintlib's content-keyed ``parse_cached`` / ``comments_by_line``
memos mean each file's AST is built once and shared: the first lint
pays the parse, the other five get cache hits.

This is the `make lint` umbrella.  The `make test` flow gets the
same six via the single ``metrics_lint main()`` gate (which also
checks the metrics series registry); this entrypoint exists for the
edit-lint loop where you want all verdicts in one fast command.

The wall time of the full six-lint pass is appended to the perf
ledger as ``lint_wall_seconds`` (source ``lint_all``) — perfdiff
treats ``seconds`` as lower-is-better, so `make perf-gate` catches a
lint that quietly goes quadratic on the growing tree the same way it
catches a verify regression.  Ledger writes are best-effort: the
lint verdict must never depend on ledger I/O.
"""

from __future__ import annotations

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import (  # noqa: E402 — path bootstrap above
    determcheck,
    envcheck,
    hotpathcheck,
    jitcheck,
    lintlib,
    lockcheck,
    trustcheck,
)

#: gate order: cheap registry lints first, call-graph walks last, so
#: the common "typo in a registry" failure reports in milliseconds
LINTS = (lockcheck, jitcheck, envcheck, determcheck, hotpathcheck,
         trustcheck)


def _record_wall(wall: float) -> None:
    """Best-effort ``lint_wall_seconds`` ledger row for perfdiff."""
    try:
        from tools import perfledger

        perfledger.append([
            perfledger.make_entry(
                "lint_wall_seconds", round(wall, 3), "seconds",
                "lint_all",
                measured=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                note=f"{len(LINTS)} lints, shared-AST single pass",
            )
        ])
    except Exception as exc:  # the ledger must never fail the lint
        print(f"lint_all: ledger append failed (ignored): {exc}",
              file=sys.stderr)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    t0 = time.perf_counter()
    rc = 0
    for lint in LINTS:
        if lint.main(list(argv)) != 0:
            rc = 1
    wall = time.perf_counter() - t0
    parsed = len(lintlib._PARSE_CACHE)
    print(
        f"lint_all: {len(LINTS)} lints "
        f"{'green' if rc == 0 else 'RED'} in {wall:.2f}s "
        f"({parsed} files parsed once, shared across lints)"
    )
    if rc == 0:
        _record_wall(wall)
    return rc


if __name__ == "__main__":
    sys.exit(main())
