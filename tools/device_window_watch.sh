#!/bin/sh
# Watches the device tunnel; the moment it answers, fires the queued
# round-5 device measurements in priority order:
#   1. tools/device_campaign.py   — keyed stack/stack16/pallas A/B
#                                   (docs/data/kernel_ab_r05.json)
#   2. tools/derive_device_min_batch.py — re-derive the dispatch
#      crossover against the 9x-faster host RLC path (writes the
#      schema-2 calibration in ONE shot at the end — not resumable,
#      which is why it runs early, right after the headline A/Bs)
#   3. bench_all.py               — all five BASELINE configs, keyed
#   4. tools/sharded_keyed_probe.py — mesh+keyed on chip, HBM accounted
# Steps 1, 3, 4 are resumable/checkpointed, so a window closing
# mid-run keeps whatever landed. Log: /tmp/device_window.log
cd "$(dirname "$0")/.." || exit 1
LOG=/tmp/device_window.log
while true; do
  t0=$(date +%s)
  out=$(timeout 25 python -c "import jax; print(len(jax.devices()))" 2>/dev/null)
  t1=$(date +%s)
  if [ "$out" != "" ] && [ "$out" != "0" ]; then
    echo "$(date -u +%H:%M:%S) tunnel OPEN ($out devices, probe $((t1-t0))s) - firing campaign" >> "$LOG"
    timeout 5400 python tools/device_campaign.py >> "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) campaign rc=$?" >> "$LOG"
    timeout 1800 python tools/derive_device_min_batch.py >> "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) recalibrate rc=$?" >> "$LOG"
    timeout 3600 python bench_all.py >> "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) bench_all rc=$?" >> "$LOG"
    timeout 2400 python tools/sharded_keyed_probe.py >> "$LOG" 2>&1
    echo "$(date -u +%H:%M:%S) sharded_keyed rc=$?" >> "$LOG"
    echo "$(date -u +%H:%M:%S) queue drained; watcher exiting" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%H:%M:%S) tunnel closed (probe $((t1-t0))s)" >> "$LOG"
  sleep 240
done
