"""Stage-by-stage timing probe for the precompute path on the device."""

import sys
import time

t0 = time.time()


def mark(s):
    print(f"[{time.time() - t0:7.1f}s] {s}", file=sys.stderr, flush=True)


import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
jax.config.update("jax_compilation_cache_dir", os.path.join(repo, ".xla_cache"))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
mark(f"jax imported; devices: {jax.devices()}")

import numpy as np  # noqa: E402

from cometbft_tpu.crypto import ed25519 as ed  # noqa: E402
from cometbft_tpu.ops import precompute as PR  # noqa: E402

mark("precompute imported")
nval = int(os.environ.get("KB_NVAL", 150))
privs = [ed.gen_priv_key() for _ in range(nval)]
pubs = [p.pub_key().bytes() for p in privs]
mark(f"{nval} keys generated")
tbl = PR.b_comb8()
mark(f"b_comb8 host build done shape={tbl.shape}")
entry = PR.TABLE_CACHE.lookup_or_build(pubs)
mark(f"table build dispatched wb={entry.window_bits} "
     f"bytes={entry.nbytes / 1e6:.0f}MB")
v = np.asarray(entry.valid)
mark(f"valid fetched: {v.all()}")
tb = np.asarray(jax.device_get(entry.table[0, 0, 0, :4]))
mark("table sample fetched (build complete)")
