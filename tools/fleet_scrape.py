"""Fleet scraper — merge N nodes' observability rings into one view.

    python tools/fleet_scrape.py --targets 127.0.0.1:26660,127.0.0.1:26662
    python tools/fleet_scrape.py --targets ... --out /tmp/fleet_trace.json
    python tools/fleet_scrape.py --targets ... --heights --json

Scrapes each node's ``/metrics``, ``/trace`` and ``/debug/flight``
(the metrics-server surfaces), aligns them on wall clock, and:

- writes ONE Chrome trace-event file (``--out``) with pid = node —
  load it in Perfetto to see proposal → gossip hop → quorum → commit
  across the fleet on a single timeline;
- prints the fleet rollup (per-node committed height + lag behind the
  fleet max, one-hot dispatch tier, verify-queue depths, gossip-hop
  aggregates, per-peer clock offsets) — the skew/lag table;
- with ``--heights``, prints the stitched per-height trees and the
  cross-node proposal→commit latency p50/p95 (the
  ``height_latency_p95_4node`` SLO's formula).

The same machinery serves live on any node at ``/debug/fleet``
(peers from CMT_TPU_FLEET_PEERS).  See docs/observability.md
"Fleet plane" for the clock-offset caveat.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_rollup(rollup: dict) -> str:
    lines = [
        f"fleet: {len(rollup['nodes'])} nodes, max height "
        f"{rollup['max_height']}, skew {rollup['height_skew']}, "
        f"{rollup['scrape_errors']} scrape errors",
        f"{'node':<24} {'height':>7} {'lag':>4} {'tier':<12} "
        f"{'hops':>6} {'hop avg ms':>10}  queue depth",
    ]
    for n in rollup["nodes"]:
        if n["error"]:
            lines.append(f"{n['node']:<24} SCRAPE ERROR: {n['error']}")
            continue
        q = ",".join(
            f"{k}={int(v)}" for k, v in sorted(
                (n.get("verify_queue_depth") or {}).items()
            )
        )
        lines.append(
            f"{n['node']:<24} {n['height'] if n['height'] is not None else '-':>7} "
            f"{n['height_lag'] if n['height_lag'] is not None else '-':>4} "
            f"{(n['dispatch_tier'] or '-'):<12} "
            f"{n['gossip_hops']:>6} "
            f"{(n['gossip_hop_avg_ms'] if n['gossip_hop_avg_ms'] is not None else '-'):>10}  {q}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge N nodes' observability rings into one view"
    )
    ap.add_argument(
        "--targets", required=True,
        help="comma-separated metrics-server addresses (host:port)",
    )
    ap.add_argument(
        "--names", default="",
        help="comma-separated display names (default: the targets)",
    )
    ap.add_argument(
        "--out", default="",
        help="write the merged Chrome trace-event JSON here",
    )
    ap.add_argument(
        "--heights", action="store_true",
        help="print stitched per-height trees + latency percentiles",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit the full /debug/fleet payload as JSON on stdout",
    )
    ap.add_argument("--timeout", type=float, default=3.0)
    args = ap.parse_args(argv)

    from cometbft_tpu.utils import fleetobs

    targets = fleetobs.fleet_peer_targets(args.targets)
    names = [n for n in args.names.split(",") if n] or None
    scrapes = fleetobs.scrape_fleet(
        targets, names=names, timeout=args.timeout
    )
    if all(s.error for s in scrapes):
        print("every target failed to scrape:", file=sys.stderr)
        for s in scrapes:
            print(f"  {s.name}: {s.error}", file=sys.stderr)
        return 1

    payload = fleetobs.fleet_payload(scrapes)

    if args.out:
        merged = fleetobs.merge_traces(scrapes)
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(merged, f)
        os.replace(tmp, args.out)
        print(
            f"wrote {len(merged['traceEvents'])} events -> {args.out}",
            file=sys.stderr,
        )

    if args.json:
        json.dump(payload, sys.stdout, indent=1, default=str)
        print()
        return 0

    print(_fmt_rollup(payload["rollup"]))
    if payload.get("scenario"):
        print(f"active scenario: {payload['scenario']}")
    if args.heights:
        lat = {
            h: ent["latency_ms"]
            for h, ent in payload["stitched_heights"].items()
            if ent.get("latency_ms") is not None
        }
        complete = payload["complete_heights"]
        print(
            f"\nstitched heights: {len(payload['stitched_heights'])} "
            f"({len(complete)} complete: {complete})"
        )
        for h, ent in payload["stitched_heights"].items():
            print(
                f"  h={h} proposal={ent['proposal']} hops={ent['hops']} "
                f"origins={ent['origins']} quorum={ent['quorum']} "
                f"commit={ent['commit']} on={ent['committed_on']} "
                f"latency_ms={ent.get('latency_ms')}"
            )
        if lat:
            vals = list(lat.values())
            print(
                f"cross-node proposal->commit latency: "
                f"p50={fleetobs.percentile(vals, 50):.1f}ms "
                f"p95={fleetobs.percentile(vals, 95):.1f}ms "
                f"over {len(vals)} heights"
            )
        # attribution plane: per-height stage budgets on the same
        # corrected axis (utils/critpath.py), then the p95 height's
        # budget — the row that explains the p95 number above
        budgets = payload.get("stage_budgets") or {}
        if budgets:
            print(f"\nstage budgets ({len(budgets)} heights):")
            for h, d in budgets.items():
                top = sorted(
                    d["stages"].items(), key=lambda kv: -kv[1]
                )[:3]
                tops = " ".join(
                    f"{s}={v * 1e3:.1f}ms" for s, v in top if v > 0
                )
                inj = d.get("injected_s") or 0.0
                inj_s = f" injected={inj * 1e3:.1f}ms" if inj else ""
                print(
                    f"  h={h} wall={d['wall_s'] * 1e3:.1f}ms "
                    f"gate={d.get('gating_node')} {tops}{inj_s}"
                )
            p95b = payload.get("stage_budget_p95")
            if p95b:
                print(
                    f"p95 height h={p95b['height']} "
                    f"wall={p95b['wall_s'] * 1e3:.1f}ms critical stage: "
                    f"{payload.get('critical_stage_p95')}"
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
