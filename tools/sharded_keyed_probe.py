"""Sharded keyed verification ON HARDWARE with HBM accounting.

VERDICT r4 #7: the mesh path and keyed path compose in CPU tests, but
per-shard device placement of the keyed tables had never been exercised
on a real chip. This probe runs the composition on whatever devices are
visible (a single-device mesh still exercises the real sharded code
path and table replication), at the BASELINE config-2/5 shapes:

  - 150-validator commit (8-bit comb pages)
  - 10k-validator mega-commit (4-bit pages, ~4.4 GB pool)

and records, per shape: table pool bytes, device memory stats before /
after the table build (live_bytes from device.memory_stats when the
backend reports them), first-launch latency (compile), and steady
launch latency through ShardedTpuBatchVerifier.verify().

    python tools/sharded_keyed_probe.py [--nvals 150,10000]

Appends to docs/data/sharded_keyed_r05.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "data", "sharded_keyed_r05.json")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def mem_stats(dev) -> dict:
    try:
        s = dev.memory_stats() or {}
        return {
            k: s[k]
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
            if k in s
        }
    except Exception:
        return {}


def probe_shape(nval: int, nsig: int) -> dict:
    import numpy as np

    import jax

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.ops import precompute as PR
    from cometbft_tpu.parallel.mesh import ShardedTpuBatchVerifier

    dev = jax.devices()[0]
    entry: dict = {
        "nval": nval,
        "nsig": nsig,
        "ndev": len(jax.devices()),
        "platform": dev.platform,
        "mem_before": mem_stats(dev),
    }
    # one shared key-table pool build at this shape
    privs = [ed.priv_key_from_secret(b"shard%d" % i) for i in range(nval)]
    pubs_b = [p.pub_key().bytes() for p in privs]
    t0 = time.time()
    tbl = PR.TABLE_CACHE.lookup_or_build(pubs_b)
    if tbl is None:
        raise SystemExit(
            f"{nval} unique keys is outside table policy "
            f"(CMT_TPU_TABLE_MAX_KEYS={PR.TABLE_MAX_KEYS})"
        )
    np.asarray(jax.device_get(tbl.table[0, 0, 0, :4]))  # force build
    entry["table_build_s"] = round(time.time() - t0, 1)
    entry["window_bits"] = tbl.window_bits
    entry["set_table_bytes"] = tbl.set_nbytes
    entry["pool_bytes"] = tbl.nbytes
    entry["mem_after_tables"] = mem_stats(dev)
    log(
        f"nval={nval}: {tbl.window_bits}-bit tables, "
        f"{tbl.set_nbytes/1e9:.2f} GB set / {tbl.nbytes/1e9:.2f} GB pool, "
        f"built in {entry['table_build_s']}s"
    )

    # the commit-shaped batch: nsig votes round-robin over the set
    rng = np.random.RandomState(3)
    msgs = [rng.bytes(110) for _ in range(nsig)]

    def run_once() -> float:
        bv = ShardedTpuBatchVerifier(device_min_batch=0)
        for i, m in enumerate(msgs):
            p = privs[i % nval]
            bv.add(p.pub_key(), m, p.sign(m))
        t0 = time.time()
        ok, bits = bv.verify()
        dt = time.time() - t0
        assert ok and all(bits), "sharded keyed verification failed"
        return dt

    t0 = time.time()
    first = run_once()
    entry["first_verify_s"] = round(first, 2)
    log(f"nval={nval}: first sharded verify (incl compile) {first:.1f}s")
    best = min(run_once() for _ in range(3))
    entry["steady_verify_s"] = round(best, 4)
    entry["steady_sigs_per_sec"] = round(nsig / best, 1)
    entry["mem_after_verify"] = mem_stats(dev)
    log(
        f"nval={nval}: steady {best*1e3:.1f} ms / {nsig} sigs "
        f"({nsig/best:,.0f} sigs/s) through the sharded seam"
    )
    return entry


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nvals", default="150,10000")
    ap.add_argument("--out", default=OUT)
    args = ap.parse_args()
    try:
        with open(args.out) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"results": []}
    for nval in [int(v) for v in args.nvals.split(",") if v]:
        # BASELINE: config 2 is one 150-val commit; config 5 is a 10k
        # mega-commit — nsig equals the validator count in both
        entry = probe_shape(nval, nsig=nval)
        entry["measured"] = time.strftime("round 5, %Y-%m-%d %H:%M")
        doc["results"] = [
            r for r in doc["results"] if r["nval"] != nval
        ] + [entry]
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, args.out)
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
