"""Coverage-guided fuzz soak runner (reference: test/fuzz/ CI targets).

    python tools/fuzz.py                     # all targets, 30s each
    python tools/fuzz.py --target ws_frame --time 600 --execs 2000000

New coverage-growing inputs land in tests/data/fuzz_corpus/<target>/
(check them in); crashes land in tests/data/fuzz_crashes/<target>/ and
exit nonzero — turn each into a regression test before clearing it.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests"),
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--target", action="append", default=None)
    ap.add_argument("--time", type=float, default=30.0,
                    help="seconds per target")
    ap.add_argument("--execs", type=int, default=1_000_000)
    args = ap.parse_args()

    from fuzz_targets import make_fuzzers

    failed = False
    for fz in make_fuzzers(args.target):
        rep = fz.run(max_execs=args.execs, time_budget_s=args.time)
        print(rep, flush=True)
        for c in rep.crashes:
            print(f"  CRASH {c}", flush=True)
            failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
