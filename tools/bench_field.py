"""Device throughput of the field core: mul / square chains.

Times a lax.fori_loop chain of dependent field ops at kernel batch
width, at two iteration counts; the difference cancels dispatch + link
RTT (axon's block_until_ready does not block).  Prints muls/s and the
implied effective element-ops/s for the MFU analysis.
"""

from __future__ import annotations

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from cometbft_tpu.ops import field as F

    dev = jax.devices()[0]
    print(f"device: {dev}")
    batch = 8192
    rng = np.random.RandomState(1)
    a = jnp.asarray(
        rng.randint(0, 1 << 10, size=(F.NLIMBS, batch)), dtype=F.DTYPE
    )

    def timed(fn, x, trials=3):
        np.asarray(fn(x))
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            np.asarray(fn(x))
            best = min(best, time.perf_counter() - t0)
        return best

    def bench(name, body, k=1 << 9, est_ops=None):
        def make(iters):
            @jax.jit
            def run(x):
                v = jax.lax.fori_loop(0, iters, lambda _, v: body(v), x)
                return v[:, :4]

            return run

        t1 = timed(make(k), a)
        t4 = timed(make(4 * k), a)
        dt = max(t4 - t1, 1e-9)
        rate = 3 * k * batch / dt  # lane-ops/s
        line = (
            f"{name:18s} {rate / 1e6:9.1f} M/s "
            f"(K={t1 * 1e3:.1f} ms, 4K={t4 * 1e3:.1f} ms)"
        )
        if est_ops:
            line += f"  ~{rate * est_ops / 1e12:.3f} Tops/s eff"
        print(line)
        return rate

    mul_rate = bench("field.mul", lambda v: F.mul(v, v + 1), est_ops=2800)
    sq_rate = bench("field.square", F.square, est_ops=1900)
    bench("mul(a,a) (ref)", lambda v: F.mul(v, v), est_ops=2800)
    print(f"square/mul speedup: {sq_rate / mul_rate:.2f}x")


if __name__ == "__main__":
    main()
