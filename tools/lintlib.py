"""lintlib: the shared machinery behind the repo's static lints.

Four AST lints enforce the codebase's documented disciplines —
lockcheck (guarded-by), jitcheck (device plane), determcheck
(replay determinism), hotpathcheck (critical-path blocking) — plus
envcheck (knob registry) and metrics_lint (series registry).  They
all share one grammar:

* **Waivers** are trailing comments of the form ``# <tag>: <reason>``
  (``# unguarded:``, ``# host sync:``, ``# deterministic:``,
  ``# blocking ok:``, ``# env ok:``).  A waiver silences exactly the
  flagged site on its own line, is counted, and is listed by ``-v``
  so the audit trail stays visible.

* **Stale-waiver inverse check.**  A waiver comment on a line with no
  flagged site is itself an error — annotations cannot outlive the
  code they audit.

* **Fixture-tree runner.**  Every lint exposes
  ``check_source(source, rel)`` (unit-testable on fixture strings) and
  ``check_tree(root)`` (the repo gate), built on :func:`iter_py_files`.

* **Repo-gate entrypoint.**  ``main(argv)`` prints violations to
  stderr, waivers under ``-v``, a one-line summary, and exits 0/1 —
  uniform across tools so Makefile targets and tests/test_*.py gates
  treat them interchangeably.

This module is import-side-effect free (no jax, no cometbft_tpu): a
lint must be able to judge the tree without executing it.
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field, fields

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default package scanned by every lint's repo gate
SCAN_ROOT = "cometbft_tpu"


@dataclass
class Violation:
    file: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.message}"


@dataclass
class Waiver:
    file: str
    line: int
    site: str
    reason: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.site} — {self.reason}"


@dataclass
class Report:
    """Base report: violations + waivers + ``ok``.  Lints subclass and
    add integer counters; :meth:`merge` folds those in generically so
    subclasses don't hand-roll it."""

    violations: list[Violation] = field(default_factory=list)
    waivers: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def merge(self, other: "Report") -> None:
        self.violations.extend(other.violations)
        self.waivers.extend(other.waivers)
        for f in fields(self):
            if f.name in ("violations", "waivers"):
                continue
            mine = getattr(self, f.name)
            if isinstance(mine, int):
                setattr(self, f.name, mine + getattr(other, f.name))
            elif isinstance(mine, set):
                mine.update(getattr(other, f.name))


# -- memoized parse layer (tools/lint_all.py) ---------------------------
#
# Both caches key on source CONTENT: ast.parse and the comment map are
# pure functions of it, and no lint mutates the returned tree/dict —
# so when all six lints run in one process (tools/lint_all.py, `make
# lint`, the metrics_lint gate) each file is parsed and tokenized
# once instead of once per lint.  SyntaxError is deliberately NOT
# cached: every caller handles it per-file and failures are rare.

_PARSE_CACHE: dict[str, ast.Module] = {}
_COMMENT_CACHE: dict[str, dict[int, str]] = {}


def parse_cached(source: str) -> ast.Module:
    """``ast.parse`` memoized on source content (raises SyntaxError
    like the original).  Treat the returned tree as read-only."""
    tree = _PARSE_CACHE.get(source)
    if tree is None:
        tree = ast.parse(source)
        _PARSE_CACHE[source] = tree
    return tree


def comments_by_line(source: str) -> dict[int, str]:
    """Map line number -> comment text (tokenize survives the partial
    trees fixtures throw at it; a tokenize error just yields fewer
    comments, never a crash).  Memoized on content — treat the
    returned dict as read-only."""
    cached = _COMMENT_CACHE.get(source)
    if cached is not None:
        return cached
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    _COMMENT_CACHE[source] = out
    return out


def waiver_re(tag: str) -> re.Pattern:
    """The shared waiver grammar: ``# <tag>: <reason>`` with a
    mandatory non-empty reason.  ``tag`` may contain spaces
    (``host sync``, ``blocking ok``); internal whitespace is matched
    loosely so ``#host  sync:`` still counts."""
    toks = r"\s+".join(re.escape(t) for t in tag.split())
    return re.compile(rf"#\s*{toks}:\s*(\S.*)")


def dotted(node: ast.expr) -> str:
    """``jax.debug.callback`` -> "jax.debug.callback"; "" otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def check_stale_waivers(
    comments: dict[int, str],
    flagged_lines: set[int],
    pattern: re.Pattern,
    rel: str,
    report: Report,
    tag: str,
) -> None:
    """The inverse check: a waiver comment on a line where the lint
    found nothing to waive is an error."""
    for line, comment in comments.items():
        if pattern.search(comment) and line not in flagged_lines:
            report.violations.append(
                Violation(
                    rel, line,
                    f"stale '# {tag}:' waiver — no flagged site on this "
                    "line; delete the waiver or restore the audited call",
                )
            )


def iter_py_files(root: str = SCAN_ROOT):
    """Yield ``(rel, source)`` for every .py under REPO/root, sorted,
    skipping __pycache__ — the fixture-tree runner every lint's
    ``check_tree`` is built on."""
    base = os.path.join(REPO, root)
    for dirpath, dirnames, names in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for n in sorted(names):
            if not n.endswith(".py"):
                continue
            path = os.path.join(dirpath, n)
            rel = os.path.relpath(path, REPO)
            with open(path, encoding="utf-8") as fh:
                yield rel, fh.read()


# -- intra-repo call graph (determcheck / hotpathcheck) -----------------
#
# Name-matching over-approximation: an edge exists from function F to
# every indexed def whose basename matches a name F calls (plain
# ``name(...)``, ``obj.name(...)``, and ``ClassName(...)`` via
# ``ClassName.__init__``).  Deliberately unsound-in-the-precise-sense
# and complete-in-the-useful-sense: anything actually reachable is
# reachable in the graph, the cost being extra reachable functions —
# which the waiver grammar and per-lint stop sets keep bounded.


class FuncInfo:
    """One indexed function: where it lives and what names it calls."""

    __slots__ = ("rel", "qualname", "node", "lineno", "calls")

    def __init__(self, rel: str, qualname: str, node: ast.AST):
        self.rel = rel
        self.qualname = qualname
        self.node = node
        self.lineno = node.lineno
        self.calls = _call_names(node)

    @property
    def basename(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


def _call_names(fn_node: ast.AST) -> set[str]:
    names: set[str] = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Name):
                names.add(n.func.id)
            elif isinstance(n.func, ast.Attribute):
                names.add(n.func.attr)
    return names


class CallGraph:
    """Call graph over a set of parsed files, keyed ``(rel, qualname)``
    with qualnames ``func`` / ``Class.method``."""

    def __init__(self, files):
        self.funcs: dict[tuple[str, str], FuncInfo] = {}
        self.by_name: dict[str, list[tuple[str, str]]] = {}
        for rel, source in files:
            try:
                tree = parse_cached(source)
            except SyntaxError:
                continue
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add(rel, node.name, node, node.name)
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            qual = f"{node.name}.{item.name}"
                            # a ClassName(...) call reaches the ctor
                            alias = (
                                node.name
                                if item.name in ("__init__", "__post_init__")
                                else item.name
                            )
                            self._add(rel, qual, item, alias)

    def _add(self, rel: str, qualname: str, node: ast.AST, name: str) -> None:
        key = (rel, qualname)
        self.funcs[key] = FuncInfo(rel, qualname, node)
        self.by_name.setdefault(name, []).append(key)
        base = qualname.rsplit(".", 1)[-1]
        # ctors are reachable ONLY via their ClassName(...) alias: a
        # bare ``super().__init__()`` call would otherwise edge into
        # every constructor in the scan set
        if base != name and base not in ("__init__", "__post_init__"):
            self.by_name.setdefault(base, []).append(key)

    def reachable(
        self,
        roots,
        stops: frozenset[str] = frozenset(),
    ) -> dict[tuple[str, str], tuple[str, str] | None]:
        """BFS closure from ``roots`` (iterable of (rel, qualname)
        keys).  Returns key -> parent key (None for roots) — the
        parent chain is the "why is this on the path" explanation.
        ``stops`` are callee basenames never traversed into
        (diagnostics planes, audited boundaries)."""
        parents: dict[tuple[str, str], tuple[str, str] | None] = {}
        queue: list[tuple[str, str]] = []
        for root in roots:
            if root in self.funcs and root not in parents:
                parents[root] = None
                queue.append(root)
        while queue:
            key = queue.pop(0)
            for name in sorted(self.funcs[key].calls):
                if name in stops:
                    continue
                for tgt in self.by_name.get(name, ()):
                    if tgt not in parents:
                        parents[tgt] = key
                        queue.append(tgt)
        return parents

    def chain(self, parents, key, limit: int = 6) -> str:
        """``root → … → key`` qualname chain for violation messages."""
        names: list[str] = []
        cur = key
        while cur is not None and len(names) < limit:
            names.append(self.funcs[cur].qualname)
            cur = parents.get(cur)
        if cur is not None:
            names.append("…")
        return " ← ".join(names)


def run_main(
    tool: str,
    check_tree,
    summary,
    argv: list[str] | None = None,
) -> int:
    """The shared repo-gate entrypoint: violations to stderr, waivers
    under ``-v``, ``summary(report)`` one-liner when clean, exit 0/1."""
    argv = sys.argv[1:] if argv is None else argv
    verbose = "-v" in argv
    report = check_tree()
    for v in report.violations:
        print(f"{tool}: {v}", file=sys.stderr)
    if verbose:
        for w in report.waivers:
            print(f"{tool}: waiver: {w}")
    if report.ok:
        print(f"{tool}: {summary(report)}")
        return 0
    print(
        f"{tool}: {len(report.violations)} violations "
        f"({len(report.waivers)} waivers)",
        file=sys.stderr,
    )
    return 1
