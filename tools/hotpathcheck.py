"""hotpathcheck: static critical-path blocking lint — determcheck's
latency-plane sibling (same CallGraph, different question).

determcheck asks "is the transition pure?"; hotpathcheck asks "does the
commit pipeline ever stall on something it shouldn't?".  The height
SLO (docs/observability.md, the attribution ledger) bills every
committed height to the stage taxonomy in
``cometbft_tpu/utils/critpath.py`` — a blocking call on a consensus
step handler that is NOT billed to a stage is invisible latency: it
shows up as ``residual`` in perfdiff with no owner.  This lint walks
the intra-repo call graph from the critical-path roots
(``HOTPATH_ROOTS``: the consensus step handlers, the reactor receive
path, block persistence, the WAL write/fsync family) and flags
blocking *sites* in everything reachable:

* **sleep** — ``time.sleep()`` (the step loop must never nap);
* **subprocess** — ``subprocess.*`` / ``os.system`` / ``os.popen``;
* **HTTP** — ``requests.`` / ``urllib.`` / ``http.`` / ``httpx.``;
* **socket I/O** — ``socket.create_connection``, ``.sendall()``,
  ``.recv()``, ``.connect()`` (the ABCI round-trip is the one audited
  exception — it IS the ``abci_execute`` stage);
* **disk barriers** — ``os.fsync`` / ``.fsync()`` / ``.sync()`` and
  ``open()`` (the WAL head fsync IS the ``wal_fsync`` stage);
* **unbounded waits** — ``.wait()`` / ``.acquire()`` with no timeout
  (a bounded ``wait(timeout=...)`` passes; an unbounded one can hold
  the step mutex forever);
* **stdin** — ``input()``.

A site is silenced by ``# blocking ok: <stage> — <reason>`` where
``<stage>`` MUST be a stage name from ``critpath.STAGES`` — the waiver
is the billing record: it says "this block is already measured as that
ledger column".  A waiver naming an unknown stage is a violation; a
waiver on a line with no flagged site is a STALE-WAIVER error.  The
call graph is the lintlib name-matching over-approximation, bounded by
``GRAPH_STOPS`` (diagnostics planes and background-thread entrypoints
that the step loop only *signals*, never joins).

    python tools/hotpathcheck.py        # exit 0 clean, 1 with a report
    python tools/hotpathcheck.py -v     # also list waivers

Run in the tier-1 flow via tests/test_hotpathcheck.py and standalone
via ``make hotpathcheck``; tools/metrics_lint.py main() gates on it.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    CallGraph,
    Violation,
    Waiver,
    check_stale_waivers,
    comments_by_line,
    dotted,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

#: mirror of cometbft_tpu/utils/critpath.py STAGES — kept in lockstep
#: by tests/test_hotpathcheck.py (the jitcheck DTYPES_OK pattern).
#: Mirrored rather than imported so the lint stays runnable on a
#: checkout where the package itself fails to import.
STAGES_OK = frozenset(
    {
        "proposal_wait", "gossip_hop", "verify_spec", "verify_launch",
        "quorum_wait", "store_save", "wal_fsync", "abci_execute",
        "index", "residual",
    }
)

#: packages whose call graph the walk covers — same boundary as
#: determcheck (crypto/ops/parallel route work to device queues and are
#: billed by the dispatch plane; utils/ is host plumbing).
SCAN_DIRS = (
    "cometbft_tpu/abci",
    "cometbft_tpu/consensus",
    "cometbft_tpu/evidence",
    "cometbft_tpu/mempool",
    "cometbft_tpu/state",
    "cometbft_tpu/store",
    "cometbft_tpu/types",
    "cometbft_tpu/wal",
)

#: the registered critical-path roots: everything the commit pipeline
#: executes synchronously between "message arrives" and "height
#: committed".  check_tree errors if one stops resolving.
HOTPATH_ROOTS = (
    ("cometbft_tpu/consensus/state.py", "ConsensusState._handle_msg"),
    ("cometbft_tpu/consensus/state.py", "ConsensusState._handle_timeout"),
    ("cometbft_tpu/consensus/state.py", "ConsensusState._finalize_commit"),
    ("cometbft_tpu/consensus/reactor.py", "ConsensusReactor.receive"),
    ("cometbft_tpu/store/__init__.py", "BlockStore.save_block"),
    ("cometbft_tpu/wal/__init__.py", "WAL.write"),
    ("cometbft_tpu/wal/__init__.py", "WAL.write_sync"),
    ("cometbft_tpu/wal/__init__.py", "WAL.write_end_height"),
)

#: callee names the walk never follows.  Diagnostics planes (their
#: sinks run on background threads), service lifecycle (the step loop
#: signals services, never joins them), and stdlib-ish over-matchers.
GRAPH_STOPS = frozenset(
    {
        # flight recorder / tracer / metrics / logger
        "record", "format_tail", "span", "add_complete", "observe",
        "observe_height", "inc", "dec", "set", "labels", "remove",
        "info", "debug", "error", "warning", "with_fields",
        # event bus + pubsub fan-out (subscribers are off-path)
        "publish", "publish_new_block", "publish_new_block_events",
        "publish_tx_event", "publish_validator_set_updates", "fire",
        # service lifecycle + thread plumbing
        "start", "stop", "is_running", "quit_event",
        # stdlib-ish names that would wildly over-match
        "get", "put", "append", "extend", "pop", "items", "keys",
        "values", "join", "split", "strip", "encode_varint", "read",
        "close", "flush",
    }
)

_WAIVER_RE = waiver_re("blocking ok")

#: dotted prefixes that make network round-trips
_HTTP_PREFIXES = ("requests.", "urllib.", "urllib2.", "http.", "httpx.")

#: attribute basenames that are socket I/O on an established connection
_SOCKET_IO = frozenset({"sendall", "recv", "connect", "create_connection"})

#: attribute basenames that are disk write barriers
_DISK_BARRIER = frozenset({"fsync", "sync"})


@dataclass
class Report(lintlib.Report):
    roots: int = 0
    reachable: int = 0
    sites: int = 0


def _has_timeout(call: ast.Call) -> bool:
    """True when a wait/acquire call is bounded — any positional arg
    (Event.wait(t) / Lock.acquire(True, t)) or a timeout= kwarg."""
    if call.args:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def _detect_sites(fn: ast.AST) -> list[tuple[int, str]]:
    """All blocking sites in one function body (nested defs included —
    a closure the handler invokes inline still blocks the step loop)."""
    sites: list[tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        d = dotted(node.func)
        base = d.split(".")[-1] if d else ""
        if d in ("time.sleep", "sleep"):
            sites.append((node.lineno, "sleep() on the step loop"))
        elif d.startswith("subprocess.") or d in ("os.system", "os.popen"):
            sites.append((node.lineno, f"subprocess spawn {d}()"))
        elif d.startswith(_HTTP_PREFIXES):
            sites.append((node.lineno, f"HTTP round-trip {d}()"))
        elif d == "socket.create_connection" or (
            isinstance(node.func, ast.Attribute) and base in _SOCKET_IO
        ):
            sites.append((node.lineno, f"socket I/O .{base}()"))
        elif d == "os.fsync" or (
            isinstance(node.func, ast.Attribute) and base in _DISK_BARRIER
        ):
            sites.append((node.lineno, f"disk barrier {d or base}()"))
        elif isinstance(node.func, ast.Name) and node.func.id == "open":
            sites.append((node.lineno, "file open()"))
        elif isinstance(node.func, ast.Name) and node.func.id == "input":
            sites.append((node.lineno, "stdin read input()"))
        elif (
            isinstance(node.func, ast.Attribute)
            and base in ("wait", "acquire")
            and not _has_timeout(node)
        ):
            sites.append(
                (node.lineno,
                 f"unbounded .{base}() — no timeout, can hold the "
                 "step loop forever")
            )
    return sites


def _check_files(files: list[tuple[str, str]], report: Report) -> None:
    graph = CallGraph(files)
    roots = [r for r in HOTPATH_ROOTS if r in graph.funcs]
    report.roots += len(roots)
    parents = graph.reachable(roots, stops=GRAPH_STOPS)
    report.reachable += len(parents)

    comments = {rel: comments_by_line(src) for rel, src in files}
    flagged: dict[str, set[int]] = {rel: set() for rel, _ in files}
    waived: dict[str, set[int]] = {rel: set() for rel, _ in files}

    for key, info in graph.funcs.items():
        sites = _detect_sites(info.node)
        if not sites:
            continue
        flagged[info.rel].update(line for line, _ in sites)
        if key not in parents:
            continue  # blocking pattern present but off the hot path
        for line, site in sites:
            report.sites += 1
            m = _WAIVER_RE.search(comments[info.rel].get(line, ""))
            if m:
                reason = m.group(1).strip()
                stage = reason.split()[0].rstrip(":—-") if reason else ""
                if stage not in STAGES_OK:
                    report.violations.append(
                        Violation(
                            info.rel, line,
                            f"'# blocking ok:' waiver names unknown "
                            f"stage {stage!r} — the waiver is a billing"
                            " record and must start with a stage from "
                            "cometbft_tpu/utils/critpath.py STAGES "
                            f"({', '.join(sorted(STAGES_OK))})",
                        )
                    )
                elif line not in waived[info.rel]:
                    waived[info.rel].add(line)
                    report.waivers.append(
                        Waiver(info.rel, line, site, reason)
                    )
                continue
            report.violations.append(
                Violation(
                    info.rel, line,
                    f"{site} in {info.qualname}() on the critical path "
                    f"({graph.chain(parents, key)}) — the commit "
                    "pipeline must not stall on unbilled work; move it "
                    "to a background thread or waive with "
                    "'# blocking ok: <stage> — <reason>' naming the "
                    "critpath stage that already measures it",
                )
            )

    for rel, _src in files:
        check_stale_waivers(
            comments[rel], flagged[rel], _WAIVER_RE, rel, report,
            "blocking ok",
        )


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source (fixtures): roots are matched against
    ``rel``, so a fixture posing as cometbft_tpu/wal/__init__.py with a
    ``class WAL: def write_sync`` exercises the real root set."""
    report = Report()
    _check_files([(rel, source)], report)
    return report


def check_tree(root: str | None = None) -> Report:
    report = Report()
    files: list[tuple[str, str]] = []
    if root is not None:
        files = list(iter_py_files(root))
    else:
        for d in SCAN_DIRS:
            files.extend(iter_py_files(d))
    seen = {rel for rel, _ in files}
    for rel, qual in HOTPATH_ROOTS:
        if rel not in seen:
            report.violations.append(
                Violation(rel, 0, f"HOTPATH_ROOTS file missing "
                                  f"(root {qual})")
            )
    _check_files(files, report)
    resolved = CallGraph(files).funcs.keys()
    for key in sorted((rel, q) for rel, q in HOTPATH_ROOTS if rel in seen):
        if key not in resolved:
            report.violations.append(
                Violation(
                    key[0], 0,
                    f"hot-path root {key[1]} no longer resolves — "
                    "update HOTPATH_ROOTS (tools/hotpathcheck.py) to "
                    "the renamed critical-path entrypoint",
                )
            )
    return report


def _summary(report: Report) -> str:
    return (
        f"{report.reachable} functions reachable from {report.roots} "
        f"critical-path roots; {report.sites} blocking sites "
        f"({len(report.waivers)} stage-billed waivers)"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("hotpathcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
