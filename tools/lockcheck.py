"""lockcheck: static guarded-by lint — the compile-time half of the
concurrency toolchain (runtime half: CMT_TPU_LOCKGRAPH / CMT_TPU_RACE
in cometbft_tpu/utils/sync.py; docs/concurrency.md is the manual).

The reference keeps its threaded core honest with ``go test -race``
and go-deadlock; neither exists for Python, so this AST pass enforces
the documented locking discipline instead:

1. **Guarded-field check.**  A class declares which lock protects
   which attribute, either with a trailing ``# guarded by <lock>``
   comment on the ``self.<field> = ...`` assignment or with a
   class-level ``_GUARDED_BY = {"field": "_lock"}`` registry (the
   registry also feeds the runtime race checker via
   ``@cmtsync.guarded``).  Every ``self.<field>`` access in the class
   must then occur lexically inside a ``with self.<lock>:`` block, or
   in a method whose ``def`` line (or the line above it) carries a
   ``# holds <lock>`` marker (the caller-holds-lock contract), or on
   a line carrying an explicit ``# unguarded: <reason>`` waiver —
   waivers are counted and reported so they stay auditable.
   ``__init__`` bodies are exempt (the object cannot have escaped).
   ``with self.<cond>:`` counts for the lock when the class creates
   ``self.<cond> = threading.Condition(self.<lock>)``.

2. **Inverse annotation check.**  An annotation naming a lock
   attribute the class never assigns is an error — a typo'd guard
   name would otherwise silently verify nothing.

3. **Seam check.**  Raw ``threading.Lock()`` / ``threading.RLock()``
   construction in core packages bypasses the ``cmtsync`` seam, so
   the deadlock watchdog, the lock-order graph, and race mode cannot
   see those locks.  Only the audited leaf-lock files in
   ``RAW_LOCK_OK`` (fine-grained locks under which no other lock is
   ever acquired — see docs/concurrency.md) may construct raw locks.

Known static limits (the runtime modes cover these): accesses through
a non-``self`` receiver (``other._field``), dynamic ``getattr``, and
callers of a ``# holds`` method are not verified.

    python tools/lockcheck.py           # exit 0 clean, 1 with a report
    python tools/lockcheck.py -v        # also list waivers

Run in the tier-1 flow via tests/test_lockcheck.py and standalone via
``make lockcheck``; tools/metrics_lint.py main() gates on it too.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    SCAN_ROOT,
    Violation,
    comments_by_line as _comments_by_line,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

#: audited leaf-lock files allowed to construct raw threading locks:
#: the seam itself, plus fine-grained primitives whose locks are never
#: held across another acquire (see docs/concurrency.md "leaf locks")
RAW_LOCK_OK = frozenset(
    {
        os.path.join("cometbft_tpu", "utils", "sync.py"),
        os.path.join("cometbft_tpu", "utils", "log.py"),
        os.path.join("cometbft_tpu", "utils", "metrics.py"),
        os.path.join("cometbft_tpu", "utils", "trace.py"),
        os.path.join("cometbft_tpu", "utils", "flowrate.py"),
        os.path.join("cometbft_tpu", "utils", "bit_array.py"),
        os.path.join("cometbft_tpu", "utils", "native_build.py"),
        os.path.join("cometbft_tpu", "utils", "kv_native.py"),
        os.path.join("cometbft_tpu", "utils", "service.py"),
    }
)

_GUARDED_RE = re.compile(r"#\s*guarded\s+by\s+([A-Za-z_]\w*)")
_HOLDS_RE = re.compile(r"#\s*(?:caller[\s-]holds|holds)[:\s]+([A-Za-z_]\w*)")
_WAIVER_RE = waiver_re("unguarded")


@dataclass
class Waiver:
    file: str
    line: int
    cls: str
    field: str
    reason: str

    def __str__(self) -> str:
        return (
            f"{self.file}:{self.line}: {self.cls}.{self.field} "
            f"unguarded — {self.reason}"
        )


@dataclass
class Report(lintlib.Report):
    guarded_fields: int = 0
    classes: int = 0


def _is_lock_ctor(node: ast.expr) -> bool:
    """``cmtsync.Mutex()`` / ``Mutex()`` / ``threading.Lock()`` etc."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    return name in {"Mutex", "RMutex", "Lock", "RLock"}


def _is_raw_lock_ctor(node: ast.expr) -> bool:
    """``threading.Lock()`` / ``threading.RLock()`` specifically."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        return fn.value.id == "threading" and fn.attr in {"Lock", "RLock"}
    return False


def _self_attr(node: ast.expr) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _condition_alias(node: ast.expr) -> str | None:
    """RHS ``threading.Condition(self.<lock>)`` -> the lock attr."""
    if not (isinstance(node, ast.Call) and node.args):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else ""
    )
    if name != "Condition":
        return None
    return _self_attr(node.args[0])


class _ClassChecker:
    def __init__(
        self,
        rel: str,
        cls: ast.ClassDef,
        comments: dict[int, str],
        report: Report,
    ):
        self.rel = rel
        self.cls = cls
        self.comments = comments
        self.report = report
        self.guarded: dict[str, str] = {}       # field -> lock attr
        self.guard_lines: dict[str, int] = {}   # field -> annotation line
        self.assigned_attrs: set[str] = set()   # every self.X = ... target
        self.cond_alias: dict[str, str] = {}    # cond attr -> lock attr

    def run(self) -> None:
        self._collect()
        if not self.guarded:
            return
        self.report.classes += 1
        self.report.guarded_fields += len(self.guarded)
        self._check_inverse()
        for item in self.cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if item.name == "__init__":
                    continue
                self._check_method(item)

    # -- annotation collection -----------------------------------------

    def _collect(self) -> None:
        for node in ast.walk(self.cls):
            # registry: _GUARDED_BY = {"field": "_mtx", ...}
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Name)
                        and tgt.id == "_GUARDED_BY"
                        and isinstance(node.value, ast.Dict)
                    ):
                        for k, v in zip(node.value.keys, node.value.values):
                            if isinstance(k, ast.Constant) and isinstance(
                                v, ast.Constant
                            ):
                                self.guarded[str(k.value)] = str(v.value)
                                self.guard_lines[str(k.value)] = node.lineno
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], None
            for tgt in targets:
                # tuple targets: self._a, self._b = ...
                elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                for el in elts:
                    attr = _self_attr(el)
                    if attr is None:
                        continue
                    self.assigned_attrs.add(attr)
                    comment = self.comments.get(el.lineno, "")
                    m = _GUARDED_RE.search(comment)
                    if m:
                        self.guarded[attr] = m.group(1)
                        self.guard_lines[attr] = el.lineno
            if value is not None and targets:
                alias = _condition_alias(value)
                attr = _self_attr(targets[0])
                if alias and attr:
                    self.cond_alias[attr] = alias

    def _check_inverse(self) -> None:
        for fname, lock in sorted(self.guarded.items()):
            if lock not in self.assigned_attrs:
                self.report.violations.append(
                    Violation(
                        self.rel,
                        self.guard_lines.get(fname, self.cls.lineno),
                        f"{self.cls.name}.{fname} is annotated "
                        f"'guarded by {lock}' but the class never "
                        f"creates self.{lock}",
                    )
                )

    # -- per-method access verification --------------------------------

    def _holds_marker(self, fn: ast.FunctionDef) -> set[str]:
        """``# holds <lock>`` on the line above ``def``, or anywhere on
        the (possibly multi-line) signature up to the first body
        statement."""
        held: set[str] = set()
        body_start = fn.body[0].lineno if fn.body else fn.lineno + 1
        for line in range(fn.lineno - 1, body_start):
            m = _HOLDS_RE.search(self.comments.get(line, ""))
            if m:
                held.add(m.group(1))
        return held

    def _check_method(self, fn: ast.FunctionDef) -> None:
        base_held = self._holds_marker(fn)
        self._walk(fn.body, base_held, fn.name)

    def _resolve(self, attr: str) -> str:
        """A with-context attr: the lock itself, or a Condition alias."""
        return self.cond_alias.get(attr, attr)

    def _walk(self, body: list[ast.stmt], held: set[str], where: str) -> None:
        for stmt in body:
            self._visit(stmt, held, where)

    def _visit(self, node: ast.AST, held: set[str], where: str) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def is (potentially) deferred — a thread target
            # or callback runs WITHOUT the enclosing with-block's lock,
            # so it starts from only its own `# holds` markers
            for default in node.args.defaults + node.args.kw_defaults:
                if default is not None:
                    self._visit(default, held, where)
            self._walk(node.body, self._holds_marker(node), node.name)
            return
        if isinstance(node, ast.Lambda):
            self._visit(node.body, set(), f"{where}.<lambda>")
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    inner.add(self._resolve(attr))
            for expr in (i.context_expr for i in node.items):
                self._visit(expr, held, where)
            self._walk(node.body, inner, where)
            return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and attr in self.guarded:
                self._check_access(node, attr, held, where)
            # keep walking (chained attributes)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, where)

    def _check_access(
        self, node: ast.Attribute, attr: str, held: set[str], where: str
    ) -> None:
        lock = self.guarded[attr]
        if lock in held:
            return
        m = _WAIVER_RE.search(self.comments.get(node.lineno, ""))
        if m:
            self.report.waivers.append(
                Waiver(
                    self.rel, node.lineno, self.cls.name, attr,
                    m.group(1).strip(),
                )
            )
            return
        kind = (
            "written" if isinstance(node.ctx, (ast.Store, ast.Del))
            else "read"
        )
        self.report.violations.append(
            Violation(
                self.rel,
                node.lineno,
                f"{self.cls.name}.{attr} (guarded by {lock}) {kind} in "
                f"{where}() without holding self.{lock} — wrap in "
                f"'with self.{lock}:', mark the method '# holds {lock}', "
                "or waive with '# unguarded: <reason>'",
            )
        )


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source; ``rel`` is the path used in reports."""
    report = Report()
    try:
        tree = lintlib.parse_cached(source)
    except SyntaxError as exc:
        report.violations.append(
            Violation(rel, exc.lineno or 0, f"syntax error: {exc.msg}")
        )
        return report
    comments = _comments_by_line(source)

    if rel not in RAW_LOCK_OK:
        for node in ast.walk(tree):
            if _is_raw_lock_ctor(node):
                report.violations.append(
                    Violation(
                        rel,
                        node.lineno,
                        "raw threading.Lock()/RLock() bypasses the "
                        "cmtsync seam (deadlock watchdog, lock-order "
                        "graph, and race mode cannot see it) — use "
                        "cmtsync.Mutex()/RMutex(), or add this audited "
                        "leaf-lock file to RAW_LOCK_OK",
                    )
                )

    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            _ClassChecker(rel, node, comments, report).run()
    return report


def check_tree(root: str = SCAN_ROOT) -> Report:
    report = Report()
    for rel, source in iter_py_files(root):
        report.merge(check_source(source, rel))
    return report


def _summary(report: Report) -> str:
    return (
        f"{report.guarded_fields} guarded fields across "
        f"{report.classes} classes verified; "
        f"{len(report.waivers)} audited unguarded waivers"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("lockcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
