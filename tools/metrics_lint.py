"""metrics-lint: every metric field registered in cometbft_tpu/metrics
must be referenced by at least one subsystem.

The structs in cometbft_tpu/metrics/__init__.py are hand-maintained
(the reference generates them with metricsgen); a field that is
registered but never updated exposes a permanently-zero series — worse
than no series, because dashboards and alerts trust it.  This checker
instantiates every struct in no-op mode to enumerate the registered
field names, then requires an ``.<field>`` attribute reference
somewhere in the package outside the metrics module itself.

It is a tripwire, not a proof: a generic name like ``size`` is
trivially satisfied by unrelated attribute access.  New metric names
are deliberately specific (``key_pool_retraces``), which is where the
check has teeth.

    python tools/metrics_lint.py        # exit 0 clean, 1 with a report

Run in the tier-1 flow via tests/test_metrics.py::TestMetricsLint and
standalone via ``make metrics-lint``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: subsystem code scanned for references (tools/ and bench drivers
#: count: the campaign/bench planes update crypto metrics too)
SCAN_ROOTS = ("cometbft_tpu", "tools", "bench.py", "bench_all.py")
#: the registration site itself never counts as a reference
EXCLUDE = (os.path.join("cometbft_tpu", "metrics", "__init__.py"),)


def registered_fields() -> dict[str, list[str]]:
    """field name -> metric struct(s) registering it."""
    import cometbft_tpu.metrics as M

    out: dict[str, list[str]] = {}
    for cls in (
        M.ConsensusMetrics,
        M.MempoolMetrics,
        M.P2PMetrics,
        M.StateMetrics,
        M.CryptoMetrics,
    ):
        for name in vars(cls(None)):
            out.setdefault(name, []).append(cls.__name__)
    return out


def _scan_files() -> list[tuple[str, str]]:
    files: list[tuple[str, str]] = []
    for root in SCAN_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            files.append((root, open(path).read()))
            continue
        for dirpath, _, names in os.walk(path):
            for n in names:
                if not n.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, n), REPO)
                if rel in EXCLUDE:
                    continue
                files.append((rel, open(os.path.join(dirpath, n)).read()))
    return files


def find_unreferenced() -> dict[str, list[str]]:
    """Registered fields with no ``.<field>`` reference in any
    subsystem — empty dict when the lint is clean."""
    fields = registered_fields()
    blobs = _scan_files()
    missing: dict[str, list[str]] = {}
    for field, owners in sorted(fields.items()):
        pat = re.compile(r"\." + re.escape(field) + r"\b")
        if not any(pat.search(text) for _, text in blobs):
            missing[field] = owners
    return missing


def main() -> int:
    missing = find_unreferenced()
    if not missing:
        print(f"metrics-lint: {len(registered_fields())} fields, all "
              "referenced")
        return 0
    for field, owners in missing.items():
        print(
            f"metrics-lint: {'/'.join(owners)}.{field} is registered "
            "but never referenced by any subsystem",
            file=sys.stderr,
        )
    return 1


if __name__ == "__main__":
    sys.exit(main())
