"""metrics-lint: the registered metric fields and the update sites in
subsystem code must agree, in BOTH directions.

The structs in cometbft_tpu/metrics/__init__.py are hand-maintained
(the reference generates them with metricsgen), so two failure modes
exist:

- **registered, never updated** — a permanently-zero series; worse
  than no series, because dashboards and alerts trust it.  Checked by
  ``find_unreferenced``: every field enumerated from the no-op structs
  needs an ``.<field>`` attribute reference somewhere in the package
  outside the metrics module itself.
- **updated, never registered** — the inverse: an update site whose
  field name matches nothing any struct registers (a typo, or a field
  deleted while its call sites survive) silently updates a fresh
  ``_Nop``/attribute and no series ever appears.  Checked by
  ``find_unregistered``: every update-shaped attribute chain
  (``.name.inc(`` / ``.observe(`` / ``.labels(`` / ``.set(<args>)``)
  must resolve to a registered field.

Both are tripwires, not proofs: a generic name like ``size`` is
trivially satisfied by unrelated attribute access.  New metric names
are deliberately specific (``key_pool_retraces``), which is where the
checks have teeth.

    python tools/metrics_lint.py        # exit 0 clean, 1 with a report

Run in the tier-1 flow via tests/test_metrics.py::TestMetricsLint and
standalone via ``make metrics-lint``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: subsystem code scanned for references (tools/ and bench drivers
#: count: the campaign/bench planes update crypto metrics too)
SCAN_ROOTS = ("cometbft_tpu", "tools", "bench.py", "bench_all.py")
#: the registration site itself never counts as a reference, and this
#: checker's own pattern literals must not feed the inverse scan
EXCLUDE = (
    os.path.join("cometbft_tpu", "metrics", "__init__.py"),
    os.path.join("tools", "metrics_lint.py"),
)


def registered_fields() -> dict[str, list[str]]:
    """field name -> metric struct(s) registering it."""
    import cometbft_tpu.metrics as M

    out: dict[str, list[str]] = {}
    for cls in (
        M.ConsensusMetrics,
        M.MempoolMetrics,
        M.P2PMetrics,
        M.StateMetrics,
        M.CryptoMetrics,
        M.RPCMetrics,
        M.EventBusMetrics,
    ):
        for name in vars(cls(None)):
            out.setdefault(name, []).append(cls.__name__)
    return out


def _scan_files() -> list[tuple[str, str]]:
    files: list[tuple[str, str]] = []
    for root in SCAN_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            files.append((root, open(path).read()))
            continue
        for dirpath, _, names in os.walk(path):
            for n in names:
                if not n.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, n), REPO)
                if rel in EXCLUDE:
                    continue
                files.append((rel, open(os.path.join(dirpath, n)).read()))
    return files


def find_unreferenced() -> dict[str, list[str]]:
    """Registered fields with no ``.<field>`` reference in any
    subsystem — empty dict when the lint is clean."""
    fields = registered_fields()
    blobs = _scan_files()
    missing: dict[str, list[str]] = {}
    for field, owners in sorted(fields.items()):
        pat = re.compile(r"\." + re.escape(field) + r"\b")
        if not any(pat.search(text) for _, text in blobs):
            missing[field] = owners
    return missing


#: update-shaped attribute chains: ``.name.inc(`` / ``.name.observe(``
#: / ``.name.labels(`` always mean metrics in this codebase;
#: ``.name.set(`` only with arguments (``Event.set()`` takes none) —
#: names starting with ``_`` (private state like ``_canceled``) never
#: match the leading ``[a-z]``.
_UPDATE_PAT = re.compile(
    r"\.([a-z][a-z0-9_]*)\.(?:inc|observe|labels)\("
    r"|\.([a-z][a-z0-9_]*)\.set\((?!\s*\))"
)

#: update-shaped chains that are NOT metrics (audited; extend when a
#: new non-metric ``.x.set(value)`` idiom appears): ``db.set(k, v)`` is
#: the KV-store put.
_NON_METRIC_UPDATES = frozenset({"db"})


def find_unregistered() -> dict[str, list[str]]:
    """Update sites whose field name no struct registers (field name ->
    files updating it) — empty dict when the lint is clean.

    Hot paths cache resolved label children under ``m_<field>``
    (``_Channel.m_send_queue_size`` holds
    ``send_queue_size.labels(...)``); the suffix must still name a
    registered field, so a typo'd handle is caught the same as a
    direct update."""
    fields = registered_fields()
    missing: dict[str, list[str]] = {}
    for rel, text in _scan_files():
        for m in _UPDATE_PAT.finditer(text):
            name = m.group(1) or m.group(2)
            if name.startswith("m_"):
                name = name[2:]
            if name in fields or name in _NON_METRIC_UPDATES:
                continue
            files = missing.setdefault(name, [])
            if rel not in files:
                files.append(rel)
    return missing


def main() -> int:
    missing = find_unreferenced()
    unregistered = find_unregistered()
    rc = 0
    if not missing and not unregistered:
        print(f"metrics-lint: {len(registered_fields())} fields, all "
              "referenced; no unregistered update sites")
    else:
        rc = 1
    for field, owners in missing.items():
        print(
            f"metrics-lint: {'/'.join(owners)}.{field} is registered "
            "but never referenced by any subsystem",
            file=sys.stderr,
        )
    for field, files in sorted(unregistered.items()):
        print(
            f"metrics-lint: .{field} is updated in {', '.join(files)} "
            "but registered by no metrics struct",
            file=sys.stderr,
        )
    # one command gates all three lints: the guarded-by/lock-seam
    # check (tools/lockcheck.py) and the device-path jit/contract
    # check (tools/jitcheck.py) run here too, so CI needs one entry
    from tools import jitcheck, lockcheck  # REPO is on sys.path (above)

    if lockcheck.main([]) != 0:
        rc = 1
    if jitcheck.main([]) != 0:
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
