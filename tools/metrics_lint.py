"""metrics-lint: the registered metric fields and the update sites in
subsystem code must agree, in BOTH directions.

The structs in cometbft_tpu/metrics/__init__.py are hand-maintained
(the reference generates them with metricsgen), so two failure modes
exist:

- **registered, never updated** — a permanently-zero series; worse
  than no series, because dashboards and alerts trust it.  Checked by
  ``find_unreferenced``: every field enumerated from the no-op structs
  needs an ``.<field>`` attribute reference somewhere in the package
  outside the metrics module itself.
- **updated, never registered** — the inverse: an update site whose
  field name matches nothing any struct registers (a typo, or a field
  deleted while its call sites survive) silently updates a fresh
  ``_Nop``/attribute and no series ever appears.  Checked by
  ``find_unregistered``: every update-shaped attribute chain
  (``.name.inc(`` / ``.observe(`` / ``.labels(`` / ``.set(<args>)``)
  must resolve to a registered field.

Both are tripwires, not proofs: a generic name like ``size`` is
trivially satisfied by unrelated attribute access.  New metric names
are deliberately specific (``key_pool_retraces``), which is where the
checks have teeth.

    python tools/metrics_lint.py        # exit 0 clean, 1 with a report

Run in the tier-1 flow via tests/test_metrics.py::TestMetricsLint and
standalone via ``make metrics-lint``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

#: subsystem code scanned for references (tools/ and bench drivers
#: count: the campaign/bench planes update crypto metrics too)
SCAN_ROOTS = ("cometbft_tpu", "tools", "bench.py", "bench_all.py")
#: the registration site itself never counts as a reference, and this
#: checker's own pattern literals must not feed the inverse scan
EXCLUDE = (
    os.path.join("cometbft_tpu", "metrics", "__init__.py"),
    os.path.join("tools", "metrics_lint.py"),
)


#: struct -> exposition subsystem prefix (the series name is
#: ``<subsystem>_<field>``); keep in sync with the ``s = "..."``
#: literals in cometbft_tpu/metrics/__init__.py
SUBSYSTEMS = {
    "ConsensusMetrics": "consensus",
    "MempoolMetrics": "mempool",
    "P2PMetrics": "p2p",
    "StateMetrics": "state",
    "CryptoMetrics": "crypto",
    "HealthMetrics": "crypto",
    "RPCMetrics": "rpc",
    "EventBusMetrics": "event_bus",
    "BlockSyncMetrics": "blocksync",
    "StateSyncMetrics": "statesync",
    "ProxyMetrics": "abci",
    "WALMetrics": "wal",
    "StoreMetrics": "store",
    "EvidenceMetrics": "evidence",
    "LightMetrics": "light",
    "FleetMetrics": "fleet",
    "AttributionMetrics": "attribution",
    "NetemMetrics": "netem",
}

#: structs whose every field must ALSO be documented in
#: docs/observability.md and mapped (or marked beyond-parity) in
#: docs/PARITY.md — the replication-plane structs started the list;
#: CryptoMetrics joined with the dispatch-tier ladder (PR 6), whose
#: series operators must be able to interpret to confirm keyed is the
#: default; extend as older planes get back-documented
DOC_CHECKED = (
    "BlockSyncMetrics",
    "StateSyncMetrics",
    "ProxyMetrics",
    "WALMetrics",
    "StoreMetrics",
    "EvidenceMetrics",
    "CryptoMetrics",
    # an undocumented health series is an alert nobody can act on
    "HealthMetrics",
    # the ingest plane (ISSUE 10): shed-vs-stall is read from the
    # mempool admission counters, so every one of them must be
    # interpretable from the docs
    "MempoolMetrics",
    # the light serving plane (ISSUE 13): cache hit rate and serve
    # latency are the serving SLO surface
    "LightMetrics",
    # the fleet plane (ISSUE 15): the cross-node rollup is the first
    # table an operator reads — every series in it must be
    # interpretable from the docs
    "FleetMetrics",
    # the wire plane joined when the fleet plane added
    # p2p_gossip_hop_seconds / p2p_peer_clock_offset_seconds: hop
    # latency is the SLO's numerator, so the whole family is now
    # doc-gated both directions
    "P2PMetrics",
    # the attribution plane (ISSUE 16): the stage budget is the first
    # thing read after a latency regression — every series must be
    # interpretable from the docs
    "AttributionMetrics",
    # the scenario plane (ISSUE 20): injected-vs-intrinsic is read
    # straight off the netem family, so it must be interpretable
    "NetemMetrics",
)

DOC_FILES = (
    os.path.join("docs", "observability.md"),
    os.path.join("docs", "PARITY.md"),
)

#: backticked doc tokens that LOOK series-shaped under a known
#: subsystem prefix but are deliberately not series — the verify-queue
#: lane name and the bench/ledger row the light plane is measured by.
#: Curated, not pattern-based: a stale series rename must still fail.
DOC_NON_SERIES = frozenset((
    "light_client",
    "light_serve_sustained",
    # evidence-type label VALUE (evidence_pool_detected_total{type}),
    # not a series — it parses as light_<field> but names an attack
    "light_client_attack",
    # critpath stage names in the observability.md taxonomy table:
    # they parse as <subsystem>_<field> under the abci/store/wal
    # prefixes but denote attribution stages, not series
    "abci_execute",
    "store_save",
    "wal_fsync",
))


def _metric_structs():
    import cometbft_tpu.metrics as M

    return tuple(getattr(M, name) for name in SUBSYSTEMS)


def registered_fields() -> dict[str, list[str]]:
    """field name -> metric struct(s) registering it."""
    out: dict[str, list[str]] = {}
    for cls in _metric_structs():
        for name in vars(cls(None)):
            out.setdefault(name, []).append(cls.__name__)
    return out


def _scan_files() -> list[tuple[str, str]]:
    files: list[tuple[str, str]] = []
    for root in SCAN_ROOTS:
        path = os.path.join(REPO, root)
        if os.path.isfile(path):
            files.append((root, open(path).read()))
            continue
        for dirpath, _, names in os.walk(path):
            for n in names:
                if not n.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, n), REPO)
                if rel in EXCLUDE:
                    continue
                files.append((rel, open(os.path.join(dirpath, n)).read()))
    return files


def find_unreferenced() -> dict[str, list[str]]:
    """Registered fields with no ``.<field>`` reference in any
    subsystem — empty dict when the lint is clean."""
    fields = registered_fields()
    blobs = _scan_files()
    missing: dict[str, list[str]] = {}
    for field, owners in sorted(fields.items()):
        pat = re.compile(r"\." + re.escape(field) + r"\b")
        if not any(pat.search(text) for _, text in blobs):
            missing[field] = owners
    return missing


#: update-shaped attribute chains: ``.name.inc(`` / ``.name.observe(``
#: / ``.name.labels(`` always mean metrics in this codebase;
#: ``.name.set(`` only with arguments (``Event.set()`` takes none) —
#: names starting with ``_`` (private state like ``_canceled``) never
#: match the leading ``[a-z]``.
_UPDATE_PAT = re.compile(
    r"\.([a-z][a-z0-9_]*)\.(?:inc|observe|labels)\("
    r"|\.([a-z][a-z0-9_]*)\.set\((?!\s*\))"
)

#: update-shaped chains that are NOT metrics (audited; extend when a
#: new non-metric ``.x.set(value)`` idiom appears): ``db.set(k, v)`` is
#: the KV-store put.
_NON_METRIC_UPDATES = frozenset({"db"})


def find_unregistered() -> dict[str, list[str]]:
    """Update sites whose field name no struct registers (field name ->
    files updating it) — empty dict when the lint is clean.

    Hot paths cache resolved label children under ``m_<field>``
    (``_Channel.m_send_queue_size`` holds
    ``send_queue_size.labels(...)``); the suffix must still name a
    registered field, so a typo'd handle is caught the same as a
    direct update."""
    fields = registered_fields()
    missing: dict[str, list[str]] = {}
    for rel, text in _scan_files():
        for m in _UPDATE_PAT.finditer(text):
            name = m.group(1) or m.group(2)
            if name.startswith("m_"):
                name = name[2:]
            if name in fields or name in _NON_METRIC_UPDATES:
                continue
            files = missing.setdefault(name, [])
            if rel not in files:
                files.append(rel)
    return missing


def _series_by_subsystem() -> dict[str, set[str]]:
    """subsystem prefix -> registered field names."""
    out: dict[str, set[str]] = {}
    for cls in _metric_structs():
        sub = SUBSYSTEMS[cls.__name__]
        out.setdefault(sub, set()).update(vars(cls(None)))
    return out


def _doc_texts() -> list[tuple[str, str]]:
    return [
        (rel, open(os.path.join(REPO, rel)).read()) for rel in DOC_FILES
    ]


def find_undocumented() -> dict[str, list[str]]:
    """DOC_CHECKED fields whose series name (``<subsystem>_<field>``)
    appears in neither/only one of the doc files — series name ->
    doc files missing it.  A field shipped without docs is a series
    operators can't interpret; docs/observability.md describes it,
    docs/PARITY.md maps it to the reference struct (or marks it
    beyond-parity)."""
    import cometbft_tpu.metrics as M

    docs = _doc_texts()
    missing: dict[str, list[str]] = {}
    for cls_name in DOC_CHECKED:
        sub = SUBSYSTEMS[cls_name]
        for field in vars(getattr(M, cls_name)(None)):
            series = f"{sub}_{field}"
            absent = [rel for rel, text in docs if series not in text]
            if absent:
                missing[series] = absent
    return missing


#: inline-backticked tokens in the docs that LOOK like one of our
#: series names; trailing ``{label,...}`` groups are stripped, inner
#: ``{a,b}`` alternation groups expanded, optional ``cometbft_``
#: namespace and histogram ``_count|_sum|_bucket`` suffixes tolerated
_DOC_TOKEN_PAT = re.compile(r"`([^`\s]+)`")
_TRAILING_LABELS = re.compile(r"\{[^{}]*\}$")
_ALTERNATION = re.compile(r"\{([a-z0-9_]+(?:,[a-z0-9_]+)+)\}")


def _strip_trailing_labels(token: str) -> str:
    while True:
        stripped = _TRAILING_LABELS.sub("", token)
        if stripped == token:
            return token
        token = stripped


def _expand_alternations(token: str) -> list[str]:
    m = _ALTERNATION.search(token)
    if m is None:
        return [token]
    out: list[str] = []
    for alt in m.group(1).split(","):
        out.extend(
            _expand_alternations(
                token[: m.start()] + alt + token[m.end():]
            )
        )
    return out


def _doc_token_candidates(raw: str) -> set[str]:
    """All plausible series names a doc token could denote.  A trailing
    ``{a,b}`` group is ambiguous — labels (`{route,reason}`) or
    brace-alternation (`key_pool_{keys,capacity}`) — so BOTH
    interpretations (strip-labels-first and expand-first) are
    candidates; the token is fine if ANY candidate is registered."""
    out: set[str] = set()
    for token in _expand_alternations(_strip_trailing_labels(raw)):
        out.add(_strip_trailing_labels(token))
    for token in _expand_alternations(raw):
        out.add(_strip_trailing_labels(token))
    return out


def find_doc_unregistered() -> dict[str, list[str]]:
    """Inverse doc check: series-shaped tokens in the docs that no
    struct registers (stale docs after a rename/removal) — token ->
    doc files naming it."""
    by_sub = _series_by_subsystem()
    # longest prefix first so event_bus_* can't parse under a shorter
    # (unknown) prefix
    subs = sorted(by_sub, key=len, reverse=True)

    def resolves(candidate: str) -> bool | None:
        """True registered / False series-shaped-but-unknown / None
        not series-shaped."""
        if candidate.startswith("cometbft_"):
            candidate = candidate[len("cometbft_"):]
        candidate = re.sub(r"_(count|sum|bucket)$", "", candidate)
        for sub in subs:
            if not candidate.startswith(sub + "_"):
                continue
            field = candidate[len(sub) + 1:]
            if not re.fullmatch(r"[a-z0-9]+(?:_[a-z0-9]+)*", field):
                return None
            return field in by_sub[sub]
        return None

    stale: dict[str, list[str]] = {}
    for rel, text in _doc_texts():
        for raw in _DOC_TOKEN_PAT.findall(text):
            if "*" in raw:
                continue  # family globs like `p2p_*`
            if raw in DOC_NON_SERIES:
                continue  # lane/bench-row names, not series
            verdicts = [
                v
                for v in map(resolves, _doc_token_candidates(raw))
                if v is not None
            ]
            if verdicts and not any(verdicts):
                stale.setdefault(raw, [])
                if rel not in stale[raw]:
                    stale[raw].append(rel)
    return stale


def find_undocumented_stages() -> list[str]:
    """Stale-taxonomy guard (same shape as jitcheck's stale-waiver
    check): every stage label utils/critpath.py can emit must appear
    in the docs/observability.md stage table — a stage added to the
    taxonomy without a documented meaning is a budget row nobody can
    act on.  Returns the missing stage names."""
    from cometbft_tpu.utils.critpath import STAGES

    text = open(
        os.path.join(REPO, "docs", "observability.md")
    ).read()
    return [s for s in STAGES if f"`{s}`" not in text]


def main() -> int:
    missing = find_unreferenced()
    unregistered = find_unregistered()
    undocumented = find_undocumented()
    doc_stale = find_doc_unregistered()
    stale_stages = find_undocumented_stages()
    rc = 0
    if not missing and not unregistered and not undocumented and (
        not doc_stale
    ) and not stale_stages:
        print(f"metrics-lint: {len(registered_fields())} fields, all "
              "referenced; no unregistered update sites; replication-"
              "plane fields documented, no stale doc series; stage "
              "taxonomy documented")
    else:
        rc = 1
    for stage in stale_stages:
        print(
            f"metrics-lint: critpath stage `{stage}` is emitted but "
            "missing from the docs/observability.md stage table",
            file=sys.stderr,
        )
    for field, owners in missing.items():
        print(
            f"metrics-lint: {'/'.join(owners)}.{field} is registered "
            "but never referenced by any subsystem",
            file=sys.stderr,
        )
    for field, files in sorted(unregistered.items()):
        print(
            f"metrics-lint: .{field} is updated in {', '.join(files)} "
            "but registered by no metrics struct",
            file=sys.stderr,
        )
    for series, files in sorted(undocumented.items()):
        print(
            f"metrics-lint: {series} is registered but undocumented "
            f"in {', '.join(files)}",
            file=sys.stderr,
        )
    for token, files in sorted(doc_stale.items()):
        print(
            f"metrics-lint: docs name series {token} "
            f"({', '.join(files)}) but no struct registers it",
            file=sys.stderr,
        )
    # one command gates every lint: the guarded-by/lock-seam check
    # (tools/lockcheck.py), the device-path jit/contract check
    # (tools/jitcheck.py), the replay-determinism walk
    # (tools/determcheck.py), the critical-path blocking walk
    # (tools/hotpathcheck.py), the env-knob registry
    # (tools/envcheck.py), and the wire-ingress taint walk
    # (tools/trustcheck.py) run here too, so CI needs one entry
    from tools import (  # REPO is on sys.path (above)
        determcheck,
        envcheck,
        hotpathcheck,
        jitcheck,
        lockcheck,
        trustcheck,
    )

    for lint in (
        lockcheck, jitcheck, determcheck, hotpathcheck, envcheck, trustcheck
    ):
        if lint.main([]) != 0:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
