"""envcheck: the CMT_TPU_* knob registry lint.

Every environment knob in the tree must obey the one contract
(cometbft_tpu/utils/env.py, generalizing flight.ring_size_from_env):
a malformed value fails LOUDLY at read time, naming the variable and
its constraint — a typo'd ``CMT_TPU_CHECKTX_BATCH=8O`` that silently
falls back to the default is a production incident disguised as a perf
regression.  This lint walks every ``CMT_TPU_*`` string literal in the
package and enforces three things:

1. **validated reads** — a literal used as the key of a raw
   ``os.environ.get`` / ``os.getenv`` / ``os.environ[...]`` read is a
   violation unless the line carries an audited ``# env ok: <reason>``
   waiver (free-form paths/lists that have no parse to fail, or reads
   whose validation demonstrably happens downstream).  Reads routed
   through a registered validator (``VALIDATED_READERS``: the
   utils/env.py helpers, ``ring_size_from_env`` and its per-module
   aliases, the profiler's range-checked reader) pass.
2. **documented** — every knob the code reads must have a row in
   docs/observability.md's env table (``| `CMT_TPU_X` | ...``).
3. **still read** (the inverse): every knob in the doc table must
   still be read somewhere — a documented-but-unread knob is an
   operator trap (setting it does nothing).

A waiver on a line with no raw CMT_TPU_* read is a STALE-WAIVER
error, same as the other three lints.

    python tools/envcheck.py            # exit 0 clean, 1 with a report
    python tools/envcheck.py -v         # also list waivers + knobs

Run in the tier-1 flow via tests/test_envcheck.py and standalone via
``make envcheck``; tools/metrics_lint.py main() gates on it.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    Violation,
    Waiver,
    check_stale_waivers,
    comments_by_line,
    dotted,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

SCAN_ROOT = "cometbft_tpu"
DOC_PATH = "docs/observability.md"

_WAIVER_RE = waiver_re("env ok")
_VAR_RE = re.compile(r"^CMT_TPU_[A-Z0-9_]+$")
_DOC_ROW_RE = re.compile(r"^\|\s*`(CMT_TPU_[A-Z0-9_]+)`")

#: call basenames that implement the fail-loudly contract.  Adding a
#: name here asserts "this function raises on a malformed value,
#: naming the variable" — tests/test_envcheck.py spot-checks the
#: utils/env.py four.
VALIDATED_READERS = frozenset(
    {
        "int_from_env", "float_from_env", "flag_from_env",
        "choice_from_env",
        # flight.ring_size_from_env, the original, and its per-module
        # aliases (light/serve, crypto/bls_dispatch, crypto/verify_queue
        # import it as _int_env; crypto/dispatch+health define peers)
        "ring_size_from_env", "_int_env", "_float_env",
        # profiler's range-checked reader (0..1000 Hz window)
        "profile_hz_from_env",
        # scenario-label reader ([A-Za-z0-9_-], <= 64 chars)
        "name_from_env",
    }
)

def _is_raw_read(d: str) -> bool:
    """``os.environ.get`` / ``os.getenv`` under any import alias
    (``import os as _os`` is common in this tree)."""
    return (
        d.endswith("environ.get")
        or d.endswith(".getenv")
        or d == "getenv"
    )


@dataclass
class Report(lintlib.Report):
    read_vars: set = field(default_factory=set)
    validated_reads: int = 0
    raw_reads: int = 0


def _literal_var(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if _VAR_RE.match(node.value):
            return node.value
    return None


def _check_file(rel: str, source: str, report: Report) -> None:
    try:
        tree = lintlib.parse_cached(source)
    except SyntaxError as exc:
        report.violations.append(Violation(rel, exc.lineno or 0,
                                           f"syntax error: {exc.msg}"))
        return
    comments = comments_by_line(source)
    flagged: set[int] = set()
    waived: set[int] = set()

    def raw_read(line: int, var: str, how: str) -> None:
        report.read_vars.add(var)
        report.raw_reads += 1
        flagged.add(line)
        m = _WAIVER_RE.search(comments.get(line, ""))
        if m:
            if line not in waived:
                waived.add(line)
                report.waivers.append(
                    Waiver(rel, line, f"{how} read of {var}",
                           m.group(1).strip())
                )
            return
        report.violations.append(
            Violation(
                rel, line,
                f"raw {how} read of {var} — route it through a "
                "validated reader (cometbft_tpu/utils/env.py: "
                "int_from_env / float_from_env / flag_from_env / "
                "choice_from_env) so a malformed value fails loudly "
                "naming the variable, or waive with "
                "'# env ok: <reason>'",
            )
        )

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            base = d.split(".")[-1] if d else ""
            if base in VALIDATED_READERS:
                for arg in node.args[:1]:
                    var = _literal_var(arg)
                    if var:
                        report.read_vars.add(var)
                        report.validated_reads += 1
            elif _is_raw_read(d):
                for arg in node.args[:1]:
                    var = _literal_var(arg)
                    if var:
                        raw_read(arg.lineno, var, d)
        elif isinstance(node, ast.FunctionDef):
            # a validated reader may carry its variable as a parameter
            # default (profiler.profile_hz_from_env) — that IS a read
            if node.name in VALIDATED_READERS:
                for default in node.args.defaults:
                    var = _literal_var(default)
                    if var:
                        report.read_vars.add(var)
                        report.validated_reads += 1
        elif isinstance(node, ast.Subscript):
            if dotted(node.value).endswith("environ"):
                var = _literal_var(
                    node.slice if not isinstance(node.slice, ast.Tuple)
                    else node.slice
                )
                if var:
                    raw_read(node.value.lineno, var, "os.environ[...]")

    check_stale_waivers(comments, flagged, _WAIVER_RE, rel, report,
                        "env ok")


def doc_table_vars(doc_source: str) -> set[str]:
    """Knob names with a row in the env table (``| `CMT_TPU_X` | ...``)."""
    out = set()
    for line in doc_source.splitlines():
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            out.add(m.group(1))
    return out


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source (fixtures) — code checks only; the doc
    cross-check needs the tree and lives in check_tree."""
    report = Report()
    _check_file(rel, source, report)
    return report


def check_tree(root: str | None = None) -> Report:
    report = Report()
    scan = root if root is not None else SCAN_ROOT
    for rel, source in iter_py_files(scan):
        _check_file(rel, source, report)

    doc_path = os.path.join(REPO, DOC_PATH)
    if not os.path.exists(doc_path):
        report.violations.append(
            Violation(DOC_PATH, 0, "env-table doc missing")
        )
        return report
    with open(doc_path, encoding="utf-8") as f:
        doc = f.read()
    documented = doc_table_vars(doc)

    for var in sorted(report.read_vars - documented):
        report.violations.append(
            Violation(
                DOC_PATH, 0,
                f"{var} is read by the code but has no row in the "
                "env table — document the default and constraint",
            )
        )
    for var in sorted(documented - report.read_vars):
        report.violations.append(
            Violation(
                DOC_PATH, 0,
                f"{var} has an env-table row but nothing reads it — "
                "setting it does nothing; delete the row or restore "
                "the read",
            )
        )
    return report


def _summary(report: Report) -> str:
    return (
        f"{len(report.read_vars)} knobs; {report.validated_reads} "
        f"validated reads, {report.raw_reads} raw reads "
        f"({len(report.waivers)} audited waivers)"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("envcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
