"""A/B the verify kernel end-to-end under the current env flags.

Prints one line: device-side marginal sigs/s (K-dispatch difference
method, cancels the tunneled link RTT).  Drive with:

    for cols in stack stack16 tree pallas; do for sq in fast mul; do
      CMT_TPU_COLS_IMPL=$cols CMT_TPU_SQUARE_IMPL=$sq \
        python tools/bench_kernel_ab.py; done; done

(stack16 halves the stacked operand's HBM bytes and only changes mul,
so pair it with CMT_TPU_SQUARE_IMPL=mul; pallas fuses the whole field
op into one VMEM-resident program.)
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".xla_cache"),
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.ops.ed25519_verify import (
        _finish,
        verify_arrays,
        verify_arrays_async,
    )

    n = int(os.environ.get("AB_N", 4096))
    rng = np.random.RandomState(0)
    priv = ed.gen_priv_key()
    pub_b = np.frombuffer(priv.pub_key().bytes(), dtype=np.uint8)
    msgs = [
        rng.randint(0, 256, size=120, dtype=np.uint8).tobytes()
        for _ in range(n)
    ]
    sigs = np.stack(
        [np.frombuffer(priv.sign(m), dtype=np.uint8) for m in msgs]
    )
    pubs = np.tile(pub_b, (n, 1))

    t0 = time.time()
    out = verify_arrays(pubs, sigs, msgs)
    compile_s = time.time() - t0
    assert bool(out.all())

    k = 6
    best = float("inf")
    for _ in range(2):
        t0 = time.time()
        parts = []
        for _ in range(k):
            parts.extend(verify_arrays_async(pubs, sigs, msgs))
        _finish(parts)
        t_k = time.time() - t0
        t0 = time.time()
        _finish(verify_arrays_async(pubs, sigs, msgs))
        t_1 = time.time() - t0
        best = min(best, max(t_k - t_1, 1e-9) / (k - 1))
    rate = n / best
    print(
        f"cols={os.environ.get('CMT_TPU_COLS_IMPL', 'stack'):5s} "
        f"square={os.environ.get('CMT_TPU_SQUARE_IMPL', 'fast'):4s} "
        f"{rate:10,.0f} sigs/s device-side "
        f"({best * 1e3:.1f} ms/launch, compile {compile_s:.0f}s)",
        flush=True,
    )


if __name__ == "__main__":
    main()
