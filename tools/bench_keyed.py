"""Benchmark the keyed (precomputed-table) verify path on the device.

Shapes mirror BASELINE configs: a 150-validator commit reused across
many blocks (table cache hot), and a light-sync style batch of
H commits x 150 validators in one launch.  Prints device-side marginal
sigs/s via the K-dispatch difference method.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(repo, ".xla_cache")
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.ops import precompute as PR
    from cometbft_tpu.ops.ed25519_verify import (
        _finish,
        verify_arrays_keyed_async,
    )

    nval = int(os.environ.get("KB_NVAL", 150))
    nsigs = int(os.environ.get("KB_NSIGS", 4096))
    rng = np.random.RandomState(0)
    privs = [ed.gen_priv_key() for _ in range(nval)]
    pubs_b = [p.pub_key().bytes() for p in privs]

    t0 = time.time()
    entry = PR.TABLE_CACHE.lookup_or_build(pubs_b)
    np.asarray(jax.device_get(entry.table[0, 0, 0, :4]))  # sync build
    print(
        f"table build: {nval} keys, {entry.window_bits}-bit windows, "
        f"{entry.nbytes / 1e6:.0f} MB, {time.time() - t0:.1f}s "
        "(incl. compile)",
        file=sys.stderr,
    )

    # light-sync-style batch: nsigs votes round-robin over the set
    idx = [i % nval for i in range(nsigs)]
    msgs = [rng.bytes(120) for _ in range(nsigs)]
    sigs = np.stack(
        [
            np.frombuffer(privs[i].sign(m), dtype=np.uint8)
            for i, m in zip(idx, msgs)
        ]
    )
    pub = np.stack(
        [np.frombuffer(pubs_b[i], dtype=np.uint8) for i in idx]
    )
    key_ids = entry.key_ids([pubs_b[i] for i in idx])

    t0 = time.time()
    out = _finish(verify_arrays_keyed_async(entry, key_ids, pub, sigs, msgs))
    print(f"first keyed launch (compile): {time.time() - t0:.1f}s",
          file=sys.stderr)
    assert bool(out.all()), "keyed verification failed"

    k = 6
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        parts = []
        for _ in range(k):
            parts.extend(
                verify_arrays_keyed_async(entry, key_ids, pub, sigs, msgs)
            )
        _finish(parts)
        t_k = time.time() - t0
        t0 = time.time()
        _finish(verify_arrays_keyed_async(entry, key_ids, pub, sigs, msgs))
        t_1 = time.time() - t0
        best = min(best, max(t_k - t_1, 1e-9) / (k - 1))
    print(
        f"keyed {nsigs} sigs x {nval} validators: "
        f"{nsigs / best:,.0f} sigs/s device-side ({best * 1e3:.1f} ms/launch)",
        flush=True,
    )
    # provenance line device_campaign.py scrapes into the step entry:
    # the warmup compile count per seam (steady trials above should
    # have added none — docs/device_contracts.md)
    import json

    from cometbft_tpu.ops import jitguard

    print(f"JITGUARD compiles: {json.dumps(jitguard.compile_counts())}",
          flush=True)


if __name__ == "__main__":
    main()
