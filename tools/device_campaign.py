"""Device measurement campaign, resumable across tunnel windows.

The tunneled axon backend comes and goes (r3's bench recorded 0 during
an outage; r4's hung for two full rounds of 600 s); this driver runs
each measurement in its OWN subprocess with a deadline, appends
whatever lands to docs/data/kernel_ab_r05.json immediately, and skips
steps that already have a result — so a short healthy window makes
progress and a wedge costs one step's timeout, not the campaign.

    python tools/device_campaign.py [--only STEP] [--timeout S]

Steps (env = the kernel config under test, tool = what runs):
  keyed_stack     CMT_TPU_COLS_IMPL=stack             bench_keyed
  keyed_stack16   CMT_TPU_COLS_IMPL=stack16 SQ=mul    bench_keyed
  keyed_pallas    CMT_TPU_COLS_IMPL=pallas            bench_keyed
  keyed_mesh      8-chip sharded keyed tier           bench.py --keyed-mesh
  ab_stack        generic kernel A/B                  bench_kernel_ab
  ab_stack16      generic kernel A/B                  bench_kernel_ab

The keyed_mesh step's JSON line (per-chip + aggregate sigs/s,
dispatch_tier, per-seam compiles) is scraped into this campaign's
MULTICHIP entry fields; bench.py itself also merges the full row into
MULTICHIP_KEYED.json.

``--auto-resume`` closes the r03/r04 loop: instead of exiting when the
tunnel is down (rc=3 at start, rc=4 mid-campaign), the driver parks
and polls ``crypto/batch.device_status()`` + the prober's tier health
(cheap in-process reads that never trigger a probe) and the subprocess
device probe every ``--poll-interval`` seconds, then restarts the
campaign from its last completed step the moment a window opens —
recording a ``campaign/resume`` flight event + span so the provenance
trail shows exactly when the window opened and how long the wait cost.
``--max-wait`` bounds the park (default 2 h; 0 = one probe, no park).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "data", "kernel_ab_r05.json")
#: campaign-level span trace (utils/trace Chrome trace-event JSON):
#: one span per probe/step with outcome args — the provenance record
#: of where a tunnel window's time actually went
TRACE_OUT = os.path.join(REPO, "docs", "data", "device_campaign_trace.json")


def dump_trace() -> None:
    try:
        from cometbft_tpu.utils.trace import TRACER

        TRACER.dump(TRACE_OUT)
    except Exception as exc:  # noqa: BLE001 — provenance only
        print(f"trace dump failed (ignored): {exc}", file=sys.stderr)

STEPS = {
    "keyed_stack": (
        {"CMT_TPU_COLS_IMPL": "stack", "CMT_TPU_SQUARE_IMPL": "fast"},
        "tools/bench_keyed.py",
    ),
    "keyed_stack16": (
        {"CMT_TPU_COLS_IMPL": "stack16", "CMT_TPU_SQUARE_IMPL": "mul"},
        "tools/bench_keyed.py",
    ),
    "keyed_pallas": (
        {"CMT_TPU_COLS_IMPL": "pallas", "CMT_TPU_SQUARE_IMPL": "fast"},
        "tools/bench_keyed.py",
    ),
    "keyed_mesh": ({}, "bench.py --keyed-mesh"),
    "ab_stack": (
        {"CMT_TPU_COLS_IMPL": "stack", "CMT_TPU_SQUARE_IMPL": "fast"},
        "tools/bench_kernel_ab.py",
    ),
    "ab_stack16": (
        {"CMT_TPU_COLS_IMPL": "stack16", "CMT_TPU_SQUARE_IMPL": "mul"},
        "tools/bench_kernel_ab.py",
    ),
}

RATE_RE = re.compile(r"([\d,]+) sigs/s device-side")
#: per-seam warmup compile counts the bench tools print (BENCH
#: provenance: future perf PRs assert steady state compiled nothing)
COMPILES_RE = re.compile(r"JITGUARD compiles: (\{.*\})")


def load() -> dict:
    try:
        with open(OUT) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {"results": {}}


def save(data: dict) -> None:
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
    os.replace(tmp, OUT)


def probe(timeout: float = 75.0) -> bool:
    """Is the device tunnel answering at all?"""
    code = (
        "import jax, jax.numpy as jnp;"
        "print(jax.devices());"
        "print(float((jnp.arange(8) * 2).sum()))"
    )
    try:
        rc = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO, timeout=timeout,
            capture_output=True,
        ).returncode
        return rc == 0
    except subprocess.TimeoutExpired:
        return False


def run_step(name: str, timeout: float) -> dict:
    from cometbft_tpu.utils.trace import TRACER

    env_extra, tool = STEPS[name]
    env = dict(os.environ, **env_extra)
    with TRACER.span("campaign/" + name, cat="bench", tool=tool) as sp:
        entry = _run_step_proc(name, tool, env, timeout)
        sp.set(rc=entry["rc"], wall_s=entry["wall_s"])
    return entry


def _run_step_proc(name: str, tool: str, env: dict, timeout: float) -> dict:
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable] + tool.split(), cwd=REPO, env=env,
            timeout=timeout, capture_output=True, text=True,
        )
        out = proc.stdout + proc.stderr
        m = RATE_RE.search(out)
        entry = {
            "rc": proc.returncode,
            "wall_s": round(time.time() - t0, 1),
            "tail": out.strip().splitlines()[-4:],
        }
        if m:
            entry["sigs_per_sec_device"] = float(m.group(1).replace(",", ""))
        mc = COMPILES_RE.search(out)
        if mc:
            try:
                entry["warmup_compiles"] = json.loads(mc.group(1))
            except ValueError:
                pass
        # keyed_mesh (and any JSON-line tool): scrape the dispatch
        # tier + per-chip/aggregate rates into the MULTICHIP entry
        for line in proc.stdout.splitlines():
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if "dispatch_tier" in row:
                entry["dispatch_tier"] = row["dispatch_tier"]
            if row.get("metric") == "keyed_mesh_batch_verify_throughput":
                entry["sigs_per_sec_aggregate"] = row.get("value")
                entry["sigs_per_sec_per_chip"] = row.get(
                    "per_chip_sigs_per_sec"
                )
                entry["ndev"] = row.get("ndev")
                entry["jit_compiles"] = row.get("jit_compiles")
                entry["steady_retraces"] = row.get("steady_retraces")
        return entry
    except subprocess.TimeoutExpired as exc:
        out = ((exc.stdout or b"").decode(errors="replace") if
               isinstance(exc.stdout, bytes) else (exc.stdout or ""))
        return {
            "rc": "timeout",
            "wall_s": round(time.time() - t0, 1),
            "tail": out.strip().splitlines()[-4:],
        }


def device_looks_up() -> bool | None:
    """Cheap in-process window check before the subprocess probe:
    the device-probe state machine (crypto/batch.device_status — a
    read that never triggers a probe) and, when a prober is running
    in-process, its per-tier health.  Returns True/False when those
    surfaces are conclusive, None when only the subprocess probe can
    tell (status "unknown"/"probing", no prober)."""
    try:
        from cometbft_tpu.crypto import batch as _batch
        from cometbft_tpu.crypto import health as _health

        status = _batch.device_status()["status"]
        if status == "failed":
            return False
        prober = _health._ACTIVE_PROBER
        if prober is not None:
            tiers = prober.snapshot()["tiers"]
            device = {
                t: s for t, s in tiers.items() if t != "host"
            }
            if device:
                return any(s.get("healthy") for s in device.values())
        if status == "ready":
            return True
    except Exception:  # noqa: BLE001 — the subprocess probe decides
        pass
    return None


def wait_for_window(
    poll_interval: float, max_wait: float
) -> float | None:
    """Park until the tunnel answers; returns the seconds waited, or
    None when ``max_wait`` elapsed first.  Polls the cheap in-process
    surfaces before paying a subprocess probe each round."""
    t0 = time.time()
    while True:
        up = device_looks_up()
        if up is not False and probe():
            return time.time() - t0
        waited = time.time() - t0
        if waited + poll_interval > max_wait:
            return None
        print(
            f"tunnel still down after {waited:.0f}s; next poll in "
            f"{poll_interval:.0f}s",
            file=sys.stderr,
        )
        time.sleep(poll_interval)


def _note_resume(waited_s: float, next_step: str) -> None:
    """The resume is a flight event + span: the provenance trail shows
    when the window opened and what the wait cost."""
    try:
        from cometbft_tpu.utils.flight import FLIGHT
        from cometbft_tpu.utils.trace import TRACER

        FLIGHT.record(
            "campaign/resume", waited_s=round(waited_s, 1),
            next_step=next_step,
        )
        with TRACER.span(
            "campaign/resume", cat="bench",
            waited_s=round(waited_s, 1), next_step=next_step,
        ):
            pass
    except Exception as exc:  # noqa: BLE001 — provenance only
        print(f"resume flight event failed (ignored): {exc}",
              file=sys.stderr)


def pending_steps(data: dict, steps: list[str], redo: bool) -> list[str]:
    """Steps without a result yet — the resume point is the first."""
    out = []
    for name in steps:
        done = data["results"].get(name, {})
        if not redo and "sigs_per_sec_device" in done:
            continue
        out.append(name)
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", help="run just this step")
    ap.add_argument("--timeout", type=float, default=1500.0)
    ap.add_argument("--redo", action="store_true",
                    help="rerun steps that already have results")
    ap.add_argument("--auto-resume", action="store_true",
                    help="park and poll for a tunnel window instead "
                         "of exiting when the device is down, then "
                         "resume from the last completed step")
    ap.add_argument("--poll-interval", type=float, default=60.0,
                    help="seconds between window polls (--auto-resume)")
    ap.add_argument("--max-wait", type=float, default=7200.0,
                    help="give up after this many seconds parked "
                         "(--auto-resume)")
    args = ap.parse_args()

    data = load()
    steps = [args.only] if args.only else list(STEPS)
    for name in steps:
        rate = data["results"].get(name, {}).get("sigs_per_sec_device")
        if not args.redo and rate:
            print(f"{name}: already measured "
                  f"({rate:,.0f} sigs/s), skipping",
                  file=sys.stderr)
    # steps attempted since the last park: a step that fails while the
    # tunnel is UP is a real failure, not a window to wait for — it is
    # not retried until a fresh window opens (else a broken step would
    # spin in a tight re-run loop under --auto-resume)
    attempted: set[str] = set()
    # steps that got a rate THIS invocation: a resume never re-runs
    # them, but --redo's claim on PRE-EXISTING results survives a park
    # (redo steps the park preempted still run when a window opens)
    measured_now: set[str] = set()
    while True:
        pending = [
            n for n in pending_steps(data, steps, args.redo)
            if n not in attempted and n not in measured_now
        ]
        if not pending:
            break
        if not probe():
            if not args.auto_resume:
                print("device tunnel not answering; campaign deferred",
                      file=sys.stderr)
                return 3
            waited = wait_for_window(args.poll_interval, args.max_wait)
            if waited is None:
                print(f"no tunnel window within {args.max_wait:.0f}s; "
                      "campaign deferred", file=sys.stderr)
                dump_trace()
                return 3
            attempted.clear()  # a fresh window warrants fresh retries
            pending = [
                n for n in pending_steps(data, steps, args.redo)
                if n not in measured_now
            ]
            _note_resume(waited, pending[0] if pending else "(none)")
            print(f"tunnel window opened after {waited:.0f}s; resuming "
                  f"at {pending[0] if pending else 'done'}",
                  file=sys.stderr)
        interrupted = False
        for name in pending:
            print(f"{name}: running (timeout {args.timeout:.0f}s)...",
                  file=sys.stderr)
            entry = run_step(name, args.timeout)
            entry["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
            data["results"][name] = entry
            save(data)
            # the merged store of record is the perf ledger
            # (tools/perfledger.py): each step's point lands there with
            # its provenance the moment it is measured, so the
            # trajectory never again has to be reassembled from
            # per-round files
            value = entry.get("sigs_per_sec_aggregate") or entry.get(
                "sigs_per_sec_device"
            )
            if value:
                from tools import perfledger

                perfledger.append_rows(
                    [
                        dict(
                            entry, config=name, value=value,
                            unit="sigs/sec",
                            measured=entry["measured_at"],
                        )
                    ],
                    source="device_campaign",
                )
            dump_trace()
            rate = entry.get("sigs_per_sec_device")
            print(f"{name}: " + (f"{rate:,.0f} sigs/s" if rate else
                                 f"no rate (rc={entry['rc']})"),
                  file=sys.stderr)
            attempted.add(name)
            if rate:
                measured_now.add(name)
            if not probe(45):
                if not args.auto_resume:
                    print("tunnel went away mid-campaign; stopping here",
                          file=sys.stderr)
                    dump_trace()
                    return 4
                # this step's failure (if any) happened while the
                # tunnel was dying — the next window retries it
                attempted.discard(name)
                print("tunnel went away mid-campaign; parking for the "
                      "next window (--auto-resume)", file=sys.stderr)
                interrupted = True
                break
        if not interrupted:
            # every remaining step was attempted in this window: what
            # is still missing a rate failed with the tunnel UP — real
            # failures, not windows to wait for
            break
        # loop: park for the next window, then resume from the first
        # step still missing a result
    dump_trace()
    print(json.dumps(load(), indent=1))
    return 0


if __name__ == "__main__":
    main()
