"""Secret-connection frame-plane micro-benchmark: Python per-frame
OpenSSL AEAD loop vs the native batched pump
(native/transport/frame_crypto.cpp).

Measures seal and open throughput for a burst of ``SIZE`` bytes (a
typical block-part gossip write), printing MB/s and frames/s for each
path.  Run on any host — no device involved.
"""

from __future__ import annotations

import os
import struct
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

from cometbft_tpu.p2p.conn import frame_native

SIZE = int(os.environ.get("FB_SIZE", 65536))
REPS = int(os.environ.get("FB_REPS", 200))
DATA_MAX = 1024


def py_seal(key: bytes, nonce0: int, data: bytes) -> bytes:
    aead = ChaCha20Poly1305(key)
    out = []
    off = ctr = 0
    while True:
        chunk = data[off : off + DATA_MAX]
        frame = struct.pack("<I", len(chunk)) + chunk
        frame += b"\x00" * (1028 - len(frame))
        nonce = b"\x00\x00\x00\x00" + struct.pack("<Q", nonce0 + ctr)
        out.append(aead.encrypt(nonce, frame, None))
        off += len(chunk)
        ctr += 1
        if off >= len(data):
            break
    return b"".join(out)


def py_open(key: bytes, nonce0: int, sealed: bytes) -> bytes:
    aead = ChaCha20Poly1305(key)
    out = []
    for f in range(len(sealed) // 1044):
        nonce = b"\x00\x00\x00\x00" + struct.pack("<Q", nonce0 + f)
        frame = aead.decrypt(nonce, sealed[f * 1044 : (f + 1) * 1044], None)
        (length,) = struct.unpack("<I", frame[:4])
        out.append(frame[4 : 4 + length])
    return b"".join(out)


def bench(label, fn):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(REPS):
        fn()
    dt = time.perf_counter() - t0
    nframes = -(-SIZE // DATA_MAX)
    print(
        f"{label:28s} {SIZE * REPS / dt / 1e6:8.1f} MB/s "
        f"({nframes * REPS / dt:9,.0f} frames/s)"
    )
    return SIZE * REPS / dt / 1e6


def main():
    lib = frame_native.load()
    key = os.urandom(32)
    data = os.urandom(SIZE)
    sealed = py_seal(key, 0, data)
    results = {}
    results["py_seal"] = bench(
        "python seal (per-frame)", lambda: py_seal(key, 0, data)
    )
    results["py_open"] = bench(
        "python open (per-frame)", lambda: py_open(key, 0, sealed)
    )
    if lib is None:
        print("native pump unavailable")
        return
    assert frame_native.seal_frames(lib, key, 0, data) == sealed
    results["native_seal"] = bench(
        "native seal (one call)",
        lambda: frame_native.seal_frames(lib, key, 0, data),
    )
    results["native_open"] = bench(
        "native open (one call)",
        lambda: frame_native.open_frames(lib, key, 0, sealed),
    )
    print(
        f"seal speedup {results['native_seal'] / results['py_seal']:.2f}x, "
        f"open speedup {results['native_open'] / results['py_open']:.2f}x "
        f"(burst={SIZE} bytes)"
    )


if __name__ == "__main__":
    main()
