"""Python networking-ceiling measurement (VERDICT r3 weak #6 / next #9).

Two curves back (or refute) the README's scaling stance that the
Python transport plane is fine for tens of peers:

A. **Per-peer transport cost**: a Switch server in a subprocess
   self-reports thread count, RSS, and process CPU while N synthetic
   peers (full SecretConnection + MConnection handshakes, echo
   traffic) hold connections — N stepped 8/16/32/64.  Echo round-trip
   latency is sampled at each step so degradation is visible, not
   just resource counts.

B. **tx/s vs peer count**: tools/bench_loadtime.py at different
   localnet sizes (full nodes, full-mesh peering).

Writes the curve to docs/data/peer_scaling.json and prints it.

    python tools/bench_peers.py [--steps 8,16,32,64]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SERVER_SNIPPET = r"""
import json, resource, sys, threading, time
sys.path.insert(0, {repo!r})
from cometbft_tpu.p2p.base_reactor import Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.test_util import make_switch

CH = 0x77

class Echo(Reactor):
    def __init__(self):
        super().__init__(name="echo")
    def get_channels(self):
        return [ChannelDescriptor(id=CH, priority=1)]
    def receive(self, env):
        env.src.send(CH, env.message)

sw = make_switch(network="peer-bench", moniker="srv",
                 reactors={{"echo": Echo()}})
sw.start()
la = sw.transport.listen_addr
print(json.dumps({{"host": la.host, "port": la.port,
                   "id": sw.node_info().node_id}}), flush=True)
while True:
    time.sleep(2.0)
    print(json.dumps({{
        "peers": len(sw.peers.copy()),
        "threads": threading.active_count(),
        "rss_kb": resource.getrusage(resource.RUSAGE_SELF).ru_maxrss,
        "cpu_s": round(time.process_time(), 3),
    }}), flush=True)
"""


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", default="8,16,32,64")
    ap.add_argument("--window", type=float, default=10.0,
                    help="seconds of echo churn per step")
    ap.add_argument(
        "--out",
        default=os.path.join(REPO, "docs", "data", "peer_scaling.json"),
    )
    args = ap.parse_args()
    steps = [int(s) for s in args.steps.split(",")]

    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    from cometbft_tpu.utils.device_env import scrub_plugin_env

    scrub_plugin_env(env)
    server = subprocess.Popen(
        [sys.executable, "-c", SERVER_SNIPPET.format(repo=REPO)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO,
    )
    hello = json.loads(server.stdout.readline())
    print(f"server: {hello}", file=sys.stderr)

    stats_lock = threading.Lock()
    latest: dict = {}

    def reader():
        for line in server.stdout:
            try:
                with stats_lock:
                    latest.update(json.loads(line))
            except ValueError:
                pass

    threading.Thread(target=reader, daemon=True).start()

    from cometbft_tpu.p2p.base_reactor import Reactor
    from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
    from cometbft_tpu.p2p.netaddr import NetAddress
    from cometbft_tpu.p2p.test_util import make_switch

    CH = 0x77
    srv_addr = NetAddress(
        id=hello["id"], host=hello["host"], port=hello["port"]
    )

    class Client(Reactor):
        def __init__(self):
            super().__init__(name="echo")
            self.event = threading.Event()

        def get_channels(self):
            return [ChannelDescriptor(id=CH, priority=1)]

        def receive(self, env):
            self.event.set()

    clients = []
    reactors = []
    curve = []
    try:
        for target in steps:
            while len(clients) < target:
                r = Client()
                sw = make_switch(
                    network="peer-bench",
                    moniker=f"c{len(clients)}",
                    reactors={"echo": r},
                )
                sw.start()
                sw.dial_peer_with_address(srv_addr)
                clients.append(sw)
                reactors.append(r)
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                with stats_lock:
                    if latest.get("peers", 0) >= target:
                        break
                time.sleep(0.5)
            with stats_lock:
                cpu_a = latest.get("cpu_s", 0.0)
            lat = []
            t_end = time.monotonic() + args.window
            while time.monotonic() < t_end:
                for r, sw in zip(reactors, clients):
                    peers = sw.peers.copy()
                    if not peers:
                        continue
                    r.event.clear()
                    t0 = time.perf_counter()
                    if not peers[0].send(CH, b"ping"):
                        continue
                    if r.event.wait(timeout=5):
                        lat.append(time.perf_counter() - t0)
                time.sleep(0.1)
            time.sleep(2.5)  # one more stats beat
            with stats_lock:
                snap = dict(latest)
            cpu_rate = (snap.get("cpu_s", 0.0) - cpu_a) / (
                args.window + 2.5
            )
            lat.sort()
            row = {
                "peers": snap.get("peers"),
                "server_threads": snap.get("threads"),
                "server_rss_kb": snap.get("rss_kb"),
                "server_cpu_cores": round(cpu_rate, 3),
                "echo_p50_ms": round(lat[len(lat) // 2] * 1e3, 2)
                if lat else None,
                "echo_p95_ms": round(lat[int(len(lat) * 0.95)] * 1e3, 2)
                if lat else None,
                "echo_samples": len(lat),
            }
            curve.append(row)
            print(json.dumps(row), flush=True)
    finally:
        for sw in clients:
            try:
                sw.stop()
            except Exception:
                pass
        server.kill()

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(
            {
                "measured": time.strftime("%Y-%m-%d"),
                "hardware": "single host, 1 CPU core (container); "
                            "clients share the core with the server",
                "transport_curve": curve,
                "promotion_criterion": (
                    "promote the secret-connection frame pump + accept "
                    "loop to native components when server CPU exceeds "
                    "~0.5 cores or echo p95 exceeds 50 ms at the "
                    "deployment's target peer count (reference default "
                    "caps: 40 inbound + 10 outbound peers)"
                ),
            },
            f,
            indent=1,
        )
    print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
