"""Measure the CPU-vs-device batch-verify crossover and recommend
DEVICE_MIN_BATCH (VERDICT r2 weak #6: the constant was never validated
against measurement).

Runs the REAL paths — ed25519.CpuBatchVerifier vs
ops.ed25519_verify.verify_arrays — at growing batch sizes and reports
the smallest batch where the device path wins end-to-end (transfers,
packing, and link round trips included).  Run on the target hardware:

    python tools/derive_device_min_batch.py

and wire the printed value via CMT_TPU_DEVICE_MIN_BATCH or update
ops/ed25519_verify.DEVICE_MIN_BATCH.
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main() -> None:
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.ops.ed25519_verify import verify_arrays

    rng = np.random.RandomState(3)
    priv = ed.gen_priv_key()
    pub = priv.pub_key()
    pub_b = np.frombuffer(pub.bytes(), dtype=np.uint8)

    sizes = [8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192]
    rows = []
    crossover = None
    # prepare the largest fixture once; slice per size
    nmax = sizes[-1]
    msgs = [
        rng.randint(0, 256, size=120, dtype=np.uint8).tobytes()
        for _ in range(nmax)
    ]
    print("signing fixture...", file=sys.stderr)
    sigs_all = np.stack(
        [np.frombuffer(priv.sign(m), dtype=np.uint8) for m in msgs]
    )
    pubs_all = np.tile(pub_b, (nmax, 1))

    for n in sizes:
        pubs, sigs, ms = pubs_all[:n], sigs_all[:n], msgs[:n]

        def cpu_run():
            bv = ed.CpuBatchVerifier()
            for m, s in zip(ms, sigs):
                bv.add(pub, m, s.tobytes())
            ok, _ = bv.verify()
            assert ok

        def dev_run():
            assert bool(verify_arrays(pubs, sigs, ms).all())

        dev_run()  # compile/warm this shape
        t_cpu = min(
            (lambda: (lambda t0: (cpu_run(), time.perf_counter() - t0)[1])(
                time.perf_counter()
            ))()
            for _ in range(3)
        )
        t_dev = min(
            (lambda: (lambda t0: (dev_run(), time.perf_counter() - t0)[1])(
                time.perf_counter()
            ))()
            for _ in range(3)
        )
        winner = "device" if t_dev < t_cpu else "cpu"
        rows.append(
            {
                "batch": n,
                "cpu_ms": round(t_cpu * 1e3, 2),
                "device_ms": round(t_dev * 1e3, 2),
                "winner": winner,
            }
        )
        print(json.dumps(rows[-1]), file=sys.stderr)
        if winner == "device" and crossover is None:
            crossover = n
        if winner == "cpu":
            crossover = None  # must win from here on up

    # per-sig slopes + fixed link cost -> calibration file the runtime
    # threshold (ops/ed25519_verify.runtime_device_min_batch) reads.
    import os

    from cometbft_tpu.ops.ed25519_verify import CALIBRATION_PATH

    big = rows[-1]
    mid = next(r for r in rows if r["batch"] >= 1024)
    t_dev_sig = max(
        (big["device_ms"] - mid["device_ms"])
        / 1e3
        / max(big["batch"] - mid["batch"], 1),
        1e-7,
    )
    t_cpu_sig = big["cpu_ms"] / 1e3 / big["batch"]
    rtt = max(mid["device_ms"] / 1e3 - mid["batch"] * t_dev_sig, 0.0)
    cal = {
        # schema 2: t_cpu measured through the native RLC host batch
        # verifier (round 5). Readers ignore older files — a schema-1
        # t_cpu (~120 us/sig per-signature path) would route mid-size
        # batches to a high-RTT device where the host now wins.
        "schema": 2,
        "t_cpu_per_sig": round(t_cpu_sig, 9),
        "t_dev_per_sig": round(t_dev_sig, 9),
        "fitted_link_rtt_s": round(rtt, 6),
        "rows": rows,
    }
    os.makedirs(os.path.dirname(CALIBRATION_PATH), exist_ok=True)
    with open(CALIBRATION_PATH, "w") as f:
        json.dump(cal, f, indent=1)
    print(f"calibration written to {CALIBRATION_PATH}", file=sys.stderr)

    print(
        json.dumps(
            {
                "recommended_device_min_batch": crossover or nmax * 2,
                "note": (
                    "device never won at measured sizes; keep CPU"
                    if crossover is None
                    else "smallest batch where the device path wins "
                    "end-to-end, stable through the largest measured"
                ),
                "calibration": {
                    k: v for k, v in cal.items() if k != "rows"
                },
                "rows": rows,
            }
        )
    )


if __name__ == "__main__":
    main()
