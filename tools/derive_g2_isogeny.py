"""Derive the BLS12-381 G2 SSWU 3-isogeny from first principles.

RFC 9380's BLS12381G2_XMD:SHA-256_SSWU_RO_ suite maps into an
isogenous curve E'': y^2 = x^3 + A''x + B'' over Fq2 (A'' = 240*I,
B'' = 1012*(1+I), Z = -(2+I)) and then applies a degree-3 isogeny to
the twist E': y^2 = x^3 + 4(1+I).  The RFC lists the isogeny's
rational-map coefficients as opaque constants; this script DERIVES
them instead (zero-egress environment — nothing to paste from):

1. roots of the 3-division polynomial psi_3 of E'' in Fq2 give the
   x-coordinates of order-3 points;
2. for each root, Velu's formulas give the unique normalized
   3-isogeny with that kernel and its codomain A_new/B_new;
3. the root whose codomain is exactly (0, 4(1+I)) is the RFC kernel
   (Velu-normalized isogenies are what Sage emits, which is how the
   suite's constants were produced — see draft-irtf-cfrg-hash-to-curve
   appendix and Wahby-Boneh 2019);
4. the y-map of a normalized isogeny is y * phi'(x).

Output: python source for the coefficient tables used by
cometbft_tpu/crypto/bls_hash_to_g2.py, printed to stdout.

Run: python tools/derive_g2_isogeny.py
"""

import sys

sys.path.insert(0, ".")

from cometbft_tpu.crypto.bls12381 import (  # noqa: E402
    F2_ONE,
    F2_ZERO,
    P,
    f2_add,
    f2_inv,
    f2_mul,
    f2_mul_scalar,
    f2_neg,
    f2_sq,
    f2_sub,
)

A2 = (0, 240)       # 240*I
B2 = (1012, 1012)   # 1012*(1+I)
TARGET_B = (4, 4)   # codomain constant 4*(1+I)


# -- dense polynomial helpers over Fq2 (coefficient lists, low->high) --

def pmul(a, b):
    out = [F2_ZERO] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai == F2_ZERO:
            continue
        for j, bj in enumerate(b):
            out[i + j] = f2_add(out[i + j], f2_mul(ai, bj))
    return out


def padd(a, b):
    n = max(len(a), len(b))
    a = a + [F2_ZERO] * (n - len(a))
    b = b + [F2_ZERO] * (n - len(b))
    return [f2_add(x, y) for x, y in zip(a, b)]


def psub(a, b):
    n = max(len(a), len(b))
    a = a + [F2_ZERO] * (n - len(a))
    b = b + [F2_ZERO] * (n - len(b))
    return [f2_sub(x, y) for x, y in zip(a, b)]


def pscale(a, s):
    return [f2_mul(x, s) for x in a]


def ptrim(a):
    while len(a) > 1 and a[-1] == F2_ZERO:
        a = a[:-1]
    return a


def pmod(a, m):
    """a mod m, m monic-ish (leading coeff inverted)."""
    a = list(a)
    dm = len(m) - 1
    inv_lead = f2_inv(m[-1])
    while len(a) - 1 >= dm and ptrim(a) != [F2_ZERO]:
        a = ptrim(a)
        if len(a) - 1 < dm:
            break
        c = f2_mul(a[-1], inv_lead)
        shift = len(a) - 1 - dm
        for i, mi in enumerate(m):
            a[shift + i] = f2_sub(a[shift + i], f2_mul(c, mi))
        a = a[:-1]
    return ptrim(a)


def pgcd(a, b):
    a, b = ptrim(a), ptrim(b)
    while b != [F2_ZERO]:
        a, b = b, pmod(a, b)
    # make monic
    return pscale(a, f2_inv(a[-1]))


def ppow_mod(base, e, m):
    out = [F2_ONE]
    base = pmod(base, m)
    while e:
        if e & 1:
            out = pmod(pmul(out, base), m)
        base = pmod(pmul(base, base), m)
        e >>= 1
    return out


def peval(a, x):
    acc = F2_ZERO
    for c in reversed(a):
        acc = f2_add(f2_mul(acc, x), c)
    return acc


def find_roots(poly):
    """All roots of poly in Fq2 (Cantor-Zassenhaus, char != 2)."""
    import random

    q = P * P
    poly = pscale(ptrim(poly), f2_inv(ptrim(poly)[-1]))
    # keep only the part splitting over Fq2
    xq = ppow_mod([F2_ZERO, F2_ONE], q, poly)
    lin = pgcd(psub(xq, [F2_ZERO, F2_ONE]), poly)
    roots = []

    def split(f):
        f = pscale(ptrim(f), f2_inv(ptrim(f)[-1]))
        d = len(f) - 1
        if d == 0:
            return
        if d == 1:
            roots.append(f2_neg(f[0]))
            return
        while True:
            r = (random.randrange(P), random.randrange(P))
            h = ppow_mod(padd([F2_ZERO, F2_ONE], [r]), (q - 1) // 2, f)
            g = pgcd(psub(h, [F2_ONE]), f)
            if 0 < len(g) - 1 < d:
                split(g)
                split(pdiv_exact(f, g))
                return

    def pdiv_exact(a, b):
        out = [F2_ZERO] * (len(a) - len(b) + 1)
        a = list(a)
        inv_lead = f2_inv(b[-1])
        for i in range(len(a) - len(b), -1, -1):
            c = f2_mul(a[len(b) - 1 + i], inv_lead)
            out[i] = c
            for j, bj in enumerate(b):
                a[i + j] = f2_sub(a[i + j], f2_mul(c, bj))
        return ptrim(out)

    split(lin)
    return roots


def derive():
    # psi_3 = 3x^4 + 6Ax^2 + 12Bx - A^2 for y^2 = x^3 + Ax + B
    psi3 = [
        f2_neg(f2_sq(A2)),
        f2_mul_scalar(B2, 12),
        f2_mul_scalar(A2, 6),
        F2_ZERO,
        (3, 0),
    ]
    roots = find_roots(psi3)
    print(f"# psi_3 roots in Fq2: {len(roots)}", file=sys.stderr)
    for x0 in roots:
        # Velu, kernel {O, (x0, +-y0)}:
        #   t = 2*(3 x0^2 + A); u = 4*(x0^3 + A x0 + B); w = u + x0 t
        #   codomain: A_new = A - 5t, B_new = B - 7w
        gx = f2_add(f2_mul_scalar(f2_sq(x0), 3), A2)
        t = f2_add(gx, gx)
        u = f2_mul_scalar(
            f2_add(f2_add(f2_mul(f2_sq(x0), x0), f2_mul(A2, x0)), B2), 4
        )
        w = f2_add(u, f2_mul(x0, t))
        a_new = f2_sub(A2, f2_mul_scalar(t, 5))
        b_new = f2_sub(B2, f2_mul_scalar(w, 7))
        print(f"# root {x0}: codomain A={a_new} B={b_new}", file=sys.stderr)
        if a_new == F2_ZERO:
            break
    else:
        raise SystemExit(
            "no kernel maps to a j=0 curve: remembered A''/B'' wrong?"
        )

    # The Velu-normalized codomain is y^2 = x^3 + b_new; compose with
    # the isomorphism (x, y) -> (s^2 x, s^3 y) where s^6 = 4(1+I)/b_new
    # to land exactly on E'.  (Here b_new = 2916(1+I) = 729 * 4(1+I),
    # so s = 1/3; the sign of s — equivalently composing with point
    # negation — is the one freedom RFC vectors would pin down.)
    ratio = f2_mul(TARGET_B, f2_inv(b_new))
    assert ratio[1] == 0, f"non-rational scaling {ratio}"
    for k in range(1, 10000):
        if ratio[0] == pow(k, -6, P):
            s = pow(k, -1, P)
            break
    else:
        raise SystemExit("no small rational 6th root for the isomorphism")
    # RFC 9380's published 3-isogeny uses the NEGATIVE root (s = -1/3):
    # with s = +1/3 every hashed point comes out negated — valid by all
    # on-curve/subgroup properties, wire-incompatible with blst.  Pinned
    # by the appendix J.10.1 KATs (tests/test_bls.py).
    s = P - s
    s2 = (pow(s, 2, P), 0)
    s3 = (pow(s, 3, P), 0)

    # x-map: phi(x) = s^2 * [x (x-x0)^2 + t (x-x0) + u] / (x-x0)^2
    h = [f2_neg(x0), F2_ONE]           # x - x0
    h2 = pmul(h, h)
    xnum_v = padd(padd(pmul([F2_ZERO, F2_ONE], h2), pscale(h, t)), [u])
    # y-map: s^3 * y * phi_v'(x) = s^3 y (xnum_v' h - 2 xnum_v h') / h^3
    dxnum = [f2_mul_scalar(c, i) for i, c in enumerate(xnum_v)][1:]
    ynum_v = psub(pmul(dxnum, h), pscale(xnum_v, (2, 0)))
    xnum = pscale(xnum_v, s2)
    xden = h2
    ynum = pscale(ynum_v, s3)
    yden = pmul(h2, h)

    # sanity: evaluate on a point of E'' and check the image is on E'
    # (needs a point: find x with x^3+Ax+B square in Fq2)
    from cometbft_tpu.crypto.bls12381 import f2_sqrt

    x = (5, 3)
    while True:
        rhs = f2_add(f2_add(f2_mul(f2_sq(x), x), f2_mul(A2, x)), B2)
        y = f2_sqrt(rhs)
        if y is not None:
            break
        x = (x[0] + 1, x[1])
    def ephi(pt):
        if pt is None:
            return None
        xx, yy = pt
        if peval(xden, xx) == F2_ZERO:
            return None  # kernel -> identity
        xo = f2_mul(peval(xnum, xx), f2_inv(peval(xden, xx)))
        yo = f2_mul(yy, f2_mul(peval(ynum, xx), f2_inv(peval(yden, xx))))
        return (xo, yo)

    def epp_add(p1, p2):
        """Affine addition on E'' (a != 0 so the module's a=0 Jacobian
        formulas don't apply here)."""
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        (x1, y1), (x2, y2) = p1, p2
        if x1 == x2:
            if f2_add(y1, y2) == F2_ZERO:
                return None
            lam = f2_mul(
                f2_add(f2_mul_scalar(f2_sq(x1), 3), A2),
                f2_inv(f2_add(y1, y1)),
            )
        else:
            lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
        x3 = f2_sub(f2_sub(f2_sq(lam), x1), x2)
        return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))

    def ep_add(p1, p2):
        """Affine addition on E' (b = 4(1+I))."""
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        (x1, y1), (x2, y2) = p1, p2
        if x1 == x2:
            if f2_add(y1, y2) == F2_ZERO:
                return None
            lam = f2_mul(f2_mul_scalar(f2_sq(x1), 3), f2_inv(f2_add(y1, y1)))
        else:
            lam = f2_mul(f2_sub(y2, y1), f2_inv(f2_sub(x2, x1)))
        x3 = f2_sub(f2_sub(f2_sq(lam), x1), x2)
        return (x3, f2_sub(f2_mul(lam, f2_sub(x1, x3)), y1))

    pt1 = (x, y)
    img1 = ephi(pt1)
    lhs = f2_sq(img1[1])
    rhs = f2_add(f2_mul(f2_sq(img1[0]), img1[0]), (4, 4))
    assert lhs == rhs, "image not on E': derivation bug"
    # homomorphism: phi(P+P) == phi(P) + phi(P)
    assert ephi(epp_add(pt1, pt1)) == ep_add(img1, img1), "not a homomorphism"
    # kernel maps to identity: (x0, y0) has phi undefined (pole)
    print("# image-on-curve + homomorphism checks passed", file=sys.stderr)

    def fmt(coeffs, name):
        rows = ",\n    ".join(f"({c[0]:#x}, {c[1]:#x})" for c in coeffs)
        return f"{name} = (\n    {rows},\n)"

    print("# Derived by tools/derive_g2_isogeny.py — do not edit by hand.")
    print(fmt(xnum, "ISO3_XNUM"))
    print(fmt(xden, "ISO3_XDEN"))
    print(fmt(ynum, "ISO3_YNUM"))
    print(fmt(yden, "ISO3_YDEN"))


if __name__ == "__main__":
    derive()
