"""BASELINE configs measured through the PRODUCTION dispatch on the
host path — full counts, no modeling, no device.

Round-5 context: the device tunnel never opened (the watcher's device
re-measure stays queued), but the production no-device dispatch gained
the native RLC batch verifier, so these shapes deserve fresh honest
numbers through types/validation.verify_commit — the path a real
no-accelerator deployment takes. Entries are merged into
BENCH_ALL.json with explicit host provenance.

    python tools/bench_host_baseline.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the host path must not wait on the wedged device tunnel: scrub the
# plugin env for children AND force the in-process platform to cpu —
# env scrubbing alone cannot undo a sitecustomize registration, and
# TpuBatchVerifier's threshold probe would hit jax.devices() in C
os.environ["CMT_TPU_DISABLE_DEVICE_VERIFY"] = "1"
from cometbft_tpu.utils.device_env import (  # noqa: E402
    force_cpu_platform,
    scrub_plugin_env,
)

scrub_plugin_env(os.environ)
force_cpu_platform()

from bench_all import (  # noqa: E402
    CHAIN_ID,
    log,
    make_commit_fixture,
    merge_results,
    timed,
)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--label", default="",
        help="measurement label prefix (e.g. 'round 5'); stamped "
        "alongside the date so reruns never carry a stale round tag",
    )
    args = ap.parse_args()
    label = (args.label + ", " if args.label else "") + time.strftime(
        "%Y-%m-%d"
    )

    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.crypto.batch import create_batch_verifier
    from cometbft_tpu.types import validation

    import numpy as np

    results = []

    def record(config: str, value: float, unit: str, **extra):
        row = {"config": config, "value": round(value, 2), "unit": unit}
        row.update(extra)
        row["measured"] = label
        row["host_path"] = True  # merge key: host rows replace only
        # host rows, never device-measured entries
        row["provenance"] = (
            "PRODUCTION no-device dispatch (native RLC host batch "
            "verifier, native/crypto/ed25519_batch.cpp); full counts, "
            "nothing modeled. Device keyed-path numbers are recorded "
            "separately when a device window allows."
        )
        results.append(row)
        print(json.dumps(row), flush=True)

    # ---- config 1: 64-sig micro-bench, production dispatch -----------
    # through the REAL seam (crypto/batch.py create_batch_verifier),
    # which honors CMT_TPU_DISABLE_DEVICE_VERIFY and selects the host
    # verifier here — so the recorded path matches the label even if a
    # device happens to be visible
    rng = np.random.RandomState(7)
    priv = ed.gen_priv_key()
    msgs64 = [rng.bytes(120) for _ in range(64)]
    sigs64 = [priv.sign(m) for m in msgs64]
    pub = priv.pub_key()

    def micro():
        bv = create_batch_verifier(pub)
        for m, s in zip(msgs64, sigs64):
            bv.add(pub, m, s)
        ok, _ = bv.verify()
        assert ok

    dt = timed(micro)
    record(
        "micro_64sig", 64 / dt, "sigs/sec",
        latency_ms=round(dt * 1e3, 2), dispatch="host RLC batch",
    )

    # ---- config 2: VerifyCommit @ 150 validators ---------------------
    t0 = time.time()
    vals150, commit150, bid150 = make_commit_fixture(150)
    log(f"150-val fixture in {time.time() - t0:.1f}s")

    def vc150():
        validation.verify_commit(CHAIN_ID, vals150, bid150, 1, commit150)

    dt = timed(vc150)
    record(
        "verify_commit_150", dt * 1e3, "ms",
        sigs_per_sec=round(150 / dt, 1),
    )

    # ---- config 3: VerifyCommit @ 10k validators (FULL) --------------
    t0 = time.time()
    vals10k, commit10k, bid10k = make_commit_fixture(10_000)
    log(f"10k-val fixture in {time.time() - t0:.1f}s")

    def vc10k():
        validation.verify_commit(CHAIN_ID, vals10k, bid10k, 1, commit10k)

    dt = timed(vc10k)
    record(
        "verify_commit_10000", dt * 1e3, "ms",
        sigs_per_sec=round(10_000 / dt, 1), target_ms=2.0,
    )

    # ---- config 4: light sync, 10k headers x 150-val commits (FULL) --
    n4 = 10_000
    t0 = time.time()
    done = 0
    while done < n4:
        vc150()
        done += 1
    dt = time.time() - t0
    record(
        "light_sync_150val", n4 * 150 / dt, "sigs/sec",
        commits_per_sec=round(n4 / dt, 1), n_commits_run=n4,
    )

    # ---- config 5: blocksync replay, 1k blocks x 1k-val (FULL) -------
    t0 = time.time()
    vals1k, commit1k, bid1k = make_commit_fixture(1000)
    log(f"1k-val fixture in {time.time() - t0:.1f}s")
    n5 = 1000
    t0 = time.time()
    for _ in range(n5):
        validation.verify_commit(CHAIN_ID, vals1k, bid1k, 1, commit1k)
    dt = time.time() - t0
    record(
        "blocksync_replay_1kval", n5 * 1000 / dt, "sigs/sec",
        commits_per_sec=round(n5 / dt, 1), n_commits_run=n5,
    )

    # merge into BENCH_ALL.json: host rows replace only PRIOR host
    # rows for the same config — device-measured entries (and the
    # top-level device field) are never clobbered by a host refresh
    path = os.path.join(REPO, "BENCH_ALL.json")
    ours = {r["config"] for r in results}
    merge_results(
        path, results,
        replace_if=lambda r: (
            r.get("config") in ours and r.get("host_path")
        ),
    )
    log(f"merged {len(results)} host entries into BENCH_ALL.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
