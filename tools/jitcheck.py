"""jitcheck: static device-path correctness lint — the compile-time
half of the jit/retrace toolchain (runtime half: CMT_TPU_JITGUARD in
cometbft_tpu/ops/jitguard.py; docs/device_contracts.md is the manual).

PR 3 gave the host-concurrency plane a `go test -race` analog
(tools/lockcheck.py + utils/sync.py runtime modes); this is the same
treatment for the device plane, where the silent failure modes are a
retrace, an implicit host<->device transfer, or a shape/dtype drift in
the kernel ABI — all of which degrade the hot path with no error and
no signal.  Four AST checks, lockcheck-style:

1. **Jit-seam check.**  Every ``jax.jit`` call in ``cometbft_tpu``
   must sit inside a REGISTERED compile-cache seam (``JIT_SEAMS`` —
   the ``_compiled*`` memoizers and the memoized
   ``sharded_verify_fn``).  A seam must (a) memoize through a
   module-level ``*_cache`` dict, (b) take only parameters from the
   audited pow2/bucket/chunk ladder (``LADDER_PARAMS``) so the jit
   cache stays bounded, and (c) report its misses through
   ``jitguard.note_compile`` so the runtime retrace guard and BENCH
   provenance see every compile.

2. **Closure-globals check.**  The callable handed to ``jax.jit`` may
   not load a module global that is REBOUND anywhere (a ``global``
   statement, or multiple module-scope assignments): such a value is
   captured at trace time, so later mutation silently diverges the
   compiled program from the source (program-shaping flags belong in
   the cache key — see field.trace_config()).

3. **Host-sync check** (device-plane files only: ``ops/``,
   ``parallel/``, ``crypto/batch.py``).  Host-synchronization sites —
   ``np.asarray``, ``jax.device_get``, ``.item()``,
   ``.block_until_ready()``, ``jax.debug.callback``, and
   ``float()``/``bool()``/``int()`` on a device-tainted local — must
   carry an audited ``# host sync: <reason>`` waiver (mirroring
   lockcheck's ``# unguarded:``).  Waivers are counted and reported;
   a waiver on a line with no sync site is a STALE-WAIVER error, so
   annotations cannot outlive the code they audit.

4. **Kernel-contract check.**  Every public kernel in
   ``REQUIRED_CONTRACTS`` must declare a ``_CONTRACTS`` entry (pure
   literals, grammar in ops/contracts.py) whose arg names match the
   function signature, whose dtypes come from the audited set (int32
   limbs, uint8 packed buffers...), and whose dims reference only the
   known ladder symbols.  The deviceless ``jax.eval_shape`` sweep in
   tests/test_jitcheck.py then verifies the declarations against the
   traced kernels across the bucket ladder.

Known static limits (the runtime guard covers these): host syncs
reached through helper calls, taint through attributes/containers,
and jit wrappers constructed outside the seams at runtime are not
seen; CMT_TPU_JITGUARD=1 catches them as retraces / transfer-guard
trips.

    python tools/jitcheck.py            # exit 0 clean, 1 with a report
    python tools/jitcheck.py -v         # also list waivers

Run in the tier-1 flow via tests/test_jitcheck.py and standalone via
``make jitcheck``; tools/metrics_lint.py main() gates on it too.
"""

from __future__ import annotations

import ast
import os
import sys
from dataclasses import dataclass

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.lintlib import (  # noqa: E402 — path bootstrap above
    SCAN_ROOT,
    Violation,
    Waiver,
    check_stale_waivers,
    comments_by_line as _comments_by_line,
    dotted as _dotted,
    iter_py_files,
    run_main,
    waiver_re,
)
from tools import lintlib  # noqa: E402

#: the registered compile-cache seams: (file, function) pairs allowed
#: to call jax.jit — everything the runtime guard's note_compile sees
JIT_SEAMS = frozenset(
    {
        (os.path.join("cometbft_tpu", "ops", "ed25519_verify.py"),
         "_compiled"),
        (os.path.join("cometbft_tpu", "ops", "ed25519_verify.py"),
         "_compiled_chunked"),
        (os.path.join("cometbft_tpu", "ops", "ed25519_verify.py"),
         "_compiled_keyed"),
        (os.path.join("cometbft_tpu", "ops", "precompute.py"),
         "_compiled_build"),
        (os.path.join("cometbft_tpu", "parallel", "mesh.py"),
         "sharded_verify_fn"),
        (os.path.join("cometbft_tpu", "parallel", "mesh.py"),
         "_compiled_keyed_mesh"),
    }
)

#: parameter names a seam may key its cache on — the pow2/bucket/chunk
#: ladder (plus the mesh handle, itself drawn from the cached
#: flat_mesh).  Anything else is an unbounded cache dimension.
LADDER_PARAMS = frozenset(
    {"batch", "bucket", "chunk", "window_bits", "n", "nblocks", "mesh"}
)

#: device-plane files subject to the host-sync check
SYNC_SCOPE_DIRS = (
    os.path.join("cometbft_tpu", "ops") + os.sep,
    os.path.join("cometbft_tpu", "parallel") + os.sep,
)
SYNC_SCOPE_FILES = frozenset(
    {os.path.join("cometbft_tpu", "crypto", "batch.py")}
)

#: public kernels that MUST declare a _CONTRACTS entry
REQUIRED_CONTRACTS = {
    os.path.join("cometbft_tpu", "ops", "ed25519_verify.py"): frozenset(
        {"build_padded_input", "verify_kernel", "verify_kernel_packed",
         "verify_kernel_keyed", "verify_kernel_keyed_packed"}
    ),
    os.path.join("cometbft_tpu", "ops", "field.py"): frozenset(
        {"from_bytes_le", "to_bytes_le", "reduce_full", "mul", "square"}
    ),
    os.path.join("cometbft_tpu", "ops", "curve.py"): frozenset(
        {"decompress", "nibbles_from_bytes_le", "comb_mul_base",
         "window_mul", "mul8"}
    ),
    os.path.join("cometbft_tpu", "ops", "scalar.py"): frozenset(
        {"reduce_digest", "bytes_lt_l", "limbs_to_windows8",
         "limbs_to_nibbles"}
    ),
    os.path.join("cometbft_tpu", "ops", "sha512.py"): frozenset(
        {"sha512_padded", "bytes_to_words", "words_to_bytes"}
    ),
    os.path.join("cometbft_tpu", "ops", "precompute.py"): frozenset(
        {"build_tables_kernel", "comb_mul_base8", "comb_mul_keyed"}
    ),
    os.path.join("cometbft_tpu", "parallel", "mesh.py"): frozenset(
        {"verify_keyed_shard"}
    ),
}

_WAIVER_RE = waiver_re("host sync")

#: contract vocabulary — mirrored from ops/contracts.py WITHOUT
#: importing it (the ops package import initializes jax; a lint must
#: stay side-effect free).  tests/test_jitcheck.py asserts the two
#: stay in lockstep.
DTYPES_OK = frozenset({"u8", "i32", "i64", "u64", "bool"})
DIM_SYMBOLS = frozenset(
    {"B", "bucket", "nblocks", "NLIMBS", "nwin", "nent", "cap", "M",
     "ndev"}
)
STATIC_PARAMS_OK = DIM_SYMBOLS | {"window_bits"}


def _dim_names(dim) -> set[str]:
    if isinstance(dim, int):
        return set()
    return {
        n.id
        for n in ast.walk(ast.parse(str(dim), mode="eval"))
        if isinstance(n, ast.Name)
    }


def _is_leaf_spec(spec) -> bool:
    return (
        isinstance(spec, tuple)
        and len(spec) == 2
        and isinstance(spec[0], str)
    )


@dataclass
class Report(lintlib.Report):
    jit_calls: int = 0
    seams: int = 0
    contracts: int = 0
    sync_sites: int = 0


def _is_jit_call(node: ast.Call) -> bool:
    d = _dotted(node.func)
    return d in {"jax.jit", "jit"}


# -- module-level binding census (closure-globals check) ----------------


def _module_rebound_names(tree: ast.Module) -> set[str]:
    """Module globals that are REBOUND: targets of a ``global``
    statement anywhere, or assigned more than once at module scope."""
    counts: dict[str, int] = {}
    rebound: set[str] = set()
    for stmt in tree.body:
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for tgt in targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                if isinstance(el, ast.Name):
                    counts[el.id] = counts.get(el.id, 0) + 1
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            rebound.update(node.names)
    rebound.update(n for n, c in counts.items() if c > 1)
    return rebound


def _bound_names(fn: ast.AST) -> set[str]:
    """Names bound inside a function/lambda: params + assignments +
    comprehension targets + inner defs."""
    bound: set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in (
            a.posonlyargs + a.args + a.kwonlyargs
            + ([a.vararg] if a.vararg else [])
            + ([a.kwarg] if a.kwarg else [])
        ):
            bound.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
    return bound


class _FileChecker:
    def __init__(self, rel: str, source: str, report: Report):
        self.rel = rel
        self.source = source
        self.report = report
        self.comments = _comments_by_line(source)
        self.waived_lines: set[int] = set()   # lines with a USED waiver
        self.flagged_lines: set[int] = set()  # lines with any sync site

    def run(self) -> None:
        try:
            tree = lintlib.parse_cached(self.source)
        except SyntaxError as exc:
            self.report.violations.append(
                Violation(self.rel, exc.lineno or 0,
                          f"syntax error: {exc.msg}")
            )
            return
        self.rebound = _module_rebound_names(tree)
        self._check_jit_calls(tree)
        if self._in_sync_scope():
            self._check_host_syncs(tree)
            self._check_stale_waivers()
        self._check_contracts(tree)

    def _in_sync_scope(self) -> bool:
        return (
            self.rel in SYNC_SCOPE_FILES
            or any(self.rel.startswith(d) for d in SYNC_SCOPE_DIRS)
        )

    # -- jit seam + closure checks --------------------------------------

    def _check_jit_calls(self, tree: ast.Module) -> None:
        # map every jax.jit call to its innermost enclosing function
        def walk(node: ast.AST, fn_stack: tuple):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_stack = fn_stack + (node,)
            for child in ast.iter_child_nodes(node):
                walk(child, fn_stack)
            if isinstance(node, ast.Call) and _is_jit_call(node):
                self.report.jit_calls += 1
                self._check_one_jit(node, fn_stack)

        walk(tree, ())

    def _check_one_jit(self, call: ast.Call, fn_stack: tuple) -> None:
        outer = fn_stack[0] if fn_stack else None
        seam_name = outer.name if outer is not None else "<module>"
        if (self.rel, seam_name) not in JIT_SEAMS:
            self.report.violations.append(
                Violation(
                    self.rel, call.lineno,
                    f"jax.jit called in {seam_name}() which is not a "
                    "registered compile-cache seam — route the compile "
                    "through a memoizer in JIT_SEAMS (tools/jitcheck.py) "
                    "so retraces are counted, guarded, and bounded",
                )
            )
            return
        self.report.seams += 1
        self._check_seam_discipline(outer)
        # the traced callable: first positional arg
        if call.args:
            self._check_closure_globals(call.args[0], fn_stack)

    def _check_seam_discipline(self, fn: ast.FunctionDef) -> None:
        params = {
            a.arg
            for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        }
        off_ladder = params - LADDER_PARAMS
        if off_ladder:
            self.report.violations.append(
                Violation(
                    self.rel, fn.lineno,
                    f"seam {fn.name}() keys its cache on non-ladder "
                    f"parameter(s) {sorted(off_ladder)} — only the "
                    f"pow2/bucket/chunk ladder ({sorted(LADDER_PARAMS)}) "
                    "keeps the jit cache bounded",
                )
            )
        names = {
            n.id for n in ast.walk(fn) if isinstance(n, ast.Name)
        }
        attrs = {
            n.attr for n in ast.walk(fn) if isinstance(n, ast.Attribute)
        }
        if not any(n.endswith("_cache") for n in names | attrs):
            self.report.violations.append(
                Violation(
                    self.rel, fn.lineno,
                    f"seam {fn.name}() does not reference a module-level "
                    "*_cache memoizer — an unmemoized jax.jit wrapper "
                    "retraces per call",
                )
            )
        if "note_compile" not in attrs and "note_compile" not in names:
            self.report.violations.append(
                Violation(
                    self.rel, fn.lineno,
                    f"seam {fn.name}() does not call "
                    "jitguard.note_compile — cache misses would be "
                    "invisible to the retrace guard and BENCH provenance",
                )
            )

    def _check_closure_globals(self, fn_arg: ast.expr, fn_stack) -> None:
        target: ast.AST | None = None
        if isinstance(fn_arg, ast.Lambda):
            target = fn_arg
        elif isinstance(fn_arg, ast.Name):
            # a local `def` in any enclosing function scope
            for fn in reversed(fn_stack):
                for node in ast.walk(fn):
                    if (
                        isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and node.name == fn_arg.id
                    ):
                        target = node
                        break
                if target is not None:
                    break
        if target is None:
            return
        bound = _bound_names(target)
        for fn in fn_stack:
            bound |= _bound_names(fn)
        for node in ast.walk(target):
            if (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id not in bound
                and node.id in self.rebound
            ):
                self.report.violations.append(
                    Violation(
                        self.rel, node.lineno,
                        f"jit closure captures mutable module global "
                        f"'{node.id}' (rebound via `global` or multiple "
                        "module-scope assignments) — its value is baked "
                        "in at trace time; pass it as an argument or "
                        "fold it into the compile-cache key "
                        "(field.trace_config())",
                    )
                )

    # -- host-sync check ------------------------------------------------

    def _check_host_syncs(self, tree: ast.Module) -> None:
        # every def is its own scope, and so is the module body itself
        # (a module-init sync site is just as real as one in a
        # function — and its waiver must not read as stale)
        self._scan_scope(tree, "<module>")
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_scope(node, node.name)

    @staticmethod
    def _walk_scope(root: ast.AST):
        """ast.walk restricted to ONE scope: does not descend into
        nested function/lambda bodies (each def is scanned as its own
        scope — descending would both double-report their sites and
        leak taint across scopes)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    def _scan_scope(self, scope: ast.AST, where: str) -> None:
        compiled_vars: set[str] = set()
        device_vars: set[str] = set()

        def rhs_taints(value: ast.expr) -> tuple[bool, bool]:
            """(is_compiled_fn, is_device_value) for an assignment RHS."""
            is_compiled = is_device = False
            for node in ast.walk(value):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    base = d.split(".")[-1]
                    if base.startswith("_compiled"):
                        is_compiled = True
                    if d.startswith("jnp.") or d == "jax.device_put":
                        is_device = True
                    if (
                        isinstance(node.func, ast.Name)
                        and node.func.id in compiled_vars
                    ):
                        is_device = True
            return is_compiled, is_device

        for node in self._walk_scope(scope):
            if isinstance(node, ast.Assign):
                is_compiled, is_device = rhs_taints(node.value)
                for tgt in node.targets:
                    elts = (
                        tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    )
                    for el in elts:
                        if isinstance(el, ast.Name):
                            if is_compiled:
                                compiled_vars.add(el.id)
                            if is_device:
                                device_vars.add(el.id)

        for node in self._walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            site = self._sync_site(node, device_vars)
            if site is not None:
                self._flag_sync(node, site, where)

    def _sync_site(self, node: ast.Call, device_vars: set[str]) -> str | None:
        d = _dotted(node.func)
        if d in {"np.asarray", "numpy.asarray"}:
            return d
        if d == "jax.device_get":
            return d
        if d == "jax.debug.callback":
            return d
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "item" and not node.args:
                return ".item()"
            if node.func.attr == "block_until_ready":
                return ".block_until_ready()"
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in {"float", "bool", "int"}
            and len(node.args) == 1
        ):
            arg = node.args[0]
            name = None
            if isinstance(arg, ast.Name):
                name = arg.id
            elif isinstance(arg, ast.Subscript) and isinstance(
                arg.value, ast.Name
            ):
                name = arg.value.id
            if name in device_vars:
                return f"{node.func.id}() on device value '{name}'"
        return None

    def _flag_sync(self, node: ast.Call, site: str, where: str) -> None:
        self.report.sync_sites += 1
        self.flagged_lines.add(node.lineno)
        m = _WAIVER_RE.search(self.comments.get(node.lineno, ""))
        if m:
            if node.lineno not in self.waived_lines:
                self.waived_lines.add(node.lineno)
                self.report.waivers.append(
                    Waiver(self.rel, node.lineno, site, m.group(1).strip())
                )
            return
        self.report.violations.append(
            Violation(
                self.rel, node.lineno,
                f"host-sync site {site} in {where}() without an audited "
                "waiver — a blocking transfer here stalls the device "
                "pipeline (~70ms RTT on a tunneled backend); batch it "
                "through the documented single-fetch path (_finish) or "
                "waive with '# host sync: <reason>'",
            )
        )

    def _check_stale_waivers(self) -> None:
        check_stale_waivers(
            self.comments, self.flagged_lines, _WAIVER_RE,
            self.rel, self.report, "host sync",
        )

    # -- contract check -------------------------------------------------

    def _check_contracts(self, tree: ast.Module) -> None:
        contracts: dict = {}
        decl_line = 0
        for stmt in tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_CONTRACTS"
            ):
                decl_line = stmt.lineno
                try:
                    contracts = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError):
                    self.report.violations.append(
                        Violation(
                            self.rel, stmt.lineno,
                            "_CONTRACTS must be a pure literal "
                            "(no names, calls, or comprehensions) so it "
                            "is statically checkable",
                        )
                    )
                    return
        required = REQUIRED_CONTRACTS.get(self.rel, frozenset())
        missing = required - set(contracts)
        if missing:
            self.report.violations.append(
                Violation(
                    self.rel, decl_line or 1,
                    f"public kernel(s) {sorted(missing)} have no "
                    "_CONTRACTS entry — shape/dtype regressions would "
                    "only surface on device",
                )
            )
        if not contracts:
            return
        fns = {
            n.name: n
            for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for fname, contract in contracts.items():
            self._check_one_contract(fname, contract, fns, decl_line)

    def _check_one_contract(
        self, fname: str, contract, fns: dict, line: int
    ) -> None:
        def bad(msg: str) -> None:
            self.report.violations.append(
                Violation(self.rel, line, f"_CONTRACTS[{fname!r}]: {msg}")
            )

        fn = fns.get(fname)
        if fn is None:
            bad("names no module-level function")
            return
        if not isinstance(contract, dict) or "args" not in contract or \
                "out" not in contract:
            bad("must be a dict with 'args' and 'out'")
            return
        params = [
            a.arg
            for a in fn.args.posonlyargs + fn.args.args
        ]
        static = tuple(contract.get("static", ()))
        declared = list(contract["args"]) + list(static)
        if set(declared) != set(params):
            bad(
                f"declares args {sorted(declared)} but the signature "
                f"has {params}"
            )
        for sname in static:
            if sname not in STATIC_PARAMS_OK:
                bad(
                    f"static arg {sname!r} is not a ladder symbol "
                    f"({sorted(STATIC_PARAMS_OK)}) — off-ladder statics "
                    "unbound the jit cache"
                )
        self.report.contracts += 1
        for spec in list(contract["args"].values()) + [contract["out"]]:
            self._check_spec(fname, spec, bad)

    def _check_spec(self, fname: str, spec, bad) -> None:
        if _is_leaf_spec(spec):
            dtype, shape = spec
            if dtype not in DTYPES_OK:
                bad(f"dtype {dtype!r} not in the audited set "
                    f"{sorted(DTYPES_OK)}")
            if not isinstance(shape, tuple):
                bad(f"shape {shape!r} must be a tuple of dims")
                return
            for dim in shape:
                if isinstance(dim, int):
                    continue
                try:
                    unknown = _dim_names(dim) - DIM_SYMBOLS
                except SyntaxError:
                    bad(f"unparseable dim expression {dim!r}")
                    continue
                if unknown:
                    bad(
                        f"dim {dim!r} references unknown symbol(s) "
                        f"{sorted(unknown)} (known: {sorted(DIM_SYMBOLS)})"
                    )
            return
        if isinstance(spec, list):
            for sub in spec:
                self._check_spec(fname, sub, bad)
            return
        bad(f"spec {spec!r} is neither a (dtype, shape) leaf nor a list")


def check_source(source: str, rel: str) -> Report:
    """Lint one file's source; ``rel`` is the path used in reports and
    scope decisions (fixtures pass cometbft_tpu/ops/... paths)."""
    report = Report()
    _FileChecker(rel, source, report).run()
    return report


def check_tree(root: str = SCAN_ROOT) -> Report:
    report = Report()
    seen: set[str] = set()
    for rel, source in iter_py_files(root):
        seen.add(rel)
        report.merge(check_source(source, rel))
    # coverage: a REQUIRED_CONTRACTS file that vanished entirely would
    # otherwise silently pass
    for rel in REQUIRED_CONTRACTS:
        if rel not in seen:
            report.violations.append(
                Violation(rel, 0, "REQUIRED_CONTRACTS file missing")
            )
    return report


def _summary(report: Report) -> str:
    return (
        f"{report.jit_calls} jax.jit calls through "
        f"{report.seams} registered seams; {report.contracts} kernel "
        f"contracts; {report.sync_sites} host-sync sites "
        f"({len(report.waivers)} audited waivers)"
    )


def main(argv: list[str] | None = None) -> int:
    return run_main("jitcheck", check_tree, _summary, argv)


if __name__ == "__main__":
    sys.exit(main())
