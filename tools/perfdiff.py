"""perfdiff: compare two perf-ledger points and gate on regression.

    python tools/perfdiff.py OLD.json NEW.json [--threshold 0.10]
    python tools/perfdiff.py --selftest          # make perf-gate

Inputs are perf-ledger documents (tools/perfledger.py schema) or any
BENCH_ALL-shaped ``{"results": [...]}`` file; for each ``config``
present in both, the LATEST entry on each side is compared with a
noise-aware relative threshold:

- direction comes from the unit: throughput units (sigs/sec, ops/sec,
  tx/sec...) regress DOWN, latency units (ms, s, ns_per_op) regress
  UP;
- the default threshold (10%) sits above the run-to-run noise the
  bench history shows (repeat trials of the same config vary ~3-5% on
  this stack: bench.py takes best-of-3 precisely because single runs
  wobble) and well below any change worth a human's attention — the
  measured regressions that mattered were 3-5x, not 1.1x;
- values <= 0 on either side are skipped (a 0 means "the device was
  down", which the availability entries record separately — gating on
  it would page on every tunnel outage instead of every code change).

Exit status: 0 clean, 1 when any compared config regressed past the
threshold, 2 on usage errors.  ``--selftest`` (what ``make perf-gate``
runs, standalone and in tier-1 via tests/test_health.py) proves the
gate's calibration against the committed fixture pair in
tests/data/perf_gate/: a seeded 20% regression MUST fail and seeded
noise-level (3%) deltas MUST pass — so the gate cannot silently decay
into always-green or always-red.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_THRESHOLD = 0.10

#: units where SMALLER is better; everything else is throughput-like
LOWER_BETTER_UNITS = frozenset({"ms", "s", "seconds", "ns_per_op"})

FIXTURE_DIR = os.path.join(REPO, "tests", "data", "perf_gate")


def _latest_by_config(doc: dict) -> dict[str, dict]:
    """config -> last entry, from a ledger or BENCH_ALL-shaped doc."""
    rows = doc.get("entries")
    if rows is None:
        rows = doc.get("results", [])
    out: dict[str, dict] = {}
    for row in rows:
        cfg = row.get("config") or row.get("metric")
        if cfg is None or row.get("value") is None:
            continue
        out[cfg] = row  # later entries win: the ledger is append-order
    return out


def compare(
    old_doc: dict, new_doc: dict,
    threshold: float = DEFAULT_THRESHOLD,
    configs: list[str] | None = None,
) -> tuple[list[dict], list[dict]]:
    """Returns (regressions, comparisons): every config compared, and
    the subset whose delta crossed the threshold in the bad
    direction."""
    old = _latest_by_config(old_doc)
    new = _latest_by_config(new_doc)
    names = configs or sorted(set(old) & set(new))
    comparisons: list[dict] = []
    regressions: list[dict] = []
    for cfg in names:
        o, n = old.get(cfg), new.get(cfg)
        if o is None or n is None:
            continue
        try:
            ov, nv = float(o["value"]), float(n["value"])
        except (TypeError, ValueError):
            continue
        if ov <= 0 or nv <= 0:
            continue  # availability zeros, not perf points
        unit = n.get("unit") or o.get("unit") or ""
        lower_better = unit in LOWER_BETTER_UNITS
        # delta > 0 always means WORSE, whichever way the unit points
        delta = (nv - ov) / ov if lower_better else (ov - nv) / ov
        row = {
            "config": cfg, "unit": unit, "old": ov, "new": nv,
            "delta": round(delta, 4), "threshold": threshold,
            "regressed": delta > threshold,
        }
        comparisons.append(row)
        if row["regressed"]:
            regressions.append(row)
    return regressions, comparisons


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _report(regressions: list[dict], comparisons: list[dict]) -> None:
    for row in comparisons:
        mark = "REGRESSION" if row["regressed"] else "ok"
        print(
            f"perfdiff: {row['config']}: {row['old']:g} -> "
            f"{row['new']:g} {row['unit']} "
            f"({row['delta'] * 100:+.1f}% worse, threshold "
            f"{row['threshold'] * 100:.0f}%) {mark}",
            file=sys.stderr if row["regressed"] else sys.stdout,
        )
    if not comparisons:
        print("perfdiff: no comparable configs", file=sys.stderr)


def selftest() -> int:
    """Prove the gate's calibration on the committed fixture pair:
    the seeded 20% regression must trip it, the seeded 3% noise must
    not.  This is what ``make perf-gate`` runs — deterministic (no
    live measurement), so it can gate ``make test``."""
    baseline = _load(os.path.join(FIXTURE_DIR, "baseline.json"))
    regressed = _load(os.path.join(FIXTURE_DIR, "regressed.json"))
    noise = _load(os.path.join(FIXTURE_DIR, "noise.json"))
    failures: list[str] = []
    regs, comps = compare(baseline, regressed)
    if not comps:
        failures.append("fixture pair produced no comparisons")
    missed = [c["config"] for c in comps if not c["regressed"]]
    if missed:
        failures.append(
            f"seeded 20% regression NOT detected for: {missed}"
        )
    regs_noise, comps_noise = compare(baseline, noise)
    if not comps_noise:
        failures.append("noise fixture produced no comparisons")
    if regs_noise:
        failures.append(
            "noise-level deltas tripped the gate: "
            f"{[r['config'] for r in regs_noise]}"
        )
    if failures:
        for f in failures:
            print(f"perf-gate selftest FAILED: {f}", file=sys.stderr)
        return 1
    print(
        f"perf-gate: ok — seeded 20% regression detected on "
        f"{len(comps)} config(s), {len(comps_noise)} noise-level "
        "delta(s) passed"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", nargs="?", help="baseline ledger/BENCH file")
    ap.add_argument("new", nargs="?", help="candidate ledger/BENCH file")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative regression threshold (default 0.10)")
    ap.add_argument("--config", action="append", dest="configs",
                    help="limit to these config names (repeatable)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the gate against the seeded fixture "
                    "pair (make perf-gate)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.old or not args.new:
        ap.print_usage(sys.stderr)
        return 2
    try:
        old_doc, new_doc = _load(args.old), _load(args.new)
    except (OSError, ValueError) as exc:
        print(f"perfdiff: {exc}", file=sys.stderr)
        return 2
    regressions, comparisons = compare(
        old_doc, new_doc, threshold=args.threshold, configs=args.configs
    )
    _report(regressions, comparisons)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
