"""perfdiff: compare two perf-ledger points and gate on regression.

    python tools/perfdiff.py OLD.json NEW.json [--threshold 0.10]
    python tools/perfdiff.py --selftest          # make perf-gate

Inputs are perf-ledger documents (tools/perfledger.py schema) or any
BENCH_ALL-shaped ``{"results": [...]}`` file; for each ``config``
present in both, the LATEST entry on each side is compared with a
noise-aware relative threshold:

- direction comes from the unit: throughput units (sigs/sec, ops/sec,
  tx/sec...) regress DOWN, latency units (ms, s, ns_per_op) regress
  UP;
- the default threshold (10%) sits above the run-to-run noise the
  bench history shows (repeat trials of the same config vary ~3-5% on
  this stack: bench.py takes best-of-3 precisely because single runs
  wobble) and well below any change worth a human's attention — the
  measured regressions that mattered were 3-5x, not 1.1x;
- values <= 0 on either side are skipped (a 0 means "the device was
  down", which the availability entries record separately — gating on
  it would page on every tunnel outage instead of every code change).

Exit status: 0 clean, 1 when any compared config regressed past the
threshold, 2 on usage errors.  ``--selftest`` (what ``make perf-gate``
runs, standalone and in tier-1 via tests/test_health.py) proves the
gate's calibration against the committed fixture pair in
tests/data/perf_gate/: a seeded 20% regression MUST fail and seeded
noise-level (3%) deltas MUST pass — so the gate cannot silently decay
into always-green or always-red.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

DEFAULT_THRESHOLD = 0.10

#: units where SMALLER is better; everything else is throughput-like
LOWER_BETTER_UNITS = frozenset({"ms", "s", "seconds", "ns_per_op"})

FIXTURE_DIR = os.path.join(REPO, "tests", "data", "perf_gate")


def _latest_by_config(doc: dict) -> dict[str, dict]:
    """config -> last entry, from a ledger or BENCH_ALL-shaped doc."""
    rows = doc.get("entries")
    if rows is None:
        rows = doc.get("results", [])
    out: dict[str, dict] = {}
    for row in rows:
        cfg = row.get("config") or row.get("metric")
        if cfg is None or row.get("value") is None:
            continue
        out[cfg] = row  # later entries win: the ledger is append-order
    return out


def compare(
    old_doc: dict, new_doc: dict,
    threshold: float = DEFAULT_THRESHOLD,
    configs: list[str] | None = None,
) -> tuple[list[dict], list[dict]]:
    """Returns (regressions, comparisons): every config compared, and
    the subset whose delta crossed the threshold in the bad
    direction."""
    old = _latest_by_config(old_doc)
    new = _latest_by_config(new_doc)
    names = configs or sorted(set(old) & set(new))
    comparisons: list[dict] = []
    regressions: list[dict] = []
    for cfg in names:
        o, n = old.get(cfg), new.get(cfg)
        if o is None or n is None:
            continue
        try:
            ov, nv = float(o["value"]), float(n["value"])
        except (TypeError, ValueError):
            continue
        if ov <= 0 or nv <= 0:
            continue  # availability zeros, not perf points
        unit = n.get("unit") or o.get("unit") or ""
        lower_better = unit in LOWER_BETTER_UNITS
        # delta > 0 always means WORSE, whichever way the unit points
        delta = (nv - ov) / ov if lower_better else (ov - nv) / ov
        row = {
            "config": cfg, "unit": unit, "old": ov, "new": nv,
            "delta": round(delta, 4), "threshold": threshold,
            "regressed": delta > threshold,
        }
        comparisons.append(row)
        if row["regressed"]:
            regressions.append(row)
    return regressions, comparisons


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


#: the configs the stage-attribution rows explain: a regressed
#: ``height_latency_p95_<suffix>`` looks for sibling
#: ``height_stage_p95_<stage>_<suffix>`` rows (utils/critpath.py
#: taxonomy, appended by the same fleet smoke)
_LATENCY_PREFIX = "height_latency_p95_"
_STAGE_PREFIX = "height_stage_p95_"


def explain_stages(
    old_doc: dict, new_doc: dict, config: str
) -> list[dict]:
    """Attribute a ``height_latency_p95_*`` delta to its stage rows:
    for each critpath stage present on both sides, the absolute delta
    and its share of the latency regression — sorted worst first.
    Empty when ``config`` isn't a height-latency row or no stage rows
    exist (older ledgers), so callers can print-if-any."""
    if not config.startswith(_LATENCY_PREFIX):
        return []
    suffix = config[len(_LATENCY_PREFIX):]
    from cometbft_tpu.utils.critpath import STAGES

    old = _latest_by_config(old_doc)
    new = _latest_by_config(new_doc)
    try:
        lat_delta = float(new[config]["value"]) - float(
            old[config]["value"]
        )
    except (KeyError, TypeError, ValueError):
        lat_delta = 0.0
    out: list[dict] = []
    for stage in STAGES:
        cfg = f"{_STAGE_PREFIX}{stage}_{suffix}"
        o, n = old.get(cfg), new.get(cfg)
        if o is None or n is None:
            continue
        try:
            ov, nv = float(o["value"]), float(n["value"])
        except (TypeError, ValueError):
            continue
        delta = nv - ov
        out.append(
            {
                "stage": stage, "old": ov, "new": nv,
                "delta_ms": round(delta, 3),
                "share": (
                    round(delta / lat_delta, 4) if lat_delta else None
                ),
            }
        )
    out.sort(key=lambda r: -r["delta_ms"])
    return out


def _report(
    regressions: list[dict],
    comparisons: list[dict],
    old_doc: dict | None = None,
    new_doc: dict | None = None,
) -> None:
    for row in comparisons:
        mark = "REGRESSION" if row["regressed"] else "ok"
        print(
            f"perfdiff: {row['config']}: {row['old']:g} -> "
            f"{row['new']:g} {row['unit']} "
            f"({row['delta'] * 100:+.1f}% worse, threshold "
            f"{row['threshold'] * 100:.0f}%) {mark}",
            file=sys.stderr if row["regressed"] else sys.stdout,
        )
        if (
            row["regressed"]
            and old_doc is not None
            and new_doc is not None
        ):
            stages = explain_stages(old_doc, new_doc, row["config"])
            for s in stages:
                if s["delta_ms"] <= 0:
                    continue
                share = (
                    f" ({s['share'] * 100:.0f}% of the regression)"
                    if s["share"] is not None else ""
                )
                print(
                    f"perfdiff:   explained by {s['stage']}: "
                    f"{s['old']:g} -> {s['new']:g} ms "
                    f"(+{s['delta_ms']:g}ms){share}",
                    file=sys.stderr,
                )
    if not comparisons:
        print("perfdiff: no comparable configs", file=sys.stderr)


def selftest() -> int:
    """Prove the gate's calibration on the committed fixture pair:
    the seeded 20% regression must trip it, the seeded 3% noise must
    not.  This is what ``make perf-gate`` runs — deterministic (no
    live measurement), so it can gate ``make test``."""
    baseline = _load(os.path.join(FIXTURE_DIR, "baseline.json"))
    regressed = _load(os.path.join(FIXTURE_DIR, "regressed.json"))
    noise = _load(os.path.join(FIXTURE_DIR, "noise.json"))
    failures: list[str] = []
    regs, comps = compare(baseline, regressed)
    if not comps:
        failures.append("fixture pair produced no comparisons")
    # stage-attribution rows are seeded so ONE stage owns the latency
    # regression — the others hold steady by design, so the
    # every-config-must-trip check applies to the non-stage rows
    missed = [
        c["config"] for c in comps
        if not c["regressed"]
        and not c["config"].startswith(_STAGE_PREFIX)
    ]
    if missed:
        failures.append(
            f"seeded 20% regression NOT detected for: {missed}"
        )
    # the explanation path: the regressed latency row must be
    # attributable, and the seeded slow stage must rank first
    lat_cfg = "height_latency_p95_4node"
    if lat_cfg not in {r["config"] for r in regs}:
        failures.append(f"seeded {lat_cfg} regression not detected")
    stages = explain_stages(baseline, regressed, lat_cfg)
    if not stages:
        failures.append("stage rows produced no regression explanation")
    elif stages[0]["stage"] != "store_save":
        failures.append(
            "seeded store_save slowdown not named dominant "
            f"(got {stages[0]['stage']})"
        )
    regs_noise, comps_noise = compare(baseline, noise)
    if not comps_noise:
        failures.append("noise fixture produced no comparisons")
    if regs_noise:
        failures.append(
            "noise-level deltas tripped the gate: "
            f"{[r['config'] for r in regs_noise]}"
        )
    if failures:
        for f in failures:
            print(f"perf-gate selftest FAILED: {f}", file=sys.stderr)
        return 1
    print(
        f"perf-gate: ok — seeded 20% regression detected on "
        f"{len(comps)} config(s), {len(comps_noise)} noise-level "
        "delta(s) passed, store_save named dominant stage"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", nargs="?", help="baseline ledger/BENCH file")
    ap.add_argument("new", nargs="?", help="candidate ledger/BENCH file")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative regression threshold (default 0.10)")
    ap.add_argument("--config", action="append", dest="configs",
                    help="limit to these config names (repeatable)")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the gate against the seeded fixture "
                    "pair (make perf-gate)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.old or not args.new:
        ap.print_usage(sys.stderr)
        return 2
    try:
        old_doc, new_doc = _load(args.old), _load(args.new)
    except (OSError, ValueError) as exc:
        print(f"perfdiff: {exc}", file=sys.stderr)
        return 2
    regressions, comparisons = compare(
        old_doc, new_doc, threshold=args.threshold, configs=args.configs
    )
    _report(regressions, comparisons, old_doc, new_doc)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
