#!/bin/sh
# Nightly fuzz job (reference analogs: .github/workflows/fuzz-nightly.yml
# + test/fuzz/oss-fuzz-build.sh). Run from cron/CI:
#
#     tools/fuzz_nightly.sh [seconds-per-target]
#
# Behavior matches the reference's nightly contract:
#  - every target soaks for a fixed budget on the checked-in corpus
#  - coverage-growing inputs are ADDED to tests/data/fuzz_corpus/
#    (commit them: the corpus is an artifact that only grows)
#  - any crash leaves a reproducer in tests/data/fuzz_crashes/<target>/
#    and the job exits nonzero so CI pages — each reproducer must
#    become a regression test before being cleared
#  - a JSON summary is appended to docs/data/fuzz_nightly.jsonl so
#    exec-rate and corpus-size trends are inspectable over time
set -u
cd "$(dirname "$0")/.." || exit 1
BUDGET="${1:-600}"
TS=$(date -u +%Y-%m-%dT%H:%M:%SZ)
OUT=$(python tools/fuzz.py --time "$BUDGET" 2>&1)
RC=$?
echo "$OUT"
CORPUS=$(find tests/data/fuzz_corpus -type f | wc -l | tr -d ' ')
# count only NEW (untracked) reproducers: checked-in crash files are
# regression-test fixtures from already-fixed bugs
CRASHES=$(git ls-files --others --exclude-standard tests/data/fuzz_crashes 2>/dev/null | wc -l | tr -d ' ')
mkdir -p docs/data
printf '{"ts": "%s", "budget_s": %s, "rc": %s, "corpus_files": %s, "crash_files": %s}\n' \
    "$TS" "$BUDGET" "$RC" "$CORPUS" "$CRASHES" >> docs/data/fuzz_nightly.jsonl
exit "$RC"
