"""Macro throughput baseline: loadtime vs a 4-validator localnet.

Reference comparison point: the QA report's saturation at 400 tx/s of
1 KB txs with c=1 on a 200-node DigitalOcean testnet
(docs/references/qa/CometBFT-QA-v1.md:137).  This harness runs the
same shape scaled to one machine: `testnet` CLI homes, four real node
subprocesses over TCP, the loadtime Loader at a fixed rate, then the
loadtime reporter over node0's block store for latency percentiles and
block-interval stats.

    python tools/bench_loadtime.py [--rate 200] [--duration 60]

Merges a "loadtime_localnet" entry into BENCH_ALL.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_PORT = 28100
N_NODES = 4


def _rpc_port(i: int) -> int:
    return BASE_PORT + 2 * i + 1


def _height(port: int) -> int:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/status", timeout=3
    ) as resp:
        return int(
            json.load(resp)["result"]["sync_info"]["latest_block_height"]
        )


def _node_env() -> dict:
    env = dict(
        os.environ,
        PYTHONPATH=REPO,
        JAX_PLATFORMS="cpu",
        CMT_TPU_DISABLE_DEVICE_VERIFY="1",
    )
    # a wedged device tunnel can hang `import jax` while the device
    # plugin is importable — the localnet is CPU-only, scrub it
    from cometbft_tpu.utils.device_env import scrub_plugin_env

    scrub_plugin_env(env)
    return env


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rate", type=int, default=200, help="tx/s target")
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--size", type=int, default=1024, help="tx bytes")
    ap.add_argument("--connections", type=int, default=1)
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_ALL.json"))
    ap.add_argument(
        "--config-name", default="loadtime_localnet",
        help="BENCH_ALL.json entry to write (e.g. "
        "loadtime_localnet_saturation for the knee probe)",
    )
    args = ap.parse_args()

    env = _node_env()
    root = tempfile.mkdtemp(prefix="cmt-loadnet-")
    subprocess.run(
        [
            sys.executable, "-m", "cometbft_tpu", "testnet",
            "--v", str(N_NODES), "--o", root,
            "--chain-id", "load-chain",
            "--starting-port", str(BASE_PORT),
        ],
        env=env, check=True, capture_output=True, cwd=REPO,
    )
    procs = []
    for i in range(N_NODES):
        log = open(os.path.join(root, f"node{i}.log"), "ab", buffering=0)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "cometbft_tpu",
                    "--home", os.path.join(root, f"node{i}"), "start",
                ],
                env=env, stdout=subprocess.DEVNULL, stderr=log, cwd=REPO,
            )
        )
    try:
        deadline = time.monotonic() + 120
        while True:
            try:
                if all(_height(_rpc_port(i)) >= 3 for i in range(N_NODES)):
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise RuntimeError("localnet failed to reach height 3")
            time.sleep(1.0)
        print("localnet up; loading...", file=sys.stderr)

        from cometbft_tpu.loadtime import Loader, block_interval_stats

        loader = Loader(
            endpoints=[
                f"http://127.0.0.1:{_rpc_port(i)}" for i in range(N_NODES)
            ],
            rate=args.rate,
            size=args.size,
            connections=args.connections,
        )
        t0 = time.time()
        summary = loader.run(args.duration)
        load_wall = time.time() - t0
        print(f"load summary: {summary}", file=sys.stderr)
        time.sleep(5)  # let the tail commit
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    from cometbft_tpu.config import Config
    from cometbft_tpu.loadtime import report_from_home
    from cometbft_tpu.store import BlockStore
    from cometbft_tpu.utils.db import open_db

    home0 = os.path.join(root, "node0")
    reports = report_from_home(home0)
    cfg = Config.load(home0)
    db = open_db("blockstore", cfg.base.db_backend, cfg.db_dir)
    try:
        stats = block_interval_stats(BlockStore(db), last_n=200)
    finally:
        db.close()
    rep = reports[0].as_dict() if reports else {}
    committed = rep.get("count", 0)
    entry = {
        "config": args.config_name,
        "value": round(committed / load_wall, 1),
        "unit": "tx/sec committed",
        "offered_rate": args.rate,
        "tx_bytes": args.size,
        "connections": args.connections,
        "duration_s": round(load_wall, 1),
        "nodes": N_NODES,
        "latency_s": {
            k: round(rep[k], 3)
            for k in ("min_s", "avg_s", "p50_s", "p95_s", "max_s")
            if k in rep
        },
        "blocks_per_min": stats.get("blocks_per_min"),
        "mean_block_interval_s": stats.get("mean_interval_s"),
        "reference_baseline": (
            "400 tx/s saturation, <=4 s latency, 20-40 blocks/min "
            "(200-node DO testnet, CometBFT-QA-v1.md:137)"
        ),
        "hardware": "single host, 1 CPU core, 4 subprocess validators",
    }
    print(json.dumps(entry, indent=1))
    from bench_all import merge_results

    merge_results(args.out, [entry])
    print(f"merged into {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
