"""VPU arithmetic microbenchmark — decides the field-core number system.

Measures sustained element-op throughput for the candidate limb
arithmetics on the live device:
  - int32 multiply (current field core)
  - fp32 multiply-add (candidate radix-2^8 float core)
  - int32 add / shift (carry machinery)
  - emulated int64 multiply, for scale

Method: the tunneled axon backend's block_until_ready does NOT block,
and a result fetch pays a ~70 ms link round trip — so each flavor is
timed at two iteration counts (K and 4K) with a host fetch of a scalar
reduction, and the throughput comes from the DIFFERENCE, cancelling
dispatch + RTT + fetch.  Ops are dependent (loop-carried) so XLA cannot
collapse the chain.
"""

from __future__ import annotations

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"device: {dev}")
    shape = (8, 128, 512)
    numel = int(np.prod(shape))

    def timed(fn, x, trials=3):
        fn(x)  # compile
        best = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            np.asarray(fn(x))  # host fetch = true sync
            best = min(best, time.perf_counter() - t0)
        return best

    def bench(name, dtype, body, k=1 << 14):
        x = jnp.asarray(
            np.random.randint(1, 200, size=shape), dtype=dtype
        )

        def make(iters):
            @jax.jit
            def run(x):
                v = jax.lax.fori_loop(0, iters, lambda _, v: body(v), x)
                return v.reshape(-1)[:8]  # tiny fetch

            return run

        t1 = timed(make(k), x)
        t4 = timed(make(4 * k), x)
        dt = max(t4 - t1, 1e-9)
        rate = 3 * k * numel / dt
        print(
            f"{name:24s} {rate / 1e12:8.3f} Tops/s   "
            f"(K={t1 * 1e3:.1f} ms, 4K={t4 * 1e3:.1f} ms)"
        )
        return rate

    bench("int32 mul", jnp.int32, lambda v: (v * v) & 0x7FF)
    bench("int32 add", jnp.int32, lambda v: (v + 3) ^ 1)
    bench("int32 mul+add+mask", jnp.int32, lambda v: ((v * v + v) & 0x7FF))
    bench("int32 shift", jnp.int32, lambda v: ((v >> 2) ^ v) | 1)
    bench(
        "fp32 fma+clamp",
        jnp.float32,
        lambda v: jnp.minimum(v * v + v, 199.0),
    )
    bench(
        "fp32 carry step",
        jnp.float32,
        lambda v: jnp.minimum(v - 256.0 * jnp.floor(v * (1.0 / 256.0)), 199.0),
    )
    bench("uint32 mul (emu64 half)", jnp.uint32, lambda v: (v * v) & 0x7FF)


if __name__ == "__main__":
    main()
