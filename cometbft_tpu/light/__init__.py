"""Light plane — header verification without full blocks (reference:
light/)."""

from cometbft_tpu.light.client import (
    Client,
    ErrLightClientAttack,
    LightClientError,
    SEQUENTIAL,
    SKIPPING,
    TrustOptions,
)
from cometbft_tpu.light.provider import (
    LightBlockNotFoundError,
    NodeProvider,
    Provider,
    ProviderError,
)
from cometbft_tpu.light.serve import (
    HeaderRangeCache,
    LightHeaderServer,
    LightServeError,
)
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    VerificationError,
    verify,
    verify_adjacent,
    verify_non_adjacent,
)

__all__ = [
    "Client",
    "DEFAULT_TRUST_LEVEL",
    "ErrLightClientAttack",
    "HeaderRangeCache",
    "LightBlockNotFoundError",
    "LightClientError",
    "LightHeaderServer",
    "LightServeError",
    "LightStore",
    "NodeProvider",
    "Provider",
    "ProviderError",
    "SEQUENTIAL",
    "SKIPPING",
    "TrustOptions",
    "VerificationError",
    "verify",
    "verify_adjacent",
    "verify_non_adjacent",
]
