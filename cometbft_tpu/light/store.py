"""Trusted light-block store (reference: light/store/db/db.go)."""

from __future__ import annotations

import threading

from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.utils.db import DB
from cometbft_tpu.utils import sync as cmtsync

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + height.to_bytes(8, "big")


class LightStore:
    """(light/store/store.go Store iface, db implementation)"""

    def __init__(self, db: DB):
        self.db = db
        self._mtx = cmtsync.Mutex()

    def save(self, lb: LightBlock) -> None:
        with self._mtx:
            self.db.set(_key(lb.height), lb.encode())

    def get(self, height: int) -> LightBlock | None:
        raw = self.db.get(_key(height))
        return LightBlock.decode(bytes(raw)) if raw is not None else None

    def latest(self) -> LightBlock | None:
        """(db.go LastLightBlockHeight) — one reverse-range step."""
        with self._mtx:
            for _, raw in self.db.reverse_iterator(
                _PREFIX, _key(1 << 62)
            ):
                return LightBlock.decode(bytes(raw))
        return None

    def first(self) -> LightBlock | None:
        with self._mtx:
            for _, raw in self.db.prefix_iterator(_PREFIX):
                return LightBlock.decode(bytes(raw))
        return None

    def light_block_before(self, height: int) -> LightBlock | None:
        """Largest stored height strictly below ``height`` — one
        reverse-range step (db.go LightBlockBefore)."""
        with self._mtx:
            for _, raw in self.db.reverse_iterator(_PREFIX, _key(height)):
                return LightBlock.decode(bytes(raw))
        return None

    def delete(self, height: int) -> None:
        with self._mtx:
            self.db.delete(_key(height))

    def prune(self, keep: int) -> int:
        """Drop oldest blocks beyond ``keep`` (db.go Prune)."""
        with self._mtx:
            keys = [k for k, _ in self.db.prefix_iterator(_PREFIX)]
            excess = len(keys) - keep
            for k in keys[: max(excess, 0)]:
                self.db.delete(k)
            return max(excess, 0)

    def size(self) -> int:
        with self._mtx:
            return sum(1 for _ in self.db.prefix_iterator(_PREFIX))


__all__ = ["LightStore"]
