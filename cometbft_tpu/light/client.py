"""Light client (reference: light/client.go:133).

Verifies headers against a trusted root using sequential or skipping
(bisection) verification, cross-checks every newly verified header
against witness providers (fork detection, light/detector.go), and
persists trusted blocks.  The 10k-header verification benchmark
(BASELINE.json) exercises this plane's batch-verify calls.
"""

from __future__ import annotations

import threading
from cometbft_tpu.utils import sync as cmtsync
from dataclasses import dataclass
from fractions import Fraction

from cometbft_tpu.light.provider import Provider
from cometbft_tpu.light.store import LightStore
from cometbft_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    ErrNewValSetCantBeTrusted,
    VerificationError,
    verify as _verify,
    verify_adjacent,
)
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.time import now_ns

SEQUENTIAL = "sequential"   # client.go:44
SKIPPING = "skipping"       # client.go:50

DEFAULT_PRUNING_SIZE = 1000  # client.go:60
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * 10**9


class LightClientError(Exception):
    pass


class ErrLightClientAttack(LightClientError):
    """(light/errors.go ErrLightClientAttack) — divergence between the
    primary and a witness was detected and evidence submitted."""


class NoWitnessesError(LightClientError):
    pass


@dataclass(frozen=True)
class TrustOptions:
    """(light/client.go:77 TrustOptions) — the subjective root of trust."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise LightClientError("trusting period must be positive")
        if self.height <= 0:
            raise LightClientError("trust height must be positive")
        if len(self.hash) != 32:
            raise LightClientError("trust hash must be 32 bytes")


class Client:
    """(light/client.go:133 Client)"""

    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions | None,
        primary: Provider,
        witnesses: list[Provider],
        trusted_store: LightStore,
        verification_mode: str = SKIPPING,
        trust_level: Fraction = DEFAULT_TRUST_LEVEL,
        trust_period_ns: int = 7 * 24 * 3600 * 1_000_000_000,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        logger: Logger | None = None,
    ):
        if trust_options is not None:
            trust_options.validate()
        self.chain_id = chain_id
        self.trust_options = trust_options
        # the trusting period outlives the root of trust: resume mode
        # (trust_options=None, NewClientFromTrustedStore) still expires
        # stored headers against it
        self.trust_period_ns = (
            trust_options.period_ns if trust_options is not None
            else trust_period_ns
        )
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = trusted_store
        self.mode = verification_mode
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.pruning_size = pruning_size
        self.logger = logger or default_logger().with_fields(module="light")
        self._mtx = cmtsync.Mutex()
        self._initialize()

    # -- initialization (client.go:265 initializeWithTrustOptions) -------

    def _initialize(self) -> None:
        existing = self.store.latest()
        if existing is not None:
            return  # already have a trust root (client.go checkTrustedHeaderUsingOptions simplified: keep store)
        if self.trust_options is None:
            # NewClientFromTrustedStore semantics (light/client.go:233,
            # cmd light.go:189 "continue from latest state"): without a
            # root of trust there is nothing subjective to anchor to
            raise LightClientError(
                "trusted store is empty and no trust options given "
                "(supply --trusted-height/--trusted-hash on first run)"
            )
        lb = self.primary.light_block(self.trust_options.height)
        lb.validate_basic(self.chain_id)
        if lb.hash() != self.trust_options.hash:
            raise LightClientError(
                f"primary's header hash {lb.hash().hex()[:12]} != "
                f"trust hash {self.trust_options.hash.hex()[:12]}"
            )
        # +2/3 of ITS validator set signed it
        from cometbft_tpu.light.verifier import _verify_self_commit

        _verify_self_commit(lb, self.chain_id)
        self._compare_with_witnesses(lb)
        self.store.save(lb)

    # -- public API -------------------------------------------------------

    def trusted_light_block(self, height: int) -> LightBlock | None:
        return self.store.get(height)

    def latest_trusted(self) -> LightBlock | None:
        return self.store.latest()

    def update(self, now: int | None = None) -> LightBlock | None:
        """Verify the primary's latest header (client.go:486 Update)."""
        latest = self.primary.light_block(0)
        trusted = self.store.latest()
        if trusted is not None and latest.height <= trusted.height:
            return None
        return self.verify_light_block_at_height(latest.height, now)

    def verify_light_block_at_height(
        self, height: int, now: int | None = None
    ) -> LightBlock:
        """(client.go:473 VerifyLightBlockAtHeight)"""
        if height <= 0:
            raise LightClientError("height must be positive")
        now = now_ns() if now is None else now
        with self._mtx:
            existing = self.store.get(height)
            if existing is not None:
                return existing
            lb = self.primary.light_block(height)
            lb.validate_basic(self.chain_id)
            if lb.height != height:
                raise LightClientError(
                    f"primary returned height {lb.height}, wanted {height}"
                )
            self._verify_light_block(lb, now)
            return lb

    def verify_header(self, header, now: int | None = None) -> LightBlock:
        """Verify a caller-supplied header by fetching its light block
        (client.go:520 VerifyHeader)."""
        lb = self.verify_light_block_at_height(header.height, now)
        if lb.hash() != header.hash():
            raise LightClientError(
                "header differs from the verified header at that height"
            )
        return lb

    # -- verification strategies -----------------------------------------

    def _verify_light_block(self, new: LightBlock, now: int) -> None:
        trusted = self.store.light_block_before(new.height)
        if trusted is None:
            # target below our first trusted block: backwards verification
            first = self.store.first()
            if first is None:
                raise LightClientError("store has no trust root")
            self._verify_backwards(first, new)
            self._finalize_verified(new)
            return
        if self.mode == SEQUENTIAL:
            self._verify_sequential(trusted, new, now)
        else:
            self._verify_skipping(trusted, new, now)
        self._finalize_verified(new)

    def _finalize_verified(self, new: LightBlock) -> None:
        self._compare_with_witnesses(new)
        self.store.save(new)
        if self.store.size() > self.pruning_size:
            self.store.prune(self.pruning_size)

    def _verify_sequential(
        self, trusted: LightBlock, new: LightBlock, now: int
    ) -> None:
        """(client.go:612 verifySequential) — fetch and verify every
        intermediate header."""
        current = trusted
        for h in range(trusted.height + 1, new.height + 1):
            nxt = (
                new if h == new.height else self.primary.light_block(h)
            )
            nxt.validate_basic(self.chain_id)
            verify_adjacent(
                current, nxt, self.chain_id,
                self.trust_period_ns, now,
                self.max_clock_drift_ns,
            )
            if h != new.height:
                self.store.save(nxt)
            current = nxt

    def _verify_skipping(
        self, trusted: LightBlock, new: LightBlock, now: int
    ) -> None:
        """(client.go:705 verifySkipping) — bisection: try the jump; on
        insufficient trusted power, verify the midpoint first."""
        verified = [trusted]
        pending = [new]
        depth_guard = 0
        while pending:
            depth_guard += 1
            if depth_guard > 10_000:
                raise LightClientError("bisection did not converge")
            base = verified[-1]
            target = pending[-1]
            try:
                _verify(
                    base, target, self.chain_id,
                    self.trust_period_ns, now,
                    self.trust_level, self.max_clock_drift_ns,
                )
                verified.append(target)
                pending.pop()
                if target.height != new.height:
                    self.store.save(target)
            except ErrNewValSetCantBeTrusted:
                pivot = (base.height + target.height) // 2
                if pivot in (base.height, target.height):
                    raise LightClientError(
                        "cannot bisect further — chain not verifiable "
                        "within the trusting period"
                    ) from None
                mid = self.primary.light_block(pivot)
                mid.validate_basic(self.chain_id)
                pending.append(mid)

    def _verify_backwards(self, trusted: LightBlock, new: LightBlock) -> None:
        """(client.go:790 backwards) — hash-link each header back from
        the trusted block to the target."""
        current = trusted
        for h in range(trusted.height - 1, new.height - 1, -1):
            prev = new if h == new.height else self.primary.light_block(h)
            prev.validate_basic(self.chain_id)
            if current.header.last_block_id.hash != prev.hash():
                raise VerificationError(
                    f"header {h} does not hash-link to header {h + 1}"
                )
            current = prev

    # -- fork detection (light/detector.go) ------------------------------

    def _make_attack_evidence(
        self, conflicting: LightBlock, common: LightBlock, trusted: LightBlock
    ) -> LightClientAttackEvidence:
        """(detector.go newLightClientAttackEvidence) — ``common`` is
        the latest trusted block both sides agree on; ``trusted`` is the
        header we believe at the conflicting height.  Total power and
        the byzantine list come from the common-height validator set and
        the actual conflicting signatures, so full nodes' checks pass."""
        from dataclasses import replace

        ev = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=common.height,
            total_voting_power=common.validator_set.total_voting_power(),
            timestamp_ns=common.time_ns,
        )
        byz = ev.get_byzantine_validators(
            common.validator_set, trusted.signed_header
        )
        return replace(
            ev, byzantine_validators=tuple(v.address for v in byz)
        )

    def _compare_with_witnesses(self, lb: LightBlock) -> None:
        """(detector.go:33 detectDivergence) — any witness serving a
        different header at this height implies an attack on one side;
        we can't tell which, so build evidence against each side and
        report it to the other."""
        for witness in self.witnesses:
            try:
                w_lb = witness.light_block(lb.height)
            except Exception:  # noqa: BLE001 — witness down: skip
                continue
            if w_lb.hash() == lb.hash():
                continue
            common = self.store.light_block_before(lb.height)
            if common is None:
                self.logger.error(
                    "divergence detected but no trusted block below the "
                    "conflicting height — cannot build attack evidence",
                    height=lb.height,
                )
            else:
                # witness's block is the fraud → tell the primary
                ev_w = self._make_attack_evidence(w_lb, common, lb)
                # primary's block is the fraud → tell the witness
                ev_p = self._make_attack_evidence(lb, common, w_lb)
                for target, ev in ((self.primary, ev_w), (witness, ev_p)):
                    try:
                        target.report_evidence(ev)
                    except Exception:  # noqa: BLE001
                        pass
            raise ErrLightClientAttack(
                f"witness header {w_lb.hash().hex()[:12]} conflicts with "
                f"primary {lb.hash().hex()[:12]} at height {lb.height}"
            )


__all__ = [
    "Client",
    "ErrLightClientAttack",
    "LightClientError",
    "NoWitnessesError",
    "SEQUENTIAL",
    "SKIPPING",
    "TrustOptions",
]
