"""Light proxy — a local JSON-RPC endpoint that serves verified
answers (reference: light/proxy/proxy.go:27).

`Proxy` runs a JSONRPCServer whose routes go through a
:class:`~cometbft_tpu.light.rpc.VerifyingClient`, so anything an RPC
consumer reads from it (query results, blocks, commits, validator
sets) has been checked against the light client's verified header
chain.  This is the reference's flagship trust-minimized deployment:
point wallets/explorers at the proxy instead of a remote full node.
"""

from __future__ import annotations

from cometbft_tpu.light.client import LightClientError
from cometbft_tpu.light.rpc import VerifyingClient
from cometbft_tpu.rpc.jsonrpc import JSONRPCServer, RPCError
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.service import BaseService


class Proxy(BaseService):
    """(light/proxy/proxy.go Proxy)"""

    def __init__(
        self,
        client: VerifyingClient,
        host: str = "127.0.0.1",
        port: int = 0,
        logger: Logger | None = None,
    ):
        super().__init__(
            name="light-proxy",
            logger=logger
            or default_logger().with_fields(module="light-proxy"),
        )
        self.client = client
        self._server = JSONRPCServer(
            routes=self._routes(),
            host=host,
            port=port,
            logger=self.logger,
        )

    @property
    def port(self) -> int:
        return self._server.port

    def _wrap(self, fn):
        def route(**params):
            try:
                return fn(**params)
            except LightClientError as exc:
                raise RPCError(-32000, "light client verification failed",
                               str(exc)) from exc

        return route

    def _routes(self) -> dict:
        c = self.client
        return {
            "status": self._wrap(lambda **_: c.status()),
            "abci_query": self._wrap(c.abci_query),
            "block": self._wrap(c.block),
            "header": self._wrap(c.header),
            "commit": self._wrap(c.commit),
            "validators": self._wrap(c.validators),
            "light_trusted": self._wrap(self._trusted),
        }

    def _trusted(self, **_) -> dict:
        """Framework extra: the light client's current trusted head."""
        lb = self.client.light.latest_trusted()
        if lb is None:
            raise RPCError(-32603, "no trusted state yet")
        return {
            "height": str(lb.height),
            "hash": lb.signed_header.header.hash().hex(),
        }

    def on_start(self) -> None:
        self._server.start()
        self.logger.info("light proxy listening", port=self.port)

    def on_stop(self) -> None:
        self._server.stop()
