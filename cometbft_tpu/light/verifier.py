"""Light-client verification core (reference: light/verifier.go).

Two modes (verifier.go:129 Verify):
- adjacent (H → H+1): the new header's validator set must hash to the
  trusted header's next_validators_hash (verifier.go:91 VerifyAdjacent);
- non-adjacent (H → H+n): the *trusted* validator set must have signed
  the new commit with ≥ 1/3 of its power (skipping trust,
  verifier.go:30 VerifyNonAdjacent), then the new set verifies its own
  commit with +2/3.

Both commit checks ride the batch-verify plane (types/validation —
the TPU kernel seam, SURVEY.md §3.4).
"""

from __future__ import annotations

from fractions import Fraction

from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.types.validation import (
    verify_commit_light,
    verify_commit_light_trusting,
)
from cometbft_tpu.utils.time import now_ns

DEFAULT_TRUST_LEVEL = Fraction(1, 3)  # light/verifier.go:21


class VerificationError(Exception):
    pass


class ErrOldHeaderExpired(VerificationError):
    """Trusted header fell outside the trusting period."""


class ErrNewValSetCantBeTrusted(VerificationError):
    """Skipping verification failed: not enough trusted power signed.
    The client responds by bisecting (client.go verifySkipping)."""


class ErrInvalidHeader(VerificationError):
    pass


def _check_trusted_within_period(
    trusted: LightBlock, trusting_period_ns: int, now: int
) -> None:
    """(light/verifier.go:213 HeaderExpired check)"""
    expiration = trusted.time_ns + trusting_period_ns
    if now > expiration:
        raise ErrOldHeaderExpired(
            f"trusted header expired at {expiration} (now {now})"
        )


def _verify_new_header_and_vals(
    untrusted: LightBlock,
    trusted: LightBlock,
    chain_id: str,
    now: int,
    max_clock_drift_ns: int,
) -> None:
    """(light/verifier.go:147 verifyNewHeaderAndVals)"""
    untrusted.validate_basic(chain_id)
    if untrusted.height <= trusted.height:
        raise ErrInvalidHeader(
            f"new header height {untrusted.height} <= "
            f"trusted {trusted.height}"
        )
    if untrusted.time_ns <= trusted.time_ns:
        raise ErrInvalidHeader("new header time not after trusted header")
    if untrusted.time_ns >= now + max_clock_drift_ns:
        raise ErrInvalidHeader("new header is from the future")


def verify_adjacent(
    trusted: LightBlock,
    untrusted: LightBlock,
    chain_id: str,
    trusting_period_ns: int,
    now: int | None = None,
    max_clock_drift_ns: int = 10 * 10**9,
) -> None:
    """(light/verifier.go:91 VerifyAdjacent)"""
    if untrusted.height != trusted.height + 1:
        raise ErrInvalidHeader("headers must be adjacent in height")
    now = now_ns() if now is None else now
    _check_trusted_within_period(trusted, trusting_period_ns, now)
    _verify_new_header_and_vals(
        untrusted, trusted, chain_id, now, max_clock_drift_ns
    )
    if (
        untrusted.header.validators_hash
        != trusted.header.next_validators_hash
    ):
        raise ErrInvalidHeader(
            "new validator set hash does not match trusted "
            "next_validators_hash"
        )
    _verify_self_commit(untrusted, chain_id)


def verify_non_adjacent(
    trusted: LightBlock,
    untrusted: LightBlock,
    chain_id: str,
    trusting_period_ns: int,
    now: int | None = None,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    max_clock_drift_ns: int = 10 * 10**9,
) -> None:
    """(light/verifier.go:30 VerifyNonAdjacent)"""
    if untrusted.height == trusted.height + 1:
        return verify_adjacent(
            trusted, untrusted, chain_id, trusting_period_ns, now,
            max_clock_drift_ns,
        )
    now = now_ns() if now is None else now
    _check_trusted_within_period(trusted, trusting_period_ns, now)
    _verify_new_header_and_vals(
        untrusted, trusted, chain_id, now, max_clock_drift_ns
    )
    # ≥ trust_level of the OLD (trusted) set must have signed the new
    # commit; the untrusted block's own set resolves aggregate signers
    # that rotated in past the trusted set (types/validation._verify)
    try:
        verify_commit_light_trusting(
            chain_id,
            trusted.validator_set,
            untrusted.signed_header.commit,
            trust_level,
            signer_vals=untrusted.validator_set,
        )
    except Exception as exc:
        raise ErrNewValSetCantBeTrusted(str(exc)) from exc
    _verify_self_commit(untrusted, chain_id)


def verify(
    trusted: LightBlock,
    untrusted: LightBlock,
    chain_id: str,
    trusting_period_ns: int,
    now: int | None = None,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    max_clock_drift_ns: int = 10 * 10**9,
) -> None:
    """(light/verifier.go:129 Verify) — dispatch on adjacency."""
    if untrusted.height != trusted.height + 1:
        verify_non_adjacent(
            trusted, untrusted, chain_id, trusting_period_ns, now,
            trust_level, max_clock_drift_ns,
        )
    else:
        verify_adjacent(
            trusted, untrusted, chain_id, trusting_period_ns, now,
            max_clock_drift_ns,
        )


def _verify_self_commit(lb: LightBlock, chain_id: str) -> None:
    """+2/3 of the new set signed its own header (batch path)."""
    sh = lb.signed_header
    block_id = BlockID(
        hash=sh.header.hash(),
        part_set_header=sh.commit.block_id.part_set_header,
    )
    try:
        verify_commit_light(
            chain_id,
            lb.validator_set,
            block_id,
            sh.height,
            sh.commit,
        )
    except Exception as exc:
        raise ErrInvalidHeader(f"invalid commit: {exc}") from exc


__all__ = [
    "DEFAULT_TRUST_LEVEL",
    "ErrInvalidHeader",
    "ErrNewValSetCantBeTrusted",
    "ErrOldHeaderExpired",
    "VerificationError",
    "verify",
    "verify_adjacent",
    "verify_non_adjacent",
]
