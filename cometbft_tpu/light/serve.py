"""Batched light-client serving plane — the millions-of-users workload
(ROADMAP item 1; "Practical Light Clients for Committee-Based
Blockchains", arXiv:2410.03347, defines the traffic shape: huge
numbers of light clients concurrently syncing header ranges).

A full node serving light clients re-verifies each requested header's
commit before vouching for it.  Naively that is one synchronous batch
launch per header per client — 10k clients syncing the same 100-header
range would pay 1M launches for 100 headers' worth of distinct work.
This module removes both multiplicities:

- **Cross-client coalescing.**  ``LightHeaderServer.sync_range``
  verifies commits through ``types/validation`` inside a
  ``verify_queue.submission_lane("light_client")`` context, so the
  signatures of CONCURRENT requests ride the VerifyQueue's
  ``light_client`` lane and its micro-batcher
  (``CMT_TPU_LIGHT_BATCH`` / ``CMT_TPU_LIGHT_WAIT_MS``) coalesces
  them into single DispatchLadder launches — strictly preempted by
  consensus and prefetch, so serving load can never delay a live
  vote.  BLS aggregate commits (types/block.py) verify with one
  pairing-product through the same validation seam.

- **Repeat-sync elimination.**  Verified headers land in the
  :class:`HeaderRangeCache` — a bounded LRU over heights, trusting-
  period aware — and the speculative-result cache keeps the
  underlying signature verdicts, so a fully cached repeat sync
  performs ZERO launches (pinned by tests/test_light_serve.py).

Observability: the ``light_*`` family (metrics/LightMetrics —
cache hit/miss/eviction, serve latency/volume) next to the queue's
``crypto_verify_queue_*{priority="light_client"}`` series; env knobs
validated fail-loudly via the shared ring-size contract.
"""

from __future__ import annotations

import time
from collections import OrderedDict

from cometbft_tpu.metrics import light_metrics as _light_metrics
from cometbft_tpu.crypto import verify_queue as _vq
from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.validation import verify_commit_light
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import FLIGHT
from cometbft_tpu.utils.flight import ring_size_from_env as _int_env
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.time import now_ns
from cometbft_tpu.utils.trace import TRACER as _tracer

DEFAULT_CACHE_CAP = 8192
#: default trusting period: 7 days (light/client.py default)
DEFAULT_TRUST_PERIOD_NS = 7 * 24 * 3600 * 1_000_000_000
#: largest height span one sync request may ask for — a bound, not a
#: knob: an unbounded range is a griefing vector (one request pinning
#: the serving thread for the whole chain)
MAX_RANGE = 1024


def header_cache_capacity_from_env() -> int:
    """Verified-header cache capacity in headers (>= 16; smaller
    caches thrash on a single client's range and the repeat-sync
    elimination silently degrades to all-miss)."""
    return _int_env("CMT_TPU_LIGHT_CACHE", DEFAULT_CACHE_CAP, 16)


class LightServeError(Exception):
    pass


@cmtsync.guarded
class HeaderRangeCache:
    """Bounded LRU of height -> (verified header hash, header time).

    An entry means "this exact header at this height carried a valid
    +2/3 commit of its own validator set" — a pure fact, EXCEPT that
    light clients only accept headers inside their trusting period,
    so entries expire ``trust_period_ns`` after the header's own
    timestamp: serving a stale hit would vouch for a header the
    client's own rules reject (trust-period-aware eviction, counted
    under reason="expired"; capacity pressure evicts oldest-used
    first under reason="lru").  Reads and writes are mutex-guarded —
    the serving plane consults this from many RPC threads at once,
    hammered under CMT_TPU_RACE=1 in tests/test_light_serve.py."""

    _GUARDED_BY = {"_map": "_mtx"}

    def __init__(
        self,
        capacity: int | None = None,
        trust_period_ns: int = DEFAULT_TRUST_PERIOD_NS,
        clock=now_ns,
    ) -> None:
        self.capacity = (
            capacity if capacity is not None
            else header_cache_capacity_from_env()
        )
        if self.capacity < 1:
            raise ValueError("header cache capacity must be >= 1")
        if trust_period_ns <= 0:
            raise ValueError("trusting period must be positive")
        self.trust_period_ns = trust_period_ns
        self._clock = clock
        self._mtx = cmtsync.Mutex()
        self._map: OrderedDict[int, tuple[bytes, int]] = OrderedDict()

    def get(self, height: int, now: int | None = None) -> bytes | None:
        """The verified header hash at ``height``, or None on miss or
        trust-period expiry (the expired entry is evicted)."""
        lm = _light_metrics()
        now = self._clock() if now is None else now
        expired = False
        with self._mtx:
            ent = self._map.get(height)
            if ent is not None:
                if now > ent[1] + self.trust_period_ns:
                    del self._map[height]
                    expired = True
                    ent = None
                else:
                    self._map.move_to_end(height)
        if expired:
            lm.header_cache_evictions.labels(reason="expired").inc()
            lm.header_cache_entries.set(len(self))
        if ent is None:
            lm.header_cache.labels(result="miss").inc()
            return None
        lm.header_cache.labels(result="hit").inc()
        return ent[0]

    def put(
        self, height: int, header_hash: bytes, header_time_ns: int
    ) -> None:
        lm = _light_metrics()
        evicted = 0
        with self._mtx:
            self._map[height] = (bytes(header_hash), header_time_ns)
            self._map.move_to_end(height)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
                evicted += 1
            size = len(self._map)
        if evicted:
            lm.header_cache_evictions.labels(reason="lru").inc(evicted)
        lm.header_cache_entries.set(size)

    def __len__(self) -> int:
        with self._mtx:
            return len(self._map)

    def clear(self) -> None:
        with self._mtx:
            self._map.clear()
        _light_metrics().header_cache_entries.set(0)

    def stats(self) -> dict:
        with self._mtx:
            return {
                "entries": len(self._map),
                "capacity": self.capacity,
                "trust_period_ns": self.trust_period_ns,
            }


class LightHeaderServer:
    """The serving plane (module docstring): verified header ranges
    from a light-block :class:`~cometbft_tpu.light.provider.Provider`
    (a node's own stores via ``NodeProvider`` in production, a
    fixture provider in benches), with the header cache in front and
    the ``light_client`` verify-queue lane underneath."""

    def __init__(
        self,
        chain_id: str,
        provider,
        cache: HeaderRangeCache | None = None,
        trust_period_ns: int = DEFAULT_TRUST_PERIOD_NS,
        logger: Logger | None = None,
    ) -> None:
        self.chain_id = chain_id
        self.provider = provider
        self.cache = cache or HeaderRangeCache(
            trust_period_ns=trust_period_ns
        )
        self.logger = logger or default_logger().with_fields(
            module="light.serve"
        )

    def sync_range(
        self,
        from_height: int,
        to_height: int,
        now: int | None = None,
    ) -> dict:
        """Serve heights [from_height, to_height]: each header's own
        +2/3 commit is verified (``verify_commit_light`` — aggregate
        or batch by what the commit carries) unless the cache already
        vouches for that height, and every freshly verified header is
        cached.  Raises LightServeError on bad ranges or missing
        blocks; crypto failures propagate as the validation errors
        they are."""
        if from_height < 1 or to_height < from_height:
            raise LightServeError(
                f"bad range [{from_height}, {to_height}]"
            )
        if to_height - from_height + 1 > MAX_RANGE:
            raise LightServeError(
                f"range wider than {MAX_RANGE} headers"
            )
        lm = _light_metrics()
        t0 = time.perf_counter()
        now = now_ns() if now is None else now
        headers: list[dict] = []
        hits = 0
        try:
            with _tracer.span(
                "light/serve_range", cat="light",
                from_height=from_height, to_height=to_height,
            ) as sp:
                # the lane context makes validation route signature
                # batches through the queue's light_client
                # micro-batcher (no queue installed -> exact sync
                # behavior).  Two phases: collect every uncached
                # height's light block first and PRIME the lane with
                # ALL their signatures as one submission — a lone
                # client cold-syncing a wide range fills the batch
                # from its own work and pays the accumulation
                # deadline once, not once per header — then verify
                # each height (phase-1 verdicts answer from the
                # speculative cache).
                with _vq.submission_lane(_vq.PRIORITY_LIGHT):
                    entries: list[tuple] = []
                    for h in range(from_height, to_height + 1):
                        cached = self.cache.get(h, now)
                        if cached is not None:
                            hits += 1
                            entries.append((h, cached, None))
                        else:
                            entries.append(
                                (h, None, self._fetch_height(h))
                            )
                    self._prime_lane(
                        [lb for _, _, lb in entries if lb is not None]
                    )
                    for h, cached_hash, lb in entries:
                        if lb is None:
                            headers.append(
                                {"height": h,
                                 "hash": cached_hash.hex(),
                                 "cached": True}
                            )
                        else:
                            headers.append(self._verify_block(lb))
                sp.set(headers=len(headers), cache_hits=hits)
        except Exception:
            lm.serve_requests.labels(result="error").inc()
            raise
        wall = time.perf_counter() - t0
        lm.serve_requests.labels(result="ok").inc()
        lm.serve_headers.inc(len(headers))
        lm.serve_seconds.observe(wall)
        return {
            "chain_id": self.chain_id,
            "from_height": from_height,
            "to_height": to_height,
            "headers": headers,
            "cache_hits": hits,
            "elapsed_ms": round(wall * 1e3, 3),
        }

    def _fetch_height(self, height: int):
        lb = self.provider.light_block(height)
        lb.validate_basic(self.chain_id)
        if lb.height != height:
            raise LightServeError(
                f"provider returned height {lb.height}, wanted {height}"
            )
        return lb

    def _prime_lane(self, lbs: list) -> None:
        """Phase 1: every uncached height's per-signature work rides
        the light lane as ONE submission (``light_verify_or_fallback``
        waits for the coalesced launch; verdicts land in the
        speculative cache, so phase 2's ``verify_commit_light`` is
        cache hits).  Well-formedness is NOT judged here — a
        malformed commit just primes less and phase 2 reports the
        precise error.  Aggregate-covered signatures are skipped:
        their proof is the commit-level pairing, cached under its own
        key at first verification.  Primes every commit-flag
        signature where phase 2's early-break stops at +2/3 — a
        bounded overshoot that buys the single coalesced launch."""
        if not lbs or not _vq.speculation_active():
            return
        items = []
        for lb in lbs:
            commit = lb.commit
            vals = lb.validator_set
            if commit.size() != len(vals):
                continue
            for i, cs in enumerate(commit.signatures):
                if not cs.is_commit() or commit.is_aggregated(i):
                    continue
                val = vals.get_by_index(i)
                if val is None or val.address != cs.validator_address:
                    break  # malformed: phase 2 raises the real error
                items.append((
                    val.pub_key,
                    commit.vote_sign_bytes(self.chain_id, i),
                    cs.signature,
                ))
        if items:
            _vq.light_verify_or_fallback(items)

    def _verify_block(self, lb) -> dict:
        height = lb.height
        sh = lb.signed_header
        block_id = BlockID(
            hash=sh.header.hash(),
            part_set_header=sh.commit.block_id.part_set_header,
        )
        verify_commit_light(
            self.chain_id, lb.validator_set, block_id, sh.height,
            sh.commit,
        )
        self.cache.put(height, lb.hash(), lb.time_ns)
        FLIGHT.record(
            "light/header_verified", height=height,
            sigs=sh.commit.size(),
            aggregate=bool(sh.commit.agg_signature),
        )
        return {
            "height": height, "hash": lb.hash().hex(), "cached": False,
        }


__all__ = [
    "DEFAULT_CACHE_CAP",
    "DEFAULT_TRUST_PERIOD_NS",
    "HeaderRangeCache",
    "LightHeaderServer",
    "LightServeError",
    "MAX_RANGE",
    "header_cache_capacity_from_env",
]
