"""Proof-verifying RPC client — trust-minimized node access
(reference: light/rpc/client.go:41).

Wraps an untrusted full-node RPC client with a light.Client so every
answer is checked against a header the light client has verified
through its trust chain:

- ``abci_query`` demands a merkle proof and verifies it against the
  verified app_hash of the NEXT header (header H+1 commits the app
  state after block H, like the reference's proof verification at
  resp.Height+1, light/rpc/client.go:179).
- ``block``/``header``/``commit`` check the primary's data against the
  verified header hash for that height.
- ``validators`` checks the set's hash against the verified header's
  validators_hash.
- ``status`` passes through (explicitly unverified, as upstream).

The proof format is the framework's native simple-merkle k/v op
(crypto/merkle.py KV_PROOF_OP_TYPE); unknown op types are rejected
rather than trusted.
"""

from __future__ import annotations

import base64
import time

from cometbft_tpu.crypto import merkle
from cometbft_tpu.light.client import Client as LightClient
from cometbft_tpu.light.client import LightClientError
from cometbft_tpu.light.provider import LightBlockNotFoundError


class ProofError(LightClientError):
    """The node's answer failed verification against a trusted header."""


def _b64(data) -> bytes:
    return base64.b64decode(data) if data else b""


class VerifyingClient:
    """(light/rpc/client.go Client) — same call surface as
    rpc.client.HTTPClient for the verified subset of routes."""

    def __init__(self, node, light_client: LightClient,
                 head_wait_s: float = 10.0):
        self.node = node          # untrusted full-node RPC client
        self.light = light_client
        #: how long to wait for header H+1 when a query answers at the
        #: chain head H (the committing header lands one block later)
        self.head_wait_s = head_wait_s

    def _verified_block_at(self, height: int):
        deadline = time.monotonic() + self.head_wait_s
        while True:
            try:
                return self.light.verify_light_block_at_height(height)
            except LightBlockNotFoundError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    # -- verified queries ----------------------------------------------

    def abci_query(self, path=None, data=None, height=None, **_):
        """ABCIQuery with mandatory proof verification
        (light/rpc/client.go:150 ABCIQueryWithOptions)."""
        resp = self.node.abci_query(
            path=path, data=data, height=height, prove=True
        )["response"]
        code = int(resp.get("code", 0))
        if code != 0:
            return {"response": resp}  # app-level error: nothing to verify
        key = _b64(resp.get("key"))
        value = _b64(resp.get("value"))
        qheight = int(resp.get("height", "0"))
        if qheight <= 0:
            raise ProofError("query response carries no height")
        ops = (resp.get("proofOps") or {}).get("ops") or []
        if not ops:
            # Absence (or a proof-less answer): no absence proofs in
            # the native format (the reference gets them from ics23
            # apps) — surface that honestly instead of pretending the
            # nil answer was verified.  An empty-string VALUE is fine:
            # it arrives with an inclusion proof for kv_leaf(key, "").
            raise ProofError(
                "node returned no proof (key absent or app "
                "non-provable), which this proof format cannot verify"
            )
        # header H+1 commits the app state after block H
        lb = self._verified_block_at(qheight + 1)
        root = lb.signed_header.header.app_hash
        if len(ops) != 1:
            raise ProofError(f"expected one proof op, got {len(ops)}")
        op = ops[0]
        if op.get("type") != merkle.KV_PROOF_OP_TYPE:
            raise ProofError(f"unknown proof op type {op.get('type')!r}")
        if _b64(op.get("key")) != key:
            raise ProofError("proof op key mismatch")
        try:
            proof = merkle.proof_from_bytes(_b64(op.get("data")))
        except ValueError as exc:
            raise ProofError(f"malformed proof: {exc}") from exc
        if not proof.verify(root, merkle.kv_leaf(key, value)):
            raise ProofError("merkle proof does not match app_hash")
        return {"response": resp, "verified_height": qheight}

    def block(self, height=None):
        """Verify the returned block BODY, not just the node's claimed
        block_id: the header json must re-hash to the trusted header
        hash, and the txs must re-hash to that header's data_hash —
        otherwise a primary could pair an honest hash with fabricated
        content."""
        resp = self.node.block(height=height)
        h = int(resp["block"]["header"]["height"])
        lb = self.light.verify_light_block_at_height(h)
        want = lb.signed_header.header.hash()
        if bytes.fromhex(resp["block_id"]["hash"]) != want:
            raise ProofError(f"block id mismatch at {h}")
        from cometbft_tpu.light.provider import _header_from_json

        hdr = _header_from_json(resp["block"]["header"])
        if hdr.hash() != want:
            raise ProofError(f"block header content mismatch at {h}")
        txs = [
            base64.b64decode(t)
            for t in (resp["block"].get("data") or {}).get("txs") or []
        ]
        from cometbft_tpu.types.block import Data

        if Data(txs=tuple(txs)).hash() != hdr.data_hash:
            raise ProofError(f"block txs do not match data_hash at {h}")
        return resp

    def header(self, height=None):
        resp = self.node.header(height=height)
        from cometbft_tpu.light.provider import _header_from_json

        hdr = _header_from_json(resp["header"])
        lb = self.light.verify_light_block_at_height(hdr.height)
        if hdr.hash() != lb.signed_header.header.hash():
            raise ProofError(f"header mismatch at {hdr.height}")
        return resp

    def commit(self, height=None):
        """Verify the header AND the commit signatures against the
        verified validator set — the commit half of a signed header is
        otherwise attacker-controlled data."""
        resp = self.node.commit(height=height)
        h = int(resp["signed_header"]["header"]["height"])
        lb = self.light.verify_light_block_at_height(h)
        from cometbft_tpu.light.provider import (
            _commit_from_json,
            _header_from_json,
        )

        hdr = _header_from_json(resp["signed_header"]["header"])
        if hdr.hash() != lb.signed_header.header.hash():
            raise ProofError(f"commit header mismatch at {h}")
        commit = _commit_from_json(resp["signed_header"]["commit"])
        if commit.height != h or commit.block_id.hash != hdr.hash():
            raise ProofError(f"commit is not for header at {h}")
        from cometbft_tpu.types import verify_commit_light
        from cometbft_tpu.types.validation import CommitError

        try:
            verify_commit_light(
                self.light.chain_id,
                lb.validator_set,
                commit.block_id,
                h,
                commit,
            )
        except CommitError as exc:
            raise ProofError(f"commit signatures invalid at {h}: {exc}")
        return resp

    def validators(self, height=None, **kw):
        resp = self.node.validators(height=height, **kw)
        h = int(resp.get("block_height", height or 0))
        lb = self.light.verify_light_block_at_height(h)
        from cometbft_tpu.light.provider import _validator_set_from_json

        vals = _validator_set_from_json(resp["validators"])
        if vals.hash() != lb.signed_header.header.validators_hash:
            raise ProofError(f"validator set hash mismatch at {h}")
        return resp

    # -- unverified passthrough ----------------------------------------

    def status(self):
        return self.node.status()
