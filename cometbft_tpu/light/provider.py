"""Light-block providers (reference: light/provider/provider.go).

A provider serves light blocks for a chain.  ``NodeProvider`` reads a
local node's stores directly (the in-process analog of the reference's
http provider — the RPC-backed provider plugs in the same interface
once the RPC plane lands)."""

from __future__ import annotations

from cometbft_tpu.types.light_block import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class LightBlockNotFoundError(ProviderError):
    """(provider/errors.go ErrLightBlockNotFound)"""


class Provider:
    """(light/provider/provider.go:14 Provider)"""

    def chain_id(self) -> str:
        raise NotImplementedError

    def light_block(self, height: int) -> LightBlock:
        """Height 0 means latest."""
        raise NotImplementedError

    def report_evidence(self, ev) -> None:
        raise NotImplementedError


class NodeProvider(Provider):
    """Serves light blocks straight from a node's block/state stores."""

    def __init__(self, chain_id: str, block_store, state_store,
                 evidence_pool=None):
        self._chain_id = chain_id
        self.block_store = block_store
        self.state_store = state_store
        self.evidence_pool = evidence_pool

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        if meta is None:
            raise LightBlockNotFoundError(f"no block at height {height}")
        # the canonical commit FOR height H is stored with block H+1;
        # for the chain head fall back to the seen commit
        commit = self.block_store.load_block_commit(height)
        if commit is None:
            commit = self.block_store.load_seen_commit(height)
        if commit is None:
            raise LightBlockNotFoundError(f"no commit for height {height}")
        vals = self.state_store.load_validators(height)
        return LightBlock(
            signed_header=SignedHeader(header=meta.header, commit=commit),
            validator_set=vals,
        )

    def report_evidence(self, ev) -> None:
        if self.evidence_pool is not None:
            self.evidence_pool.add_evidence(ev)

    def consensus_params(self, height: int):
        return self.state_store.load_consensus_params(height)


# -- RPC-backed provider (reference: light/provider/http) ---------------

def _ns_from_rfc3339(s: str) -> int:
    from datetime import datetime, timezone

    base, _, frac = s.rstrip("Z").partition(".")
    dt = datetime.strptime(base, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=timezone.utc
    )
    ns = int(dt.timestamp()) * 1_000_000_000
    if frac:
        ns += int(frac.ljust(9, "0")[:9])
    return ns


def _header_from_json(d: dict):
    from cometbft_tpu.types.block import BlockID, Header, PartSetHeader

    def hx(key):
        return bytes.fromhex(d.get(key) or "")

    lbi = d.get("last_block_id") or {}
    parts = lbi.get("parts") or {}
    return Header(
        chain_id=d["chain_id"],
        height=int(d["height"]),
        time_ns=_ns_from_rfc3339(d["time"]),
        last_block_id=BlockID(
            hash=bytes.fromhex(lbi.get("hash") or ""),
            part_set_header=PartSetHeader(
                total=int(parts.get("total") or 0),
                hash=bytes.fromhex(parts.get("hash") or ""),
            ),
        ),
        last_commit_hash=hx("last_commit_hash"),
        data_hash=hx("data_hash"),
        validators_hash=hx("validators_hash"),
        next_validators_hash=hx("next_validators_hash"),
        consensus_hash=hx("consensus_hash"),
        app_hash=hx("app_hash"),
        last_results_hash=hx("last_results_hash"),
        evidence_hash=hx("evidence_hash"),
        proposer_address=hx("proposer_address"),
        version_block=int(d.get("version", {}).get("block", 0)),
        version_app=int(d.get("version", {}).get("app", 0)),
    )


def _commit_from_json(d: dict):
    import base64

    from cometbft_tpu.types.block import (
        BlockID,
        Commit,
        CommitSig,
        PartSetHeader,
    )

    bid = d.get("block_id") or {}
    parts = bid.get("parts") or {}
    sigs = []
    for s in d.get("signatures") or []:
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=bytes.fromhex(
                    s.get("validator_address") or ""
                ),
                timestamp_ns=(
                    _ns_from_rfc3339(s["timestamp"])
                    if s.get("timestamp")
                    else 0
                ),
                signature=(
                    base64.b64decode(s["signature"])
                    if s.get("signature")
                    else b""
                ),
            )
        )
    return Commit(
        height=int(d["height"]),
        round=int(d["round"]),
        block_id=BlockID(
            hash=bytes.fromhex(bid.get("hash") or ""),
            part_set_header=PartSetHeader(
                total=int(parts.get("total") or 0),
                hash=bytes.fromhex(parts.get("hash") or ""),
            ),
        ),
        signatures=tuple(sigs),
        agg_signature=(
            base64.b64decode(d["agg_signature"])
            if d.get("agg_signature") else b""
        ),
    )


def _pub_key_from_json(d: dict):
    """Inverse of rpc/serialize.validator_json's pub_key tagging —
    BLS validator sets must survive the HTTP round trip (the light
    serving plane serves aggregate commits whose signers are BLS)."""
    import base64

    raw = base64.b64decode(d["value"])
    # absent type = legacy ed25519-only emitters; an UNKNOWN explicit
    # tag fails loudly — guessing ed25519 would surface later as a
    # misleading wrong-signature error instead of a key-type error
    tag = d.get("type", "tendermint/PubKeyEd25519")
    if tag == "tendermint/PubKeyBls12381":
        from cometbft_tpu.crypto.bls12381 import Bls12381PubKey

        return Bls12381PubKey(raw)
    if tag == "tendermint/PubKeySecp256k1":
        from cometbft_tpu.crypto.secp256k1 import Secp256k1PubKey

        return Secp256k1PubKey(raw)
    if tag != "tendermint/PubKeyEd25519":
        raise ValueError(f"unknown pub key JSON type {tag!r}")
    from cometbft_tpu.crypto.ed25519 import Ed25519PubKey

    return Ed25519PubKey(raw)


def _validator_set_from_json(vals: list):
    from cometbft_tpu.types.validator import Validator, ValidatorSet

    return ValidatorSet(
        [
            Validator(
                pub_key=_pub_key_from_json(v["pub_key"]),
                voting_power=int(v["voting_power"]),
                proposer_priority=int(v.get("proposer_priority", 0)),
            )
            for v in vals
        ]
    )


class HTTPProvider(Provider):
    """Light blocks over the JSON-RPC API (light/provider/http/http.go).

    Uses /commit and /validators; evidence goes to /broadcast_evidence;
    consensus params (verified by the caller against the header's
    consensus_hash) via /consensus_params."""

    def __init__(self, chain_id: str, address: str, timeout: float = 10.0):
        from cometbft_tpu.rpc.client import HTTPClient

        self._chain_id = chain_id
        base = address if "://" in address else f"http://{address}"
        self.client = HTTPClient(base, timeout=timeout)

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        kwargs = {} if height == 0 else {"height": height}
        try:
            commit_resp = self.client.commit(**kwargs)
            h = int(commit_resp["signed_header"]["header"]["height"])
            vals_resp = self.client.validators(height=h, per_page=100)
            vals = list(vals_resp["validators"])
            while len(vals) < int(vals_resp["total"]):
                more = self.client.validators(
                    height=h, per_page=100,
                    page=len(vals) // 100 + 1,
                )
                if not more["validators"]:
                    break
                vals.extend(more["validators"])
        except Exception as exc:  # noqa: BLE001 — node down / pruned height
            raise LightBlockNotFoundError(str(exc)) from exc
        return LightBlock(
            signed_header=SignedHeader(
                header=_header_from_json(
                    commit_resp["signed_header"]["header"]
                ),
                commit=_commit_from_json(
                    commit_resp["signed_header"]["commit"]
                ),
            ),
            validator_set=_validator_set_from_json(vals),
        )

    def report_evidence(self, ev) -> None:
        from cometbft_tpu.types import codec

        self.client.broadcast_evidence(
            evidence=codec.encode_evidence(ev).hex()
        )

    def consensus_params(self, height: int):
        from cometbft_tpu.types.params import ConsensusParams

        resp = self.client.consensus_params(height=height)
        return ConsensusParams.from_json_dict(resp["consensus_params"])


__all__ = [
    "HTTPProvider",
    "LightBlockNotFoundError",
    "NodeProvider",
    "Provider",
    "ProviderError",
]
