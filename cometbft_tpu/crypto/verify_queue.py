"""Pipelined verify-ahead: the async double-buffered verify queue.

BENCH_r02 measured 171 ms sync latency per device launch while the
pipelined bench mode showed 1.6x over sync, and the PR 7 utilization
plane shows the device idle between commits — the gap to the BASELINE
north star is launch overlap, not kernel speed (ROADMAP item 2).  This
module closes it at the ``TpuBatchVerifier`` seam: a process-wide
``VerifyQueue`` accepts verification requests from any caller
(consensus ``VoteSet.add_vote``, blocksync replay prefetch, and the
mempool CheckTx ingest lane — ROADMAP item 4's admission plane,
``CListMempool._verify_tx_signature``), coalesces
them into device-sized batches, and keeps **two buffers in flight**:

- a *collector* thread drains pending requests, computes the SHA-512
  cache-key prehash, and runs the verifier's host phase
  (``TpuBatchVerifier.plan()`` — dispatch routing, key-table lookup,
  input packing) for buffer N+1 **while** buffer N's device launch is
  in flight on the
- *launcher* thread, which executes prepared batches through the
  failover dispatch ladder (``crypto/dispatch.py``: keyed_mesh ->
  keyed -> generic_mesh -> generic -> host -> python; the verifier's
  ``execute()`` walks the plan's admissible tiers top-down, demoting
  a faulting tier and continuing one rung down — a tier demoted
  between plan time and launch time is skipped mid-walk) and
  delivers completion futures back to callers.

Mixed-priority scheduling: consensus-vote requests **preempt**
blocksync/prefetch batches still in the queue — the collector always
prepares pending consensus work first, and the launcher always picks a
prepared consensus batch over a prepared prefetch batch.

**Speculative-result cache.**  Every verification that PASSES lands
in a bounded LRU keyed by SHA-512(pubkey || signature || message) —
the message is the vote's sign bytes, so the key is the
(vote-sign-bytes digest, pubkey) pair the speculative plane needs,
deliberately bound to the *signature* as well: a cached verdict must
never answer for a different signature over the same bytes.  Only
POSITIVE verdicts are memoized (SpeculativeCache docstring): a
transient device fault mis-verifying a valid signature must cost one
rejection and heal on retry, never poison the cache.
``VoteSet.add_vote`` submits signatures on receipt, so
``verify_commit`` at finalize time is mostly a cache hit instead of a
10k-sig synchronous launch (types/validation.py consults
``cached_result``); blocksync submits the next
``CMT_TPU_VERIFY_PREFETCH`` blocks' commit signatures while the
current block applies.  Fall-back is STRICT: on a cache miss, queue
unavailability, a failed future, or a wait timeout, callers run the
exact synchronous verify they ran before this module existed — the
queue is an accelerator, never a correctness dependency.  And a
consensus-priority caller never WAITS behind in-flight work: when the
queue is busy, ``verify_or_fallback`` verifies inline (pre-queue
latency) and still feeds the cache.

Env knobs (validated fail-loudly, same contract as the ring vars in
utils/flight.py):

- ``CMT_TPU_VERIFY_PREFETCH`` — blocksync prefetch depth in blocks
  (default 8; 0 disables prefetch).
- ``CMT_TPU_SPEC_CACHE`` — speculative-result cache capacity in
  entries (default 65536, >= 1024; ~152 B/entry, so the default is
  ~10 MB and covers a fully speculated 10k-validator commit 6x over).
- ``CMT_TPU_VERIFY_QUEUE=0`` — node assembly skips the queue entirely
  (every caller takes the synchronous path, exactly as before).
- ``CMT_TPU_CHECKTX_BATCH`` — ingest-lane accumulation target in
  signatures (default 256, >= 1): concurrent mempool CheckTx
  submissions coalesce until this many are pending, then release as
  ONE buffer (one DispatchLadder launch).
- ``CMT_TPU_CHECKTX_WAIT_MS`` — ingest accumulation deadline in
  milliseconds (default 5, >= 0): the oldest pending CheckTx
  signature never waits longer than this for the batch to fill.
- ``CMT_TPU_LIGHT_BATCH`` / ``CMT_TPU_LIGHT_WAIT_MS`` — the same two
  bounds for the ``light_client`` serving lane (defaults 1024 / 10):
  concurrent light-client header syncs coalesce into single ladder
  launches through the SAME ``_LaneBatcher`` machinery the ingest
  lane uses.

The ``ingest`` lane (ROADMAP item 4, the mempool admission plane) is
the lowest priority: every other lane strictly preempts it at buffer
granularity, and its requests additionally accumulate behind the
micro-batcher gate above — mempool admission soaks up device idle
time between commits without ever delaying a vote.  The
``light_client`` lane (ISSUE 13, the header serving plane) sits
between prefetch and ingest with its own micro-batcher: external
clients syncing header ranges must never delay live votes or the
node's own replay, but they outrank admission.

Observability: ``crypto_verify_queue_*`` metrics (CryptoMetrics),
``verify_queue/prepare`` + ``verify_queue/launch`` spans (the overlap
is visible as prepare-of-N+1 nesting inside launch-of-N wall time —
docs/observability.md "reading an overlap trace"), and the launcher
feeds ``crypto_host_device_overlap_ratio`` with the share of each
launch wall covered by concurrent host prep.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict, deque

from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.metrics import health_metrics as _health_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import ring_size_from_env as _int_env
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.service import BaseService
from cometbft_tpu.utils.trace import TRACER as _tracer

#: request priorities (metric label values); consensus preempts
#: prefetch, both preempt the ``light_client`` serving lane, and all
#: three strictly preempt the mempool ``ingest`` lane at both the
#: collector and the launcher (buffer granularity — a prepared
#: consensus buffer launches before a parked light/ingest buffer).
#: light_client sits between prefetch and ingest: header serving for
#: external clients must never delay live votes or the node's own
#: block replay, but it IS revenue traffic — admission soaks up
#: whatever idle remains below it.
PRIORITY_CONSENSUS = "consensus"
PRIORITY_PREFETCH = "prefetch"
PRIORITY_LIGHT = "light_client"
PRIORITY_INGEST = "ingest"
_PRIORITIES = (
    PRIORITY_CONSENSUS, PRIORITY_PREFETCH, PRIORITY_LIGHT,
    PRIORITY_INGEST,
)

DEFAULT_PREFETCH_DEPTH = 8
DEFAULT_SPEC_CACHE_CAP = 65536
#: ingest micro-batcher: accumulate concurrent CheckTx submissions
#: until this many signatures are pending (one DispatchLadder launch
#: instead of one per RPC thread) ...
DEFAULT_CHECKTX_BATCH = 256
#: ... or until the OLDEST pending ingest request has waited this many
#: milliseconds — the admission-latency bound a half-full batch pays
DEFAULT_CHECKTX_WAIT_MS = 5
#: light_client lane micro-batcher (same accumulate/deadline/release
#: machinery as ingest, via the shared _LaneBatcher): concurrent
#: header-verification requests coalesce until this many signatures
#: are pending ...
DEFAULT_LIGHT_BATCH = 1024
#: ... or the OLDEST pending light request has waited this long — a
#: looser bound than CheckTx (10 ms vs 5): header sync is bulk
#: traffic, and a wider window is what turns 10k concurrent clients'
#: 150-sig commits into full-device launches
DEFAULT_LIGHT_WAIT_MS = 10
#: largest coalesced batch — matches ops/ed25519_verify.MAX_LAUNCH's
#: default so one queue batch is one device launch
DEFAULT_MAX_BATCH = 8192
#: how long a caller waits on a future before the strict sync
#: fallback; generous because a pure-Python host tier can take seconds
#: per large prefetch batch ahead of a consensus request
DEFAULT_WAIT_S = 120.0


def prefetch_depth_from_env() -> int:
    """Blocksync verify-prefetch depth in blocks; 0 disables."""
    return _int_env("CMT_TPU_VERIFY_PREFETCH", DEFAULT_PREFETCH_DEPTH, 0)


def spec_cache_capacity_from_env() -> int:
    """Speculative-result cache capacity in entries (>= 1024: smaller
    caches evict a large commit mid-verify and the speculative plane
    silently degrades to all-miss)."""
    return _int_env("CMT_TPU_SPEC_CACHE", DEFAULT_SPEC_CACHE_CAP, 1024)


def checktx_batch_from_env() -> int:
    """Ingest-lane accumulation target in signatures (>= 1; 1 disables
    coalescing — every CheckTx submission releases immediately)."""
    return _int_env("CMT_TPU_CHECKTX_BATCH", DEFAULT_CHECKTX_BATCH, 1)


def checktx_wait_ms_from_env() -> int:
    """Ingest-lane accumulation deadline in milliseconds (>= 0; 0
    releases every pending ingest batch immediately, whatever its
    size)."""
    return _int_env("CMT_TPU_CHECKTX_WAIT_MS", DEFAULT_CHECKTX_WAIT_MS, 0)


def light_batch_from_env() -> int:
    """Light-client lane accumulation target in signatures (>= 1; 1
    disables coalescing)."""
    return _int_env("CMT_TPU_LIGHT_BATCH", DEFAULT_LIGHT_BATCH, 1)


def light_wait_ms_from_env() -> int:
    """Light-client lane accumulation deadline in milliseconds (>= 0;
    0 releases every pending light batch immediately)."""
    return _int_env("CMT_TPU_LIGHT_WAIT_MS", DEFAULT_LIGHT_WAIT_MS, 0)


class QueueUnavailable(RuntimeError):
    """The queue is stopped/draining; callers must verify
    synchronously."""


class VerifyFuture:
    """Completion handle for one submitted (pubkey, msg, sig) request.

    ``result()`` returns the verification bit or raises: the waiter
    treats ANY raise (failed launch, drain, timeout) as "queue
    unavailable" and falls back to synchronous verification."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: bool | None = None
        self._error: BaseException | None = None

    def _resolve(self, result: bool) -> None:
        if not self._event.is_set():
            self._result = result
            self._event.set()

    def _fail(self, exc: BaseException) -> None:
        # first writer wins: a drain-timeout _fail must not clobber a
        # verdict a slow launcher delivered concurrently (and vice
        # versa — the waiter's strict sync fallback covers the rest)
        if not self._event.is_set():
            self._error = exc
            self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = DEFAULT_WAIT_S) -> bool:
        if not self._event.wait(timeout):
            raise QueueUnavailable("verify future timed out")
        if self._error is not None:
            raise QueueUnavailable(
                f"verify batch failed: {self._error!r}"
            ) from self._error
        return bool(self._result)


def cache_key(pub: bytes, msg: bytes, sig: bytes) -> bytes:
    """SHA-512 over pubkey || signature || sign-bytes — the host
    prehash the collector runs for buffer N+1 while buffer N launches.
    Binding the signature (not just the (digest, pubkey) pair) is
    load-bearing: two distinct signatures over the same vote bytes
    must never share a cached verdict."""
    h = hashlib.sha512()
    h.update(pub)
    h.update(sig)
    h.update(msg)
    return h.digest()


@cmtsync.guarded
class SpeculativeCache:
    """Bounded LRU of cache_key -> True: PROOFS OF VALIDITY only.
    A positive verdict is a pure fact about the (pubkey, sign-bytes,
    signature) triple — height- and validator-set-independent, never
    stale — so capacity is the only eviction policy.  Negative
    verdicts are deliberately NEVER stored: a transient device fault
    mis-verifying one signature must cost one rejected attempt (the
    pre-queue behavior — the retry re-verifies fresh), not a
    permanently poisoned cache entry that rejects a valid commit
    forever.  Invalid signatures therefore re-verify on every consult,
    which is the attacker paying, not us."""

    _GUARDED_BY = {"_map": "_mtx"}

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = (
            capacity if capacity is not None
            else spec_cache_capacity_from_env()
        )
        self._mtx = cmtsync.Mutex()
        self._map: OrderedDict[bytes, bool] = OrderedDict()

    def lookup(self, key: bytes) -> bool | None:
        with self._mtx:
            if key not in self._map:
                return None
            self._map.move_to_end(key)
            return self._map[key]

    def store(self, key: bytes, ok: bool) -> None:
        if not ok:
            return  # negative verdicts are never memoized (class doc)
        with self._mtx:
            self._map[key] = True
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def __len__(self) -> int:
        with self._mtx:
            return len(self._map)


class _Request:
    __slots__ = ("pub_key", "msg", "sig", "future", "key", "t")

    def __init__(self, pub_key, msg: bytes, sig: bytes) -> None:
        self.pub_key = pub_key
        self.msg = msg
        self.sig = sig
        self.future = VerifyFuture()
        self.key: bytes | None = None  # prehash, set by the collector
        #: arrival time (monotonic) — the ingest micro-batcher's
        #: accumulation deadline is measured from the OLDEST pending
        #: request, so a half-full batch never waits past the bound
        self.t = time.monotonic()


class _LaneBatcher:
    """The accumulate/deadline/release gate an accumulating lane puts
    in front of the collector (PR 10's CheckTx micro-batcher,
    EXTRACTED so the ingest and light_client lanes share one
    implementation instead of two drifting copies): a pending lane
    releases when it reaches the ``batch_target`` size, when the
    OLDEST pending request has waited ``wait_s``, or on drain — never
    before, so concurrent submissions coalesce into one DispatchLadder
    launch instead of one launch per caller thread.  Stateless apart
    from its two bounds; all timing reads the requests' arrival
    stamps, so unit tests drive it with explicit clocks."""

    __slots__ = ("batch_target", "wait_s")

    def __init__(self, batch_target: int, wait_ms: int) -> None:
        self.batch_target = batch_target
        self.wait_s = wait_ms / 1000.0

    def ready(
        self, lane: deque, draining: bool, now: float | None = None
    ) -> bool:
        if not lane:
            return False
        if draining or len(lane) >= self.batch_target:
            return True
        now = time.monotonic() if now is None else now
        return now - lane[0].t >= self.wait_s

    def deadline_wait(
        self, lane: deque, now: float | None = None
    ) -> float | None:
        """Seconds until the oldest pending request's accumulation
        deadline (None when the lane is empty) — the collector sleeps
        no longer than the NEAREST deadline across all batched lanes,
        so the wait bounds stay real."""
        if not lane:
            return None
        now = time.monotonic() if now is None else now
        return max(0.001, self.wait_s - (now - lane[0].t))


class _Prepared:
    """One prepared buffer: requests grouped per key type with their
    host-phase artifacts, ready for the launcher."""

    __slots__ = ("priority", "reqs", "groups", "prep_seconds")

    def __init__(self, priority: str) -> None:
        self.priority = priority
        self.reqs: list[_Request] = []
        #: list of (reqs, verifier | None, plan | None); verifier None
        #: means per-signature host verification in the launcher
        self.groups: list[tuple] = []
        self.prep_seconds = 0.0


@cmtsync.guarded
class VerifyQueue(BaseService):
    """The double-buffered verify queue (module docstring).

    ``verifier_factory(pub_key)`` builds the per-batch verifier
    (default: crypto/batch.create_batch_verifier — the production
    dispatch ladder).  ``launch`` overrides the launch phase entirely
    (tests gate it to prove the overlap deterministically): a callable
    ``launch(items) -> list[bool]`` over ``(pub_key, msg, sig)``
    tuples.  ``use_cache=False`` disables the speculative cache
    (benches re-verify the same batch honestly)."""

    _GUARDED_BY = {
        "_pending": "_qmtx",
        "_prepared": "_qmtx",
        "_preparing_lane": "_qmtx",
        "_draining": "_qmtx",
        "_launch_active": "_qmtx",
        "_launch_t0": "_qmtx",
        "_overlap_accum": "_qmtx",
        "_prep_since": "_qmtx",
        "_overlap_seconds": "_qmtx",
        "_launch_wall_seconds": "_qmtx",
        "_stats": "_qmtx",
        "_last_overlap": "_qmtx",
    }

    def __init__(
        self,
        verifier_factory=None,
        launch=None,
        max_batch: int = DEFAULT_MAX_BATCH,
        spec_cache: SpeculativeCache | None = None,
        use_cache: bool = True,
        checktx_batch: int | None = None,
        checktx_wait_ms: int | None = None,
        light_batch: int | None = None,
        light_wait_ms: int | None = None,
        logger: Logger | None = None,
    ) -> None:
        super().__init__(
            name="verify-queue",
            logger=logger or default_logger().with_fields(
                module="crypto.verify_queue"
            ),
        )
        self._factory = verifier_factory
        self._launch = launch
        self._max_batch = max_batch
        #: per-lane micro-batcher gates (module docstring): pending
        #: ingest/light requests accumulate until the lane's size
        #: target is reached or its oldest request hits the wait
        #: deadline, then release as ONE buffer.  Lanes absent here
        #: (consensus, prefetch) release immediately.
        self._batchers: dict[str, _LaneBatcher] = {
            PRIORITY_INGEST: _LaneBatcher(
                checktx_batch if checktx_batch is not None
                else checktx_batch_from_env(),
                checktx_wait_ms if checktx_wait_ms is not None
                else checktx_wait_ms_from_env(),
            ),
            PRIORITY_LIGHT: _LaneBatcher(
                light_batch if light_batch is not None
                else light_batch_from_env(),
                light_wait_ms if light_wait_ms is not None
                else light_wait_ms_from_env(),
            ),
        }
        self.cache = (
            (spec_cache or SpeculativeCache()) if use_cache else None
        )
        self._qmtx = cmtsync.Mutex()
        self._collector_wake = threading.Event()
        self._launcher_wake = threading.Event()
        self._pending: dict[str, deque[_Request]] = {
            p: deque() for p in _PRIORITIES
        }
        #: prepared buffers awaiting launch, at most ONE per priority:
        #: with the one the launcher holds, that is the double buffer
        self._prepared: dict[str, deque[_Prepared]] = {
            p: deque() for p in _PRIORITIES
        }
        #: the lane being prepared, from the moment _next_pending pops
        #: a batch until the collector parks (or abandons) its
        #: prepared buffer; None when idle.  Lane-aware (not a bool)
        #: so busy() can ignore an INGEST buffer mid-prepare while
        #: still covering the consensus/prefetch prep window — without
        #: it busy() goes dark for the whole prep phase and a consensus
        #: vote parks behind the prefetch batch being prepared
        self._preparing_lane: str | None = None
        self._draining = False
        self._launch_active = 0
        self._launch_t0 = 0.0
        self._overlap_accum = 0.0
        #: start (or accounted-until watermark) of the prep currently
        #: running on the collector, None when idle — lets a launch
        #: that ends MID-prep credit the overlap accrued so far (a
        #: prep outliving the launch it overlapped must not count 0)
        self._prep_since: float | None = None
        self._overlap_seconds = 0.0
        self._launch_wall_seconds = 0.0
        self._last_overlap: float | None = None
        self._stats = {
            "submitted": {p: 0 for p in _PRIORITIES},
            "cache_resolved": 0,
            "prepared_batches": 0,
            "launched_batches": 0,
            "launched_sigs": 0,
            "failed_batches": 0,
        }
        self._collector_thread: threading.Thread | None = None
        self._launcher_thread: threading.Thread | None = None

    # -- submission ------------------------------------------------------

    def accepting(self) -> bool:
        with self._qmtx:
            draining = self._draining
        return self.is_running() and not draining

    def busy(self) -> bool:
        """True while work a consensus vote could get stuck behind is
        pending, prepared, preparing, or launching.  Latency-sensitive
        callers (a live consensus vote) use this to verify INLINE
        instead of parking — priority preemption reorders queued
        buffers but can never interrupt the launch already on the
        device.

        QUEUED ingest and light_client work (accumulating requests, a
        parked buffer, a buffer mid-prepare) is deliberately
        excluded: it is exactly what consensus preempts, so a mempool
        under sustained admission load — or a serving plane under 10k
        syncing light clients — must not push every live vote
        onto the inline path by itself.  Such a launch ALREADY ON
        THE DEVICE still counts — it cannot be interrupted, and
        waiting a full launch wall behind it is what this check
        exists to avoid; while admission keeps the device saturated,
        live votes therefore verify inline at pre-queue latency (the
        designed degradation — never a stall)."""
        with self._qmtx:
            return bool(
                self._launch_active
                or self._preparing_lane in (
                    PRIORITY_CONSENSUS, PRIORITY_PREFETCH
                )
                or any(
                    self._pending[p] or self._prepared[p]
                    for p in (PRIORITY_CONSENSUS, PRIORITY_PREFETCH)
                )
            )

    def submit_many(
        self, items, priority: str = PRIORITY_CONSENSUS
    ) -> list[VerifyFuture]:
        """Enqueue ``(pub_key, msg, sig)`` tuples; returns one future
        per item.  Raises QueueUnavailable when stopped/draining."""
        if priority not in _PRIORITIES:
            raise ValueError(f"unknown priority {priority!r}")
        reqs = [_Request(pk, bytes(m), bytes(s)) for pk, m, s in items]
        with self._qmtx:
            if self._draining or not self.is_running():
                raise QueueUnavailable("verify queue is not accepting")
            self._pending[priority].extend(reqs)
            self._stats["submitted"][priority] += len(reqs)
            depth = len(self._pending[priority])
        cm = _crypto_metrics()
        cm.verify_queue_submitted.labels(priority=priority).inc(len(reqs))
        cm.verify_queue_depth.labels(priority=priority).set(depth)
        self._collector_wake.set()
        return [r.future for r in reqs]

    def submit(self, pub_key, msg, sig,
               priority: str = PRIORITY_CONSENSUS) -> VerifyFuture:
        return self.submit_many([(pub_key, msg, sig)], priority)[0]

    # -- lifecycle -------------------------------------------------------

    def on_start(self) -> None:
        self._collector_thread = threading.Thread(
            target=self._collector, name="verify-queue-collect",
            daemon=True,
        )
        self._launcher_thread = threading.Thread(
            target=self._launcher, name="verify-queue-launch",
            daemon=True,
        )
        self._collector_thread.start()
        self._launcher_thread.start()

    def on_stop(self) -> None:
        """Drain: stop accepting, let the collector prepare what is
        already pending and the launcher finish every prepared buffer,
        then fail any leftovers so no caller blocks forever."""
        with self._qmtx:
            self._draining = True
        self._collector_wake.set()
        self._launcher_wake.set()
        for t in (self._collector_thread, self._launcher_thread):
            if t is not None:
                t.join(timeout=DEFAULT_WAIT_S)
        leftovers: list[_Request] = []
        with self._qmtx:
            for p in _PRIORITIES:
                leftovers.extend(self._pending[p])
                self._pending[p].clear()
                for prep in self._prepared[p]:
                    leftovers.extend(prep.reqs)
                self._prepared[p].clear()
        for r in leftovers:
            r.future._fail(QueueUnavailable("queue stopped"))
        if _installed() is self:
            install_queue(None)

    # -- the collector (host phase: buffer N+1) --------------------------

    def _batcher_deadline_wait(self) -> float:
        """How long the collector may sleep before the NEAREST pending
        accumulation deadline across the batched lanes expires (holds
        no lock — called from the collector's idle loop only)."""
        wait = 0.05
        now = time.monotonic()
        with self._qmtx:
            for p, gate in self._batchers.items():
                remaining = gate.deadline_wait(self._pending[p], now)
                if remaining is not None:
                    wait = min(wait, remaining)
        return max(0.001, wait)

    def _next_pending(self) -> tuple[list[_Request] | None, str | None]:
        """Pop the next batch worth of requests: consensus first, then
        prefetch, then light_client, then ingest (strict preemption),
        and only for a priority lane whose prepared slot is free (the
        double-buffer bound).  The batched lanes (ingest,
        light_client) additionally hold until their micro-batch
        accumulation gate opens (``_LaneBatcher.ready``).  Sets
        ``_preparing_lane`` under the same lock as the pop so busy() never
        misses the batch between dequeue and the prepared-slot
        append."""
        with self._qmtx:
            for p in _PRIORITIES:
                gate = self._batchers.get(p)
                if gate is not None and not gate.ready(
                    self._pending[p], self._draining
                ):
                    continue
                if self._pending[p] and not self._prepared[p]:
                    take = min(len(self._pending[p]), self._max_batch)
                    reqs = [
                        self._pending[p].popleft() for _ in range(take)
                    ]
                    self._preparing_lane = p
                    _crypto_metrics().verify_queue_depth.labels(
                        priority=p
                    ).set(len(self._pending[p]))
                    return reqs, p
        return None, None

    def _idle_done(self) -> bool:
        with self._qmtx:
            if not self._draining:
                return False
            return not any(self._pending.values())

    def _collector(self) -> None:
        while True:
            reqs, priority = self._next_pending()
            if reqs is None:
                if self._idle_done():
                    return
                # sleep no longer than the nearest accumulation
                # deadline across the batched lanes — the default
                # CheckTx wait bound (5 ms) is finer than the idle
                # poll interval
                self._collector_wake.wait(self._batcher_deadline_wait())
                self._collector_wake.clear()
                continue
            try:
                try:
                    prep = self._prepare(reqs, priority)
                except Exception as exc:  # noqa: BLE001 — fall back
                    self.logger.error(
                        "verify-queue prepare failed", err=repr(exc)
                    )
                    for r in reqs:
                        r.future._fail(exc)
                    continue
                if not prep.reqs:
                    continue  # every request was a cache hit
                with self._qmtx:
                    self._prepared[priority].append(prep)
                    self._stats["prepared_batches"] += 1
                    inflight = self._launch_active + sum(
                        len(d) for d in self._prepared.values()
                    )
                _crypto_metrics().verify_queue_inflight.set(inflight)
                self._launcher_wake.set()
            finally:
                # clear AFTER the prepared-slot append (or abandon):
                # between pop and here busy() sees _preparing_lane,
                # after the append it sees the prepared buffer — no
                # window
                with self._qmtx:
                    self._preparing_lane = None

    def _prepare(self, reqs: list[_Request], priority: str) -> _Prepared:
        """Host phase for one buffer: cache-key prehash, speculative
        dedupe, then the verifier's plan() (dispatch routing + input
        packing) — all of it overlapping whatever launch is in
        flight."""
        t0 = time.perf_counter()
        with self._qmtx:
            self._prep_since = t0
        prep = _Prepared(priority)
        cm = _crypto_metrics()
        try:
            with _tracer.span(
                "verify_queue/prepare", cat="crypto", batch=len(reqs),
                priority=priority,
            ) as prep_span:
                work: list[_Request] = []
                for r in reqs:
                    r.key = cache_key(r.pub_key.bytes(), r.msg, r.sig)
                    cached = (
                        self.cache.lookup(r.key)
                        if self.cache is not None else None
                    )
                    if cached is not None:
                        cm.verify_queue_spec_cache.labels(
                            result="hit"
                        ).inc()
                        r.future._resolve(cached)
                        continue
                    if self.cache is not None:
                        cm.verify_queue_spec_cache.labels(
                            result="miss"
                        ).inc()
                    work.append(r)
                if work:
                    with self._qmtx:
                        self._stats["cache_resolved"] += (
                            len(reqs) - len(work)
                        )
                    prep.reqs = work
                    cm.verify_queue_batch_size.observe(len(work))
                    if self._launch is not None:
                        prep.groups = [(work, None, None)]
                    else:
                        prep.groups = self._build_groups(work)
                else:
                    with self._qmtx:
                        self._stats["cache_resolved"] += len(reqs)
                # stage mark for the attribution plane: how much of
                # this prepare was the speculative cache resolving
                # (critpath's verify_spec) vs real plan/pack work
                prep_span.set(
                    hits=len(reqs) - len(work), misses=len(work)
                )
            prep.prep_seconds = time.perf_counter() - t0
        finally:
            # overlap accounting: host prep that ran while a launch was
            # in flight is exactly the wall time the pipeline bought.
            # The _prep_since watermark may have been advanced by a
            # launch that ENDED mid-prep (it credited the overlap up to
            # its end), so accrue only from the watermark forward.  In
            # a finally so a raising prepare (malformed signature in
            # plan/pack) can't leave a stale watermark that every later
            # launch end mistakes for a live prep, pinning the
            # cumulative overlap ratio near 1.0.
            now = time.perf_counter()
            with self._qmtx:
                since = (
                    self._prep_since if self._prep_since is not None
                    else t0
                )
                if self._launch_active:
                    self._overlap_accum += max(
                        0.0, now - max(since, self._launch_t0)
                    )
                self._prep_since = None
        return prep

    def _build_groups(self, work: list[_Request]) -> list[tuple]:
        from cometbft_tpu.crypto import batch as crypto_batch

        by_type: dict[str, list[_Request]] = {}
        for r in work:
            by_type.setdefault(r.pub_key.type(), []).append(r)
        factory = self._factory
        groups: list[tuple] = []
        for reqs in by_type.values():
            pk0 = reqs[0].pub_key
            verifier = None
            # every group — single-signature ones included — routes
            # through the verifier seam so the dispatch ladder
            # (crypto/dispatch.py) is the ONE decision + accounting
            # point: a 1-sig group still plans (host route at
            # production thresholds, device when the ladder says so)
            # and lands in crypto_dispatch_tier; the per-sig fallback
            # below covers only unsupported key types and factory
            # failures.  The submission's COALESCED shape carries
            # through plan() untouched — the cost router (ISSUE 14)
            # sees the micro-batched size the launch will actually
            # have, not the per-caller fragment sizes, so an ingest
            # lane full of 1-sig CheckTx requests routes by the
            # 256-sig buffer it coalesced into
            if crypto_batch.supports_batch_verifier(pk0):
                try:
                    verifier = (
                        factory(pk0) if factory is not None
                        else crypto_batch.create_batch_verifier(pk0)
                    )
                except Exception:  # noqa: BLE001 — per-sig fallback
                    verifier = None
            plan = None
            if verifier is not None:
                for r in reqs:
                    verifier.add(r.pub_key, r.msg, r.sig)
                plan_fn = getattr(verifier, "plan", None)
                if plan_fn is not None:
                    plan = plan_fn()
            groups.append((reqs, verifier, plan))
        return groups

    # -- the launcher (device phase: buffer N) ---------------------------

    def _next_prepared(self) -> _Prepared | None:
        with self._qmtx:
            for p in _PRIORITIES:
                if self._prepared[p]:
                    return self._prepared[p].popleft()
        return None

    def _launch_done(self) -> bool:
        with self._qmtx:
            if not self._draining:
                return False
            if any(self._prepared.values()) or any(
                self._pending.values()
            ):
                return False
        t = self._collector_thread
        return t is None or not t.is_alive()

    def _launcher(self) -> None:
        while True:
            prep = self._next_prepared()
            if prep is None:
                if self._launch_done():
                    return
                self._launcher_wake.wait(0.05)
                self._launcher_wake.clear()
                continue
            self._collector_wake.set()  # slot freed: prep buffer N+1
            self._execute(prep)

    def _execute(self, prep: _Prepared) -> None:
        t0 = time.perf_counter()
        with self._qmtx:
            self._launch_active += 1
            if self._launch_active == 1:
                self._launch_t0 = t0
                self._overlap_accum = 0.0
        try:
            with _tracer.span(
                "verify_queue/launch", cat="crypto",
                batch=len(prep.reqs), priority=prep.priority,
            ):
                for reqs, verifier, plan in prep.groups:
                    self._execute_group(reqs, verifier, plan)
        finally:
            now = time.perf_counter()
            wall = max(now - t0, 0.0)
            with self._qmtx:
                self._launch_active -= 1
                if self._prep_since is not None:
                    # a prep is STILL running: credit its overlap with
                    # this launch now and advance its watermark so its
                    # own end-of-prep accrual can't double count
                    self._overlap_accum += max(
                        0.0, now - max(self._prep_since, t0)
                    )
                    self._prep_since = now
                overlap = min(self._overlap_accum, wall)
                self._overlap_accum = 0.0
                self._overlap_seconds += overlap
                self._launch_wall_seconds += wall
                self._stats["launched_batches"] += 1
                self._stats["launched_sigs"] += len(prep.reqs)
                # CUMULATIVE ratio: overlapped host-prep seconds over
                # total launch wall — a final buffer with nothing
                # behind it dilutes rather than zeroes the signal
                ratio = (
                    min(
                        self._overlap_seconds
                        / self._launch_wall_seconds,
                        1.0,
                    )
                    if self._launch_wall_seconds > 0 else 0.0
                )
                self._last_overlap = ratio
                inflight = self._launch_active + sum(
                    len(d) for d in self._prepared.values()
                )
            cm = _crypto_metrics()
            cm.verify_queue_inflight.set(inflight)
            _health_metrics().host_device_overlap_ratio.set(ratio)

    def _execute_group(self, reqs, verifier, plan) -> None:
        try:
            if self._launch is not None:
                results = self._launch(
                    [(r.pub_key, r.msg, r.sig) for r in reqs]
                )
            elif verifier is not None:
                if plan is not None:
                    ok, results = verifier.execute(plan)
                else:
                    ok, results = verifier.verify()
            else:
                # per-signature host fallback (unsupported key types,
                # factory failures): one ladder accounting sample at
                # the decision point — crypto_dispatch_tier covers
                # every verify, not just batch-seam launches.
                # Deliberately shape-blind (no batch/seconds): these
                # are whatever key types fell through, and timing
                # them would pollute the host tier's cost estimates
                from cometbft_tpu.crypto.dispatch import (
                    LADDER as _ladder,
                )

                _ladder.note_batch("host")
                results = [
                    r.pub_key.verify_signature(r.msg, r.sig)
                    for r in reqs
                ]
            results = list(results)
        except Exception as exc:  # noqa: BLE001 — strict sync fallback
            self.logger.error(
                "verify-queue launch failed", err=repr(exc),
                batch=len(reqs),
            )
            with self._qmtx:
                self._stats["failed_batches"] += 1
            for r in reqs:
                r.future._fail(exc)
            return
        if len(results) != len(reqs):
            # a malformed verifier/launch result must fail the batch
            # IMMEDIATELY (callers take the strict sync fallback), not
            # leave zip-truncated futures dangling until the 120 s
            # wait times out — on the consensus path, with locks held
            exc = RuntimeError(
                f"launch returned {len(results)} results for "
                f"{len(reqs)} requests"
            )
            self.logger.error(
                "verify-queue launch result mismatch", err=str(exc)
            )
            with self._qmtx:
                self._stats["failed_batches"] += 1
            for r in reqs:
                r.future._fail(exc)
            return
        for r, bit in zip(reqs, results):
            bit = bool(bit)
            if self.cache is not None and r.key is not None:
                self.cache.store(r.key, bit)
            r.future._resolve(bit)

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        with self._qmtx:
            out = {
                "submitted": dict(self._stats["submitted"]),
                "cache_resolved": self._stats["cache_resolved"],
                "prepared_batches": self._stats["prepared_batches"],
                "launched_batches": self._stats["launched_batches"],
                "launched_sigs": self._stats["launched_sigs"],
                "failed_batches": self._stats["failed_batches"],
                "pending": {
                    p: len(d) for p, d in self._pending.items()
                },
                "prepared": {
                    p: len(d) for p, d in self._prepared.items()
                },
                "overlap_ratio": self._last_overlap,
                "draining": self._draining,
            }
        out["cache_entries"] = len(self.cache) if self.cache else 0
        return out


# -- the process-wide queue + speculative helpers ------------------------

_install_mtx = cmtsync.Mutex()
_QUEUE: VerifyQueue | None = None


def install_queue(queue: VerifyQueue | None) -> None:
    """Install the process-wide queue (node assembly) or uninstall
    with None (node stop does this via VerifyQueue.on_stop)."""
    global _QUEUE
    with _install_mtx:
        _QUEUE = queue


def _installed() -> VerifyQueue | None:
    return _QUEUE


def speculation_active() -> bool:
    """True while a queue is installed and accepting — the gate every
    speculative consult (types/validation.py) and submission
    (vote_set, blocksync, consensus) checks first.  With no queue
    installed, every caller behaves exactly as before this module
    existed."""
    q = _QUEUE
    return q is not None and q.accepting()


def cached_result(
    pub: bytes, msg: bytes, sig: bytes, key: bytes | None = None
) -> bool | None:
    """Speculative-cache consult: True when this exact (pubkey,
    sign-bytes, signature) triple already verified VALID, None
    otherwise (caller verifies synchronously — negative verdicts are
    never cached, see SpeculativeCache).  Pass ``key`` (a precomputed
    ``cache_key``) to skip the SHA-512 prehash — a consult-then-record
    caller (validation._verify_group over a cold 10k-sig commit)
    hashes each triple once, not twice."""
    q = _QUEUE
    if q is None or q.cache is None:
        return None
    result = q.cache.lookup(
        key if key is not None else cache_key(pub, msg, sig)
    )
    _crypto_metrics().verify_queue_spec_cache.labels(
        result="hit" if result is not None else "miss"
    ).inc()
    return result


def record_result(
    pub: bytes, msg: bytes, sig: bytes, ok: bool,
    key: bytes | None = None,
) -> None:
    """Feed a synchronously obtained verdict into the cache so repeat
    verifications (evidence re-checks, light-client retries) skip the
    launch.  ``key`` as in ``cached_result``."""
    q = _QUEUE
    if q is not None and q.cache is not None:
        q.cache.store(
            key if key is not None else cache_key(pub, msg, sig),
            bool(ok),
        )


def _verify_inline(q: VerifyQueue | None, items) -> list[bool]:
    """The pre-queue synchronous path, cache-aware: speculated triples
    resolve from the cache, fresh verdicts feed it (True only) so
    ``verify_commit`` still hits even for inline-verified votes."""
    out: list[bool] = []
    for pk, msg, sig in items:
        key = None
        if q is not None:
            pkb = pk.bytes()
            key = cache_key(pkb, msg, sig)
            if cached_result(pkb, msg, sig, key=key) is True:
                out.append(True)
                continue
        ok = pk.verify_signature(msg, sig)
        if key is not None and ok:
            record_result(pkb, msg, sig, ok, key=key)
        out.append(ok)
    return out


def verify_or_fallback(
    items, priority: str = PRIORITY_CONSENSUS,
    timeout: float = DEFAULT_WAIT_S,
) -> list[bool]:
    """Verify ``(pub_key, msg, sig)`` tuples through the queue as ONE
    batched submission, with the strict synchronous fallback: any
    queue problem (not installed, draining, failed batch, timeout)
    degrades that item to the exact ``pub_key.verify_signature`` call
    the caller made before the queue existed.

    Consensus-priority requests NEVER park behind in-flight work:
    when the queue is busy (a prefetch launch on the device, buffers
    queued), a live vote's couple of signatures verify inline — the
    pre-queue latency — and the verdicts still land in the
    speculative cache.  Preemption reorders queued buffers; it cannot
    interrupt a launch, so waiting here could cost a full prefetch
    launch wall on the consensus hot path."""
    q = _QUEUE
    if q is None:
        return [
            pk.verify_signature(msg, sig) for pk, msg, sig in items
        ]
    if priority == PRIORITY_CONSENSUS and q.busy():
        return _verify_inline(q, items)
    try:
        futs = q.submit_many(items, priority)
    except QueueUnavailable:
        return _verify_inline(q, items)
    out: list[bool] = []
    # one SHARED deadline across the whole submission: the futures
    # resolve together (one batch), so per-future timeouts would
    # multiply a wedged launcher's stall by len(items) — with the
    # VoteSet mutex held, in the worst caller
    deadline = time.monotonic() + timeout
    for (pk, msg, sig), fut in zip(items, futs):
        try:
            out.append(
                fut.result(max(0.0, deadline - time.monotonic()))
            )
        except QueueUnavailable:
            out.append(pk.verify_signature(msg, sig))
    return out


def checktx_verify_or_fallback(
    items, timeout: float = DEFAULT_WAIT_S,
) -> tuple[list[bool], int]:
    """Mempool admission: verify ``(pub_key, msg, sig)`` tuples through
    the queue's low-priority ``ingest`` lane — the micro-batcher
    coalesces concurrent CheckTx calls into single DispatchLadder
    launches — with the same STRICT sync fallback the vote path has:
    queue off, draining, a failed batch, or a wait timeout degrades to
    the inline ``pub_key.verify_signature`` call, never a stall and
    never a dropped tx.

    Unlike consensus, ingest callers DO park behind in-flight work
    (no ``busy()`` bypass): admission is latency-tolerant by design,
    and waiting is what lets the accumulator fill.  Verdicts land in
    the speculative cache, so a tx re-submitted across peers (or hit
    again at recheck) resolves without a second launch.

    Returns ``(results, n_inline)`` — how many of the items actually
    degraded to the inline path, so the caller's batched/inline route
    metrics report what verified each signature, not what was merely
    attempted."""
    q = _QUEUE
    if q is None:
        return _verify_inline(None, items), len(items)
    try:
        futs = q.submit_many(items, PRIORITY_INGEST)
    except QueueUnavailable:
        return _verify_inline(q, items), len(items)
    out: list[bool] = []
    n_inline = 0
    # one shared deadline, same rationale as verify_or_fallback
    deadline = time.monotonic() + timeout
    for (pk, msg, sig), fut in zip(items, futs):
        try:
            out.append(
                fut.result(max(0.0, deadline - time.monotonic()))
            )
        except QueueUnavailable:
            out.append(pk.verify_signature(msg, sig))
            n_inline += 1
    return out, n_inline


def light_verify_or_fallback(
    items, timeout: float = DEFAULT_WAIT_S,
) -> tuple[list[bool], int]:
    """Light-client header serving: verify ``(pub_key, msg, sig)``
    tuples through the ``light_client`` lane — the shared micro-batcher
    coalesces CONCURRENT header syncs into single DispatchLadder
    launches — with the same STRICT sync fallback and
    ``(results, n_inline)`` contract as ``checktx_verify_or_fallback``.
    Light callers, like ingest, DO park behind in-flight work: serving
    latency is bulk-tolerant, and waiting is what fills the batch."""
    q = _QUEUE
    if q is None:
        return _verify_inline(None, items), len(items)
    try:
        futs = q.submit_many(items, PRIORITY_LIGHT)
    except QueueUnavailable:
        return _verify_inline(q, items), len(items)
    out: list[bool] = []
    n_inline = 0
    deadline = time.monotonic() + timeout
    for (pk, msg, sig), fut in zip(items, futs):
        try:
            out.append(
                fut.result(max(0.0, deadline - time.monotonic()))
            )
        except QueueUnavailable:
            out.append(pk.verify_signature(msg, sig))
            n_inline += 1
    return out, n_inline


# -- the submission-lane context (types/validation routing) --------------

_LANE_TLS = threading.local()


class submission_lane:
    """While active on this thread, ``types/validation._verify`` routes
    its batch signature verification through the queue at the given
    priority instead of building a synchronous batch verifier — the
    seam the light serving plane (light/serve.py) uses so that a full
    ``verify_commit_light`` keeps its tally/address semantics while
    its crypto rides the ``light_client`` micro-batcher.  ``_verify``
    captures the lane ONCE at entry (its key-type groups may run on
    executor threads where this thread-local is invisible).  Nests
    safely; no-op when no queue is installed."""

    def __init__(self, priority: str) -> None:
        if priority not in _PRIORITIES:
            raise ValueError(f"unknown priority {priority!r}")
        self._priority = priority
        self._prev: str | None = None

    def __enter__(self) -> "submission_lane":
        self._prev = getattr(_LANE_TLS, "lane", None)
        _LANE_TLS.lane = self._priority
        return self

    def __exit__(self, *exc) -> None:
        _LANE_TLS.lane = self._prev


def active_submission_lane() -> str | None:
    """The lane a ``submission_lane`` context has pinned on this
    thread, or None — None also when no queue is accepting, so the
    validation path degrades to its exact pre-lane behavior."""
    lane = getattr(_LANE_TLS, "lane", None)
    if lane is None:
        return None
    q = _QUEUE
    if q is None or not q.accepting():
        return None
    return lane


def submit_prefetch(items) -> int:
    """Fire-and-forget prefetch submission (blocksync replay, the
    consensus proposal's last_commit): results land in the speculative
    cache for the verify_commit that follows.  Returns the number of
    requests actually enqueued (0 when the queue is down — prefetch is
    never worth an error)."""
    q = _QUEUE
    if q is None:
        return 0
    try:
        q.submit_many(items, PRIORITY_PREFETCH)
    except QueueUnavailable:
        return 0
    return len(items)


__all__ = [
    "DEFAULT_CHECKTX_BATCH",
    "DEFAULT_CHECKTX_WAIT_MS",
    "DEFAULT_LIGHT_BATCH",
    "DEFAULT_LIGHT_WAIT_MS",
    "DEFAULT_MAX_BATCH",
    "DEFAULT_PREFETCH_DEPTH",
    "DEFAULT_SPEC_CACHE_CAP",
    "PRIORITY_CONSENSUS",
    "PRIORITY_INGEST",
    "PRIORITY_LIGHT",
    "PRIORITY_PREFETCH",
    "QueueUnavailable",
    "active_submission_lane",
    "checktx_batch_from_env",
    "checktx_verify_or_fallback",
    "checktx_wait_ms_from_env",
    "light_batch_from_env",
    "light_verify_or_fallback",
    "light_wait_ms_from_env",
    "SpeculativeCache",
    "VerifyFuture",
    "VerifyQueue",
    "cache_key",
    "cached_result",
    "install_queue",
    "prefetch_depth_from_env",
    "record_result",
    "spec_cache_capacity_from_env",
    "speculation_active",
    "submission_lane",
    "submit_prefetch",
    "verify_or_fallback",
]
