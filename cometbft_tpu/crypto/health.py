"""Device-health plane: tier probes, launch watchdog, utilization.

Two of five bench rounds silently lost the accelerator mid-run (r03: a
wedged tunnel; r04: two 600 s hung attempts) — nothing in the process
noticed until a human read the driver's rc=124.  This module turns
those failure modes into signals the dispatch ladder (ROADMAP item 5)
and an operator can act on, three instruments in one plane:

- **LaunchWatchdog** — bounds every real device launch at the
  ``TpuBatchVerifier.verify`` seam.  A launch that outlives its budget
  (``CMT_TPU_LAUNCH_BUDGET_S``, default 240 s — comfortably above the
  96 s cold-compile measured in r01 and far below the 600 s hangs of
  r04) increments ``crypto_device_hangs_total``, records a
  ``crypto/device_hang`` flight event, and logs a structured line —
  the stalled thread itself cannot be interrupted (the hang lives in C
  under the runtime), so the watchdog converts a silent stall into an
  observable one and records the recovery if the launch ever returns.
- **HealthProber** — a background thread issuing periodic lightweight
  canary verifies against each AVAILABLE dispatch tier (keyed_mesh /
  keyed / generic / host), every ``CMT_TPU_HEALTH_INTERVAL`` seconds
  (default 60; 0 disables).  Each probe feeds
  ``crypto_tier_probe_seconds{tier}`` and ``crypto_tier_healthy{tier}``
  AND the dispatch ladder (``crypto/dispatch.py``): N consecutive
  canary failures demote the tier, M consecutive healthy canaries
  promote it back — the loop this plane measures is now closed.
  Device tiers are probed only when a jax backend has
  ALREADY initialized in-process and is a real accelerator: the prober
  must never trigger the import-hang it exists to detect
  (crypto/batch.py's probe-subprocess rationale), and probing the
  XLA-on-CPU path would measure a tier no dispatch ever chooses.
- **DeviceUsage** — busy/idle accounting between launches
  (``crypto_device_busy_seconds_total{device}`` /
  ``crypto_device_idle_seconds_total{device}``, per chip on the mesh),
  the queue-wait vs kernel-wall split
  (``crypto_launch_queue_wait_seconds`` vs the existing
  ``crypto_kernel_time_seconds``), and the host/device overlap ratio
  (``crypto_host_device_overlap_ratio``) — the instrument that will
  prove where verify-ahead pipelining (ROADMAP item 2) lands.

Surfaces: ``/debug/perf`` on the metrics server and the ``debug/perf``
JSON-RPC route (inspect mode included) serve ``debug_perf_payload()``
— current tier health, last probe latencies, watchdog state,
utilization, and the perf-ledger tail (docs/data/perf_ledger.json,
tools/perfledger.py).  Documented in docs/observability.md
("Device-health plane").
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from contextlib import contextmanager

from cometbft_tpu.metrics import health_metrics as _health_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import FLIGHT
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.service import BaseService

DEFAULT_LAUNCH_BUDGET_S = 240.0
DEFAULT_HEALTH_INTERVAL_S = 60.0

#: the probe-able dispatch-ladder tiers in demotion order — a strict
#: subset of crypto/dispatch.TIER_ORDER (the python floor needs no
#: canary: it is never demoted)
TIERS = (
    "keyed_mesh", "keyed", "generic_mesh", "generic", "bls_native",
    "host",
)


def _float_env(var: str, default: float, minimum: float) -> float:
    """Validated float env knob (same fail-loudly contract as
    flight.ring_size_from_env, documented together): a float
    >= ``minimum``, anything else raises naming the variable."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be a number >= {minimum}, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {value}")
    return value


def launch_budget_from_env() -> float:
    """Watchdog budget per device launch, seconds (> 0)."""
    return _float_env(
        "CMT_TPU_LAUNCH_BUDGET_S", DEFAULT_LAUNCH_BUDGET_S, 0.001
    )


def health_interval_from_env() -> float:
    """Probe cadence, seconds; 0 disables the prober entirely."""
    return _float_env(
        "CMT_TPU_HEALTH_INTERVAL", DEFAULT_HEALTH_INTERVAL_S, 0.0
    )


class LaunchWatchdog:
    """Bounds device launches: one shared daemon thread tracks every
    armed launch's deadline; overruns are counted + flight-recorded
    (the launch itself cannot be interrupted — see module docstring).

    ``watch()`` is the seam-side API::

        with WATCHDOG.watch(tier="keyed", batch=n):
            out = self._run_keyed(...)

    Arm/disarm are O(1) dict ops under one mutex; the thread sleeps
    until the nearest deadline (or indefinitely when no launch is in
    flight), so an idle process pays nothing.
    """

    def __init__(
        self, budget_s: float | None = None, logger: Logger | None = None
    ):
        self._budget = budget_s
        self.logger = logger or default_logger().with_fields(
            module="crypto.health"
        )
        self._mtx = cmtsync.Mutex()
        self._wake = threading.Event()
        # guarded by _mtx: token -> {t0, deadline, tier, batch, fired}
        self._active: dict[int, dict] = {}
        self._next_token = 0  # guarded by _mtx
        self._thread: threading.Thread | None = None  # guarded by _mtx
        self._stop = False

    @property
    def budget_s(self) -> float:
        if self._budget is None:
            self._budget = launch_budget_from_env()
        return self._budget

    # -- seam API --------------------------------------------------------

    def arm(
        self, tier: str, batch: int = 0, budget_s: float | None = None
    ) -> int:
        deadline = time.monotonic() + (
            budget_s if budget_s is not None else self.budget_s
        )
        with self._mtx:
            self._next_token += 1
            token = self._next_token
            self._active[token] = {
                "t0": time.monotonic(),
                "deadline": deadline,
                "tier": tier,
                "batch": batch,
                "fired": False,
            }
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(
                    target=self._loop, name="crypto-watchdog", daemon=True
                )
                self._thread.start()
        self._wake.set()
        return token

    def disarm(self, token: int) -> bool:
        """Returns True when the watchdog had already fired for this
        launch (i.e. it recovered after being declared hung)."""
        with self._mtx:
            entry = self._active.pop(token, None)
        if entry is None:
            return False
        if entry["fired"]:
            stalled = time.monotonic() - entry["t0"]
            FLIGHT.record(
                "crypto/device_hang_recovered", tier=entry["tier"],
                batch=entry["batch"], stalled_s=round(stalled, 3),
            )
            self.logger.error(
                "device launch recovered after watchdog trip",
                tier=entry["tier"], stalled_s=round(stalled, 3),
            )
        return entry["fired"]

    @contextmanager
    def watch(self, tier: str, batch: int = 0,
              budget_s: float | None = None):
        """Yields a state box whose ``fired`` flag is filled at exit:
        callers that demote on escalation can tell whether THIS
        launch's overrun already demoted the tier (dispatch ladder
        duplicate-offense pairing)."""
        token = self.arm(tier, batch=batch, budget_s=budget_s)
        state = {"fired": False}
        try:
            yield state
        finally:
            state["fired"] = self.disarm(token)

    # -- the watchdog thread ---------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._mtx:
                if self._stop:
                    return
                pending = [
                    e["deadline"]
                    for e in self._active.values()
                    if not e["fired"]
                ]
            timeout = None
            if pending:
                timeout = max(min(pending) - time.monotonic(), 0.0)
            self._wake.wait(timeout)
            self._wake.clear()
            now = time.monotonic()
            expired: list[dict] = []
            with self._mtx:
                if self._stop:
                    return
                for entry in self._active.values():
                    if not entry["fired"] and entry["deadline"] <= now:
                        entry["fired"] = True
                        expired.append(dict(entry))
            for entry in expired:  # record outside the lock
                elapsed = now - entry["t0"]
                _health_metrics().device_hangs_total.inc()
                FLIGHT.record(
                    "crypto/device_hang", tier=entry["tier"],
                    batch=entry["batch"], elapsed_s=round(elapsed, 3),
                    budget_s=round(entry["deadline"] - entry["t0"], 3),
                )
                self.logger.error(
                    "device launch exceeded watchdog budget — tunnel "
                    "wedged or compile runaway (launch cannot be "
                    "interrupted; recovery will be logged if it ever "
                    "returns)",
                    tier=entry["tier"], batch=entry["batch"],
                    elapsed_s=round(elapsed, 3),
                )
                # the overrun demotes the wedged tier NOW, before the
                # stalled call returns (if it ever does) — the r04
                # failure mode becomes a ladder transition, not just a
                # counter.  Probe watchdogs carry a "probe:" prefix;
                # the hang is the underlying tier's either way.
                try:
                    from cometbft_tpu.crypto import dispatch as _disp

                    tier = entry["tier"]
                    if tier.startswith("probe:"):
                        tier = tier[len("probe:"):]
                    _disp.LADDER.watchdog_fault(tier)
                except Exception as exc:  # noqa: BLE001 — the
                    # watchdog thread must survive a ladder hiccup
                    self.logger.error(
                        "watchdog demotion failed", err=repr(exc)
                    )

    def stop(self) -> None:
        """Tests only: stop the shared thread (a fresh arm restarts
        it)."""
        with self._mtx:
            self._stop = True
            thread = self._thread
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5)
        with self._mtx:
            self._thread = None

    def snapshot(self) -> dict:
        with self._mtx:
            active = [
                {
                    "tier": e["tier"],
                    "batch": e["batch"],
                    "elapsed_s": round(time.monotonic() - e["t0"], 3),
                    "fired": e["fired"],
                }
                for e in self._active.values()
            ]
        return {"budget_s": self.budget_s, "active_launches": active}


class DeviceUsage:
    """Busy/idle accounting between launches + the queue-wait /
    fetch-wait instrumentation (module docstring).  All methods are a
    few float ops under one mutex — cheap enough for the per-batch hot
    path; the fetch-wait accumulator is thread-local so concurrent
    verifiers don't cross-charge each other's blocking fetches."""

    def __init__(self):
        self._mtx = cmtsync.Mutex()
        self._tl = threading.local()
        # guarded by _mtx: _covered_until is the high-water mark of
        # wall time already accounted busy — concurrent verifies (a
        # prober canary overlapping a production batch) contribute the
        # UNION of their launch intervals, so busy+idle never exceeds
        # wall time
        self._covered_until: float | None = None
        self._busy: dict[str, float] = {}
        self._idle: dict[str, float] = {}
        self._launches = 0
        self._last_overlap: float | None = None
        self._last_queue_wait: float | None = None
        self._last_fetch_wait: float | None = None

    # -- fetch-wait accumulator (hot fetch sites wrap device_get) --------

    @contextmanager
    def timed_fetch(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._tl.fetch = getattr(self._tl, "fetch", 0.0) + dt

    def fetch_wait(self) -> float:
        """This thread's accumulated blocking-fetch seconds."""
        return getattr(self._tl, "fetch", 0.0)

    # -- per-launch accounting (TpuBatchVerifier.verify seam) ------------

    def note_queue_wait(self, seconds: float) -> None:
        _health_metrics().launch_queue_wait_seconds.observe(seconds)
        with self._mtx:
            self._last_queue_wait = seconds

    def launch_end(
        self, t_launch: float, ndev: int = 1, fetch_wait: float = 0.0
    ) -> None:
        """Account one finished launch: busy = the not-yet-covered
        part of [t_launch, now) on each of ``ndev`` chips (union
        semantics under concurrent launches), idle = the uncovered gap
        before it, overlap = the share of the launch wall the host did
        NOT spend blocked in the result fetch."""
        now = time.perf_counter()
        wall = max(now - t_launch, 0.0)
        hm = _health_metrics()
        with self._mtx:
            prev = self._covered_until
            idle = 0.0
            if prev is None:
                busy = wall
            else:
                idle = max(t_launch - prev, 0.0)
                busy = max(now - max(t_launch, prev), 0.0)
            self._covered_until = max(prev or now, now)
            self._launches += 1
            for d in range(max(ndev, 1)):
                dev = str(d)
                self._busy[dev] = self._busy.get(dev, 0.0) + busy
                if idle:
                    self._idle[dev] = self._idle.get(dev, 0.0) + idle
            overlap = None
            if wall > 0:
                overlap = min(max(1.0 - fetch_wait / wall, 0.0), 1.0)
                self._last_overlap = overlap
            self._last_fetch_wait = fetch_wait
        for d in range(max(ndev, 1)):
            hm.device_busy_seconds_total.labels(device=str(d)).inc(busy)
            if idle:
                hm.device_idle_seconds_total.labels(device=str(d)).inc(
                    idle
                )
        if overlap is not None:
            hm.host_device_overlap_ratio.set(overlap)

    def snapshot(self) -> dict:
        with self._mtx:
            busy = dict(self._busy)
            idle = dict(self._idle)
            total_busy = sum(busy.values())
            total = total_busy + sum(idle.values())
            return {
                "launches": self._launches,
                "busy_seconds": {
                    d: round(v, 6) for d, v in sorted(busy.items())
                },
                "idle_seconds": {
                    d: round(v, 6) for d, v in sorted(idle.items())
                },
                "occupancy": (
                    round(total_busy / total, 4) if total > 0 else None
                ),
                "overlap_ratio": self._last_overlap,
                "last_queue_wait_s": self._last_queue_wait,
                "last_fetch_wait_s": self._last_fetch_wait,
            }


class HealthProber(BaseService):
    """Background canary prober over the available dispatch tiers.

    ``tiers`` maps tier name -> zero-arg callable returning truthy on
    a correct verify; None builds the default probes lazily at the
    first tick (host always; device tiers only when a real accelerator
    backend is already live in-process — see module docstring).  The
    first probe fires one full interval after start, so short-lived
    nodes (tests, localnet children) pay nothing.
    """

    def __init__(
        self,
        interval_s: float | None = None,
        tiers: dict | None = None,
        logger: Logger | None = None,
        watchdog: LaunchWatchdog | None = None,
        probe_timeout_s: float | None = None,
    ):
        super().__init__(
            name="health-prober",
            logger=logger or default_logger().with_fields(
                module="crypto.health"
            ),
        )
        self.interval_s = (
            interval_s if interval_s is not None
            else health_interval_from_env()
        )
        if self.interval_s <= 0:
            raise ValueError(
                "HealthProber needs a positive interval "
                "(CMT_TPU_HEALTH_INTERVAL=0 means: don't start one)"
            )
        self._tiers = tiers
        self._watchdog = watchdog if watchdog is not None else WATCHDOG
        self._probe_timeout = probe_timeout_s
        self._state_mtx = cmtsync.Mutex()
        self._state: dict[str, dict] = {}  # guarded by _state_mtx
        self.probes_total = 0  # guarded by _state_mtx
        # tier -> still-running probe worker (guarded by _state_mtx):
        # a tier whose previous canary is STILL stuck fails fast
        # instead of piling a new stuck thread per interval
        self._inflight: dict[str, threading.Thread] = {}
        self._thread: threading.Thread | None = None

    @property
    def probe_timeout_s(self) -> float:
        """How long one canary may run before it is declared hung —
        the watchdog launch budget unless overridden."""
        if self._probe_timeout is not None:
            return self._probe_timeout
        return self._watchdog.budget_s

    # -- lifecycle -------------------------------------------------------

    def on_start(self) -> None:
        global _ACTIVE_PROBER
        _ACTIVE_PROBER = self
        self._thread = threading.Thread(
            target=self._loop, name="health-prober", daemon=True
        )
        self._thread.start()

    def on_stop(self) -> None:
        global _ACTIVE_PROBER
        if _ACTIVE_PROBER is self:
            _ACTIVE_PROBER = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        # quit_event().wait doubles as the schedule: one probe per
        # interval, first probe one interval after start
        while not self.quit_event().wait(self.interval_s):
            try:
                self.probe_once()
            except Exception as exc:  # noqa: BLE001 — prober must
                # outlive any single bad probe round
                self.logger.error("probe round failed", err=repr(exc))

    # -- probing ---------------------------------------------------------

    def _tier_probes(self) -> dict:
        if self._tiers is not None:
            return self._tiers  # caller-pinned set (tests)
        # re-evaluated EVERY round, not cached: tier availability
        # grows during the process lifetime (a jax backend initializes
        # on the first device batch, the native BLS library loads on
        # the first aggregate commit), and a probe set frozen at the
        # first round would leave late-arriving tiers canary-less —
        # demoted once, they could then only recover through
        # half-open production batches paying the retry the prober
        # exists to absorb.  The capability checks inside
        # default_tier_probes are cheap reads (no imports, no builds).
        return default_tier_probes()

    def _run_probe(self, tier: str, probe) -> tuple[bool, str | None,
                                                    float]:
        """One canary in a bounded worker thread: a probe stuck in C
        under a wedged runtime cannot be interrupted, so the prober
        ABANDONS it at probe_timeout_s (the daemon worker parks on the
        stuck call) and reports the tier hung — the loop, and every
        other tier's schedule, keeps running.  While the stuck worker
        lives, the tier fails fast instead of stacking workers."""
        with self._state_mtx:
            prev = self._inflight.get(tier)
        if prev is not None and prev.is_alive():
            return False, "previous probe still hung", 0.0
        box: dict = {}

        def run() -> None:
            from cometbft_tpu.crypto import dispatch as _disp

            t0 = time.perf_counter()
            try:
                # probes are real device launches: the watchdog bounds
                # them exactly like production batches — and the chaos
                # plan faults canaries exactly like production batches
                # (probe=True skips the launch_hang sleep: the prober's
                # own timeout plays the watchdog's role on this seam)
                _disp.CHAOS.inject(tier, probe=True)
                with self._watchdog.watch(tier=f"probe:{tier}"):
                    box["ok"] = bool(probe())
            except Exception as exc:  # noqa: BLE001 — a dead tier is
                box["err"] = f"{type(exc).__name__}: {exc}"  # a result
            box["dt"] = time.perf_counter() - t0

        worker = threading.Thread(
            target=run, name=f"probe-{tier}", daemon=True
        )
        t0 = time.perf_counter()
        worker.start()
        worker.join(self.probe_timeout_s)
        if worker.is_alive():
            with self._state_mtx:
                self._inflight[tier] = worker
            return (
                False,
                f"probe exceeded {self.probe_timeout_s:g}s timeout",
                time.perf_counter() - t0,
            )
        with self._state_mtx:
            self._inflight.pop(tier, None)
        return (
            box.get("ok", False), box.get("err"),
            box.get("dt", time.perf_counter() - t0),
        )

    def probe_once(self) -> dict[str, bool]:
        """One canary round over every available tier; returns
        tier -> healthy.  Exposed for tests and `make health-smoke`."""
        from cometbft_tpu.crypto import dispatch as _disp

        hm = _health_metrics()
        results: dict[str, bool] = {}
        for tier, probe in self._tier_probes().items():
            ok, err, dt = self._run_probe(tier, probe)
            hm.tier_probe_seconds.labels(tier=tier).observe(dt)
            hm.tier_healthy.labels(tier=tier).set(1.0 if ok else 0.0)
            # canary evidence drives the dispatch ladder: N consecutive
            # failures demote the tier, M consecutive successes (past
            # its cool-down) promote it back (crypto/dispatch.py)
            _disp.LADDER.note_probe(tier, ok)
            with self._state_mtx:
                prev = self._state.get(tier, {})
                self._state[tier] = {
                    "healthy": ok,
                    "last_probe_s": round(dt, 6),
                    "last_probe_at": time.time(),
                    "consecutive_failures": (
                        0 if ok else prev.get("consecutive_failures", 0) + 1
                    ),
                    "error": err,
                }
                self.probes_total += 1
                was_healthy = prev.get("healthy")
            if not ok:
                hm.tier_probe_failures_total.labels(tier=tier).inc()
                FLIGHT.record(
                    "crypto/tier_unhealthy", tier=tier,
                    probe_s=round(dt, 3), err=err or "mis-verified",
                )
                self.logger.error(
                    "dispatch tier failed its canary probe", tier=tier,
                    probe_s=round(dt, 3), err=err or "mis-verified",
                )
            elif was_healthy is False:
                FLIGHT.record(
                    "crypto/tier_recovered", tier=tier,
                    probe_s=round(dt, 3),
                )
                self.logger.info(
                    "dispatch tier recovered", tier=tier
                )
            results[tier] = ok
        return results

    def snapshot(self) -> dict:
        with self._state_mtx:
            return {
                "interval_s": self.interval_s,
                "probe_timeout_s": self.probe_timeout_s,
                "probes_total": self.probes_total,
                "hung_probes": sorted(
                    t for t, w in self._inflight.items() if w.is_alive()
                ),
                "tiers": {t: dict(s) for t, s in self._state.items()},
            }


#: the currently running prober (set by HealthProber.on_start), read
#: by debug_perf_payload — None when no prober is running
_ACTIVE_PROBER: HealthProber | None = None


def _canary_fixture():
    """Two signed 64-byte messages, built once per process (signing is
    slow on the pure-Python fallback; the canary must stay cheap)."""
    global _CANARY
    if _CANARY is None:
        from cometbft_tpu.crypto import ed25519 as ed

        privs = [
            ed.priv_key_from_secret(b"health-canary-%d" % i)
            for i in range(2)
        ]
        msgs = [b"health canary %d" % i for i in range(2)]
        _CANARY = [
            (p.pub_key(), m, p.sign(m)) for p, m in zip(privs, msgs)
        ]
    return _CANARY


_CANARY = None


def default_tier_probes() -> dict:
    """tier name -> canary callable, for every tier AVAILABLE in this
    process right now.  Host is always available; device tiers only
    when a jax backend already initialized on a real accelerator
    (probing must never trigger the first-import hang, and the
    XLA-on-CPU path is a tier no dispatch chooses — see
    ops/ed25519_verify.runtime_device_min_batch)."""
    from cometbft_tpu.crypto import batch as _batch

    probes: dict = {"host": _probe_host}
    # the native BLS tier is probed only when the library ALREADY
    # loaded in this process: the prober must never trigger the
    # first-use g++ build (~10 s) for a tier no verify has asked for
    # — the same already-initialized gate the device tiers use
    from cometbft_tpu.crypto import bls_native as _bls_native

    if _bls_native.loaded():
        probes["bls_native"] = _probe_bls_native
    if not _batch._jax_backends_initialized():
        return probes
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return probes
    if not devices or devices[0].platform == "cpu":
        return probes
    probes["generic"] = _probe_generic
    probes["keyed"] = _probe_keyed
    if len(devices) > 1:
        probes["keyed_mesh"] = _probe_keyed_mesh
        probes["generic_mesh"] = _probe_generic_mesh
    return probes


def _probe_host() -> bool:
    from cometbft_tpu.crypto import ed25519 as ed

    bv = ed.CpuBatchVerifier()
    for pub, msg, sig in _canary_fixture():
        bv.add(pub, msg, sig)
    ok, bits = bv.verify()
    return ok and all(bits)


_BLS_CANARY = None


def _probe_bls_native() -> bool:
    """Native-BLS canary, PINNED to the native backend (the PR 9
    lesson: a canary that re-enters the ladder reports the FALLBACK's
    health as promotion evidence for the dead tier) — one fixed
    signature verified via bls_native.verify directly."""
    global _BLS_CANARY
    from cometbft_tpu.crypto import bls12381 as _bls
    from cometbft_tpu.crypto import bls_native as _bls_native

    if _BLS_CANARY is None:
        priv = _bls.priv_key_from_secret(b"cometbft-tpu-bls-canary")
        msg = b"bls-tier-canary"
        _BLS_CANARY = (
            priv.pub_key().bytes(), msg, _bls_native.sign(
                priv.bytes(), msg
            ),
        )
    pk, msg, sig = _BLS_CANARY
    return bool(_bls_native.verify(pk, msg, sig))


def _probe_arrays():
    import numpy as np

    fixture = _canary_fixture()
    pub = np.stack([
        np.frombuffer(p.bytes(), dtype=np.uint8) for p, _, _ in fixture
    ] * 4)
    sig = np.stack([
        np.frombuffer(s, dtype=np.uint8) for _, _, s in fixture
    ] * 4)
    msgs = [m for _, m, _ in fixture] * 4
    return pub, sig, msgs


def _probe_generic() -> bool:
    from cometbft_tpu.ops.ed25519_verify import verify_arrays

    pub, sig, msgs = _probe_arrays()
    return bool(verify_arrays(pub, sig, msgs).all())


def _probe_keyed() -> bool:
    """Keyed-tier canary: verifies against the prober's own tiny
    key-set tables (built once; table policy may decline a 2-key set,
    in which case the probe falls back to reporting the generic path's
    health under the keyed label rather than failing a healthy
    device)."""
    from cometbft_tpu.ops import precompute as PR
    from cometbft_tpu.ops.ed25519_verify import (
        _finish,
        verify_arrays_keyed_async,
    )

    pub, sig, msgs = _probe_arrays()
    pubs_b = [p.bytes() for p, _, _ in _canary_fixture()]
    entry = PR.TABLE_CACHE.lookup_or_build(pubs_b)
    if entry is None:  # out of table policy: not a device failure
        return _probe_generic()
    key_ids = entry.key_ids([bytes(p) for p in pub])
    out = _finish(
        verify_arrays_keyed_async(entry, key_ids, pub, sig, msgs)
    )
    return bool(out.all())


def _probe_keyed_mesh() -> bool:
    """Mesh-tier canary PINNED to the keyed_mesh runner: a canary must
    exercise its own tier, not walk the dispatch ladder — a demoted
    tier's canary routed one rung down would report the FALLBACK's
    health as promotion evidence for the dead tier."""
    from cometbft_tpu.ops import precompute as PR
    from cometbft_tpu.parallel.mesh import ShardedTpuBatchVerifier

    bv = ShardedTpuBatchVerifier(device_min_batch=0)
    if not bv._mesh_capable():
        return _probe_keyed()
    pub, sig, msgs = _probe_arrays()
    pubs_b = [p.bytes() for p, _, _ in _canary_fixture()]
    entry = PR.TABLE_CACHE.lookup_or_build(pubs_b)
    if entry is None:  # out of table policy: not a device failure
        return _probe_generic_mesh()
    key_ids = entry.key_ids([bytes(p) for p in pub])
    out = bv._run_keyed_mesh(entry, key_ids, pub, sig, msgs)
    return bool(out.all())


def _probe_generic_mesh() -> bool:
    """Sharded-generic canary, pinned to its runner for the same
    reason as the keyed_mesh probe."""
    from cometbft_tpu.parallel.mesh import ShardedTpuBatchVerifier

    bv = ShardedTpuBatchVerifier(device_min_batch=0)
    pub, sig, msgs = _probe_arrays()
    out = bv._run_generic_mesh(pub, sig, msgs)
    return bool(out.all())


#: process-wide singletons — the verifier seam and probers all feed
#: the same watchdog/usage state every surface reads (mirrors
#: utils/flight.FLIGHT)
WATCHDOG = LaunchWatchdog()
USAGE = DeviceUsage()


# -- the /debug/perf payload ---------------------------------------------

def perf_ledger_path() -> str:
    """docs/data/perf_ledger.json (CMT_TPU_PERF_LEDGER overrides) —
    the merged perf trajectory tools/perfledger.py maintains."""
    env = os.environ.get("CMT_TPU_PERF_LEDGER")  # env ok: free-form filesystem path — no parse to fail
    if env:
        return env
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    return os.path.join(repo, "docs", "data", "perf_ledger.json")


def perf_ledger_tail(n: int = 10) -> list[dict]:
    """Last ``n`` ledger entries (empty when no ledger exists yet)."""
    try:
        with open(perf_ledger_path()) as f:
            doc = json.load(f)
        entries = doc.get("entries", [])
        return entries[-n:] if n else entries
    except (OSError, ValueError):
        return []


#: batch-size provenance from a ledger config NAME, for rows predating
#: the explicit ``batch`` field: "<n>sig"/"<n>val" tokens
#: (micro_64sig, bls_aggregate_150val, light_sync_150val_pipelined)
#: and the verify_commit_<n> family.  Deliberately narrow — "8dev" or
#: "1kval" must NOT parse as a batch size.
_SHAPE_TOKEN_RE = re.compile(r"(?:^|[_-])(\d+)(?:sig|val)s?(?=$|[_-])")
_VERIFY_COMMIT_RE = re.compile(r"^verify_commit_(\d+)(?:$|_)")


def _entry_batch(e: dict) -> int | None:
    """The signature-batch size a ledger row measured, when its
    provenance carries one (explicit ``batch``/``nval`` field, or a
    parseable config name) — the shape key the cost-routing seed
    needs.  None means the row stays a tier-level fact only."""
    for field in ("batch", "nval"):
        v = e.get(field)
        if isinstance(v, (int, float)) and v >= 1:
            return int(v)
    cfg = e.get("config") or ""
    m = _SHAPE_TOKEN_RE.search(cfg) or _VERIFY_COMMIT_RE.match(cfg)
    if m:
        return int(m.group(1))
    return None


#: config families known to measure SINGLE-BATCH tier throughput (one
#: batch at a time through one tier's verify path) — the only numbers
#: a routing seed may treat as "what one launch of this shape costs on
#: this tier".  Deliberately default-deny: pipelined/overlapped rows
#: (verify_queue_pipelined), whole-pipeline stream rows (light_sync,
#: blocksync_replay), and mixed-workload rows (dispatch_shape_mix)
#: measure something else entirely and would mis-seed routing.
_SEEDABLE_CONFIG_RE = re.compile(
    r"^(micro_|verify_commit_|verify_queue_sync$|keyed_mesh_|"
    r"bls_aggregate_)"
)


def _route_seedable(e: dict) -> bool:
    """May this row seed a per-(tier, bucket) routing estimate?  An
    explicit ``route_seed`` field wins either way (the contract for
    new bench rows); otherwise the conservative single-batch config
    allowlist above decides."""
    flag = e.get("route_seed")
    if flag is not None:
        return bool(flag)
    return bool(_SEEDABLE_CONFIG_RE.match(e.get("config") or ""))


def _entry_throughput(e: dict) -> float | None:
    """A row's sigs/s for the per-bucket view: the value itself on a
    throughput row, else an explicit ``sigs_per_sec`` provenance field
    (latency rows like verify_commit_150_device record both) — None
    when the row carries no usable positive rate."""
    if e.get("unit") == "sigs/sec":
        v = e.get("value")
    else:
        v = e.get("sigs_per_sec")
    if isinstance(v, (int, float)) and v > 0:
        return float(v)
    return None


def measured_tier_throughput() -> dict[str, dict]:
    """Latest MEASURED sigs/s per dispatch tier from the perf ledger —
    the r05 lesson (host Pippenger outran the generic device path)
    made concrete: the static ladder order is a configuration, these
    numbers are evidence.  Ledger append order is recency (same-key
    replaces move to the end), so a later row for a tier wins; zero
    values are skipped (the ledger records device-down rounds as 0 —
    availability, not performance).

    Shape buckets (ISSUE 14): rows that measured SINGLE-BATCH tier
    throughput (``_route_seedable``: an explicit ``route_seed`` field
    or the known config allowlist — pipelined / sustained /
    mixed-workload rows are deliberately excluded, they measure a
    pipeline, not a launch) and whose provenance names a batch size
    additionally land in ``buckets`` — latest row per (tier,
    pow2-bucket) — the per-shape view ``dispatch.TierCostModel`` seeds
    from.  Latency rows carrying an explicit ``sigs_per_sec`` field
    (verify_commit_150_device) qualify for the bucket view even
    though their unit keeps them out of the tier-level map.  A row
    without batch provenance stays a tier-level fact only (the router
    never extrapolates a shapeless number across shapes)."""
    from cometbft_tpu.crypto.dispatch import shape_bucket

    out: dict[str, dict] = {}
    for e in perf_ledger_tail(0):  # 0 = the whole ledger, in order
        tier = e.get("dispatch_tier")
        if not tier:
            continue
        prev = out.get(tier)
        buckets = prev.get("buckets", {}) if prev else {}
        rate = _entry_throughput(e)
        batch = _entry_batch(e)
        bucket = shape_bucket(batch) if batch is not None else None
        if rate is not None and bucket is not None and (
            _route_seedable(e)
        ):
            buckets[bucket] = {
                "sigs_per_sec": rate,
                "batch": batch,
                "config": e.get("config"),
                "source": e.get("source"),
                "measured": e.get("measured"),
            }
        val = e.get("value")
        if e.get("unit") != "sigs/sec" or not isinstance(
            val, (int, float)
        ) or val <= 0:
            # not a tier-level throughput point; keep any bucket it
            # contributed attached to the tier's existing entry
            if prev is not None:
                prev["buckets"] = buckets
            elif buckets:
                out[tier] = {"buckets": buckets}
            continue
        entry = {
            "sigs_per_sec": val,
            "config": e.get("config"),
            "source": e.get("source"),
            "measured": e.get("measured"),
            "buckets": buckets,
        }
        if batch is not None:
            entry["batch"] = batch
            entry["bucket"] = bucket
        out[tier] = entry
    return out


def debug_perf_payload(ledger_tail_n: int = 10) -> dict:
    """Everything ``/debug/perf`` serves: tier health + last probe
    latencies, watchdog state, utilization gauges, device-probe
    status, and the perf-ledger tail."""
    from cometbft_tpu.crypto import batch as _batch

    prober = _ACTIVE_PROBER
    return {
        "device": _batch.device_status(),
        "prober": (
            prober.snapshot()
            if prober is not None
            else {"running": False, "tiers": {}}
        ),
        "watchdog": WATCHDOG.snapshot(),
        "utilization": USAGE.snapshot(),
        "ledger": {
            "path": perf_ledger_path(),
            "tail": perf_ledger_tail(ledger_tail_n),
        },
    }


__all__ = [
    "DEFAULT_HEALTH_INTERVAL_S",
    "DEFAULT_LAUNCH_BUDGET_S",
    "TIERS",
    "USAGE",
    "WATCHDOG",
    "DeviceUsage",
    "HealthProber",
    "LaunchWatchdog",
    "debug_perf_payload",
    "default_tier_probes",
    "health_interval_from_env",
    "launch_budget_from_env",
    "perf_ledger_path",
    "measured_tier_throughput",
    "perf_ledger_tail",
]
