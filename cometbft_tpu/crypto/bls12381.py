"""BLS12-381 signatures with aggregation
(reference: crypto/bls12381/key_bls12381.go — blst-backed, min-PK
variant: pubkeys are G1 points serialized UNCOMPRESSED (96 bytes,
blst.P1Affine.Serialize), signatures are G2 points compressed
(96 bytes, blst.P2Affine.Compress); messages longer than MaxMsgLen=32
are pre-hashed with SHA-256 before signing
(key_bls12381.go:110-117) — all replicated here, including the
reference's literal G1-named DST used for its G2 hash-to-curve).

From-scratch implementation built for speed on the host side (the
consensus node verifies aggregates on CPU; the TPU plane owns ed25519
volume — see ops/ed25519_verify.py):

- Tower field: Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3 - xi) with
  xi = 1+u, Fq12 = Fq6[w]/(w^2 - v). Karatsuba multiplication
  throughout; Frobenius maps are coefficient-wise conjugations times
  precomputed powers of xi (all constants derived numerically at
  import — nothing is pasted from tables).
- Optimal-ate Miller loop over affine twist points with Montgomery
  batch inversion across pairs per step, sparse line accumulation
  (coefficients only at w^0, w^3, w^5), and ONE shared loop for a
  whole aggregate (n+1 pairs -> n line-works, one final
  exponentiation).
- Final exponentiation: easy part f^((p^6-1)(p^2+1)), then the
  x-chain hard part via the exact integer identity
      3*(p^4-p^2+1)/r = (x-1)^2 * (x+p) * (x^2+p^2-1) + 3
  (asserted in tests/test_bls.py), computing f^(3t) — a fixed third
  power of the standard pairing, still bilinear and non-degenerate,
  so every verification equation is unchanged.  Four 64-bit
  x-exponentiations (|x| has Hamming weight 6) replace the naive
  ~4300-bit exponent.
- Subgroup checks: G1 membership via the x-chain
  [x^2]([x^2]P - P) + P == O (= [r]P with r = x^4-x^2+1); G2
  membership via the untwist-Frobenius-twist endomorphism psi with
  psi(Q) == [x]Q (p ≡ x mod r; completeness for BLS12-381 per
  M. Scott, "A note on group membership tests for G1, G2 and GT",
  eprint 2021/1130). Both are differentially tested against plain
  [r]-multiplication.

Hash-to-G1 follows RFC 9380 (see hash_to_curve docstrings below);
the differentially-tested slow oracle for the pairing lives in
tests/bls_naive_oracle.py.
"""

from __future__ import annotations

import hashlib
import hmac
import os

from cometbft_tpu.crypto import PrivKey, PubKey

KEY_TYPE = "bls12_381"
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 96      # G1 uncompressed (const.go:7, blst P1 Serialize)
SIGNATURE_SIZE = 96    # G2 compressed (const.go:9, blst P2 Compress)
MAX_MSG_LEN = 32       # const.go MaxMsgLen: longer messages pre-hash

# Field and curve parameters (draft-irtf-cfrg-pairing-friendly-curves).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = 0xD201000000010000  # |x|; the BLS parameter is -x
H1 = (BLS_X + 1) ** 2 // 3  # G1 cofactor (x-1)^2/3 with x = -|x|
H_EFF = BLS_X + 1           # RFC 9380 G1 clear_cofactor multiplier 1-x

_G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
_G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
_G2X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
_G2Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


# -- Fq ----------------------------------------------------------------

def _finv(a: int) -> int:
    return pow(a, -1, P)


# -- Fq2: a + b*u, u^2 = -1 --------------------------------------------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (1, 1)  # the Fq6 non-residue 1 + u
_B2 = (4, 4)  # G2 twist constant 4*xi


def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def f2_conj(a):
    return (a[0], (-a[1]) % P)


def f2_mul(a, b):
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    return ((t0 - t1) % P, ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % P)


def f2_sq(a):
    # (a0+a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t = a[0] * a[1] % P
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, 2 * t % P)


def f2_mul_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def f2_mul_xi(a):
    """a * (1+u)"""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def f2_inv(a):
    d = _finv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, (-a[1]) * d % P)


def f2_batch_inv(vals):
    """Montgomery batch inversion: one Fq inversion for n Fq2 inverses.

    The Miller loop's per-step slope denominators all invert at once
    through this (the per-pair affine formulas would otherwise cost one
    field inversion per pair per step)."""
    n = len(vals)
    if n == 0:
        return []
    prefix = [F2_ONE] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = f2_mul(prefix[i], v)
    inv_all = f2_inv(prefix[n])
    out = [F2_ZERO] * n
    for i in range(n - 1, -1, -1):
        out[i] = f2_mul(prefix[i], inv_all)
        inv_all = f2_mul(inv_all, vals[i])
    return out


def f2_pow(a, e: int):
    out = F2_ONE
    while e:
        if e & 1:
            out = f2_mul(out, a)
        a = f2_sq(a)
        e >>= 1
    return out


def f2_sqrt(a):
    """sqrt in Fq2 via the norm trick (complex method)."""
    if a == F2_ZERO:
        return F2_ZERO
    a0, a1 = a
    if a1 == 0:
        c = pow(a0, (P + 1) // 4, P)
        if c * c % P == a0:
            return (c, 0)
        t = pow((-a0) % P, (P + 1) // 4, P)
        if t * t % P == (-a0) % P:
            return (0, t)
        return None
    alpha = (a0 * a0 + a1 * a1) % P  # norm
    s = pow(alpha, (P + 1) // 4, P)
    if s * s % P != alpha:
        return None
    delta = (a0 + s) * _finv(2) % P
    x0 = pow(delta, (P + 1) // 4, P)
    if x0 * x0 % P != delta:
        delta = (a0 - s) * _finv(2) % P
        x0 = pow(delta, (P + 1) // 4, P)
        if x0 * x0 % P != delta:
            return None
    x1 = a1 * _finv(2 * x0 % P) % P
    cand = (x0, x1)
    return cand if f2_sq(cand) == a else None


# -- Fq6 = Fq2[v]/(v^3 - xi): triples (a0, a1, a2) ----------------------

F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a, b):
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a, b):
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a, b):
    """Karatsuba-style 6-multiplication (Devegili et al. interpolation)."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    c0 = f2_add(
        t0,
        f2_mul_xi(
            f2_sub(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), t1), t2)
        ),
    )
    c1 = f2_add(
        f2_sub(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), t0), t1),
        f2_mul_xi(t2),
    )
    c2 = f2_add(
        f2_sub(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def f6_sq(a):
    return f6_mul(a, a)


def f6_mul_v(a):
    """a * v:  (a0, a1, a2) -> (xi*a2, a0, a1)"""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_scale2(a, s):
    """Multiply an Fq6 element by an Fq2 scalar."""
    return (f2_mul(a[0], s), f2_mul(a[1], s), f2_mul(a[2], s))


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sq(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sq(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sq(a1), f2_mul(a0, a2))
    t = f2_add(
        f2_mul(a0, c0),
        f2_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2))),
    )
    ti = f2_inv(t)
    return (f2_mul(c0, ti), f2_mul(c1, ti), f2_mul(c2, ti))


# -- Fq12 = Fq6[w]/(w^2 - v): pairs (c0, c1) ---------------------------

F12_ONE = (F6_ONE, F6_ZERO)


def f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c1 = f6_sub(
        f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1
    )
    return (f6_add(t0, f6_mul_v(t1)), c1)


def f12_sq(a):
    a0, a1 = a
    t = f6_mul(a0, a1)
    c0 = f6_sub(
        f6_sub(f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_v(a1))), t),
        f6_mul_v(t),
    )
    return (c0, f6_add(t, t))


def f12_conj(a):
    """f^(p^6): (c0, -c1).  In the cyclotomic subgroup this IS the
    inverse, which is what makes the x-chain cheap."""
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    a0, a1 = a
    t = f6_inv(f6_sub(f6_sq(a0), f6_mul_v(f6_sq(a1))))
    return (f6_mul(a0, t), f6_neg(f6_mul(a1, t)))


# Frobenius constants, derived at import time:
#   frob(a0 + a1 v + a2 v^2) = conj(a0) + conj(a1) g1 v + conj(a2) g2 v^2
#   with g1 = xi^((p-1)/3), g2 = g1^2; and frob(c0 + c1 w) =
#   frob6(c0) + [frob6(c1) * xi^((p-1)/6)] w  (w^(p-1) = xi^((p-1)/6)).
_F6C1 = f2_pow(XI, (P - 1) // 3)
_F6C2 = f2_sq(_F6C1)
_F12C = f2_pow(XI, (P - 1) // 6)


def _frob6(a):
    return (
        f2_conj(a[0]),
        f2_mul(f2_conj(a[1]), _F6C1),
        f2_mul(f2_conj(a[2]), _F6C2),
    )


def f12_frob(a):
    return (_frob6(a[0]), f6_scale2(_frob6(a[1]), _F12C))


def f12_frob2(a):
    return f12_frob(f12_frob(a))


def f12_pow(a, e: int):
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sq(a)
        e >>= 1
    return out


# -- curve points -------------------------------------------------------
# Affine tuples; None is the identity.  G1 over Fq, G2 over Fq2 (twist
# coordinates y^2 = x^3 + 4*xi).  Scalar multiplication runs in
# Jacobian coordinates so there are no per-step field inversions.

G1_GEN = (_G1X, _G1Y)
G2_GEN = (_G2X, _G2Y)


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (pow(x, 3, P) + 4)) % P == 0


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sq(y), f2_add(f2_mul(f2_sq(x), x), _B2)) == F2_ZERO


class _FqOps:
    """Field-op table so the Jacobian formulas are written once."""

    zero = 0
    one = 1

    @staticmethod
    def add(a, b):
        return (a + b) % P

    @staticmethod
    def sub(a, b):
        return (a - b) % P

    @staticmethod
    def neg(a):
        return (-a) % P

    @staticmethod
    def mul(a, b):
        return a * b % P

    @staticmethod
    def sq(a):
        return a * a % P

    @staticmethod
    def inv(a):
        return _finv(a)

    @staticmethod
    def is_zero(a):
        return a % P == 0


class _Fq2Ops:
    zero = F2_ZERO
    one = F2_ONE
    add = staticmethod(f2_add)
    sub = staticmethod(f2_sub)
    neg = staticmethod(f2_neg)
    mul = staticmethod(f2_mul)
    sq = staticmethod(f2_sq)
    inv = staticmethod(f2_inv)

    @staticmethod
    def is_zero(a):
        return a[0] % P == 0 and a[1] % P == 0


def _jac_dbl(F, pt):
    """2P on y^2 = x^3 + b (a = 0), Jacobian (X, Y, Z), Z=0 identity."""
    X1, Y1, Z1 = pt
    if F.is_zero(Z1) or F.is_zero(Y1):
        return (F.one, F.one, F.zero)
    A = F.sq(X1)
    B = F.sq(Y1)
    C = F.sq(B)
    D = F.sub(F.sub(F.sq(F.add(X1, B)), A), C)
    D = F.add(D, D)
    E = F.add(F.add(A, A), A)
    Fv = F.sq(E)
    X3 = F.sub(Fv, F.add(D, D))
    C8 = F.add(C, C)
    C8 = F.add(C8, C8)
    C8 = F.add(C8, C8)
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), C8)
    Z3 = F.mul(F.add(Y1, Y1), Z1)
    return (X3, Y3, Z3)


def _jac_add(F, p1, p2):
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    if F.is_zero(Z1):
        return p2
    if F.is_zero(Z2):
        return p1
    Z1Z1 = F.sq(Z1)
    Z2Z2 = F.sq(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    H = F.sub(U2, U1)
    rr = F.sub(S2, S1)
    if F.is_zero(H):
        if F.is_zero(rr):
            return _jac_dbl(F, p1)
        return (F.one, F.one, F.zero)
    HH = F.sq(H)
    HHH = F.mul(H, HH)
    V = F.mul(U1, HH)
    X3 = F.sub(F.sub(F.sq(rr), HHH), F.add(V, V))
    Y3 = F.sub(F.mul(rr, F.sub(V, X3)), F.mul(S1, HHH))
    Z3 = F.mul(F.mul(Z1, Z2), H)
    return (X3, Y3, Z3)


def _jac_from_affine(F, pt):
    if pt is None:
        return (F.one, F.one, F.zero)
    return (pt[0], pt[1], F.one)


def _jac_to_affine(F, pt):
    X, Y, Z = pt
    if F.is_zero(Z):
        return None
    zi = F.inv(Z)
    zi2 = F.sq(zi)
    return (F.mul(X, zi2), F.mul(Y, F.mul(zi, zi2)))


def _jac_mul(F, pt, k: int):
    if k < 0:
        k = -k
        pt = (pt[0], F.neg(pt[1]), pt[2])
    acc = (F.one, F.one, F.zero)
    if k == 0:
        return acc
    for bit in bin(k)[2:]:
        acc = _jac_dbl(F, acc)
        if bit == "1":
            acc = _jac_add(F, acc, pt)
    return acc


def g1_add(p1, p2):
    return _jac_to_affine(
        _FqOps,
        _jac_add(_FqOps, _jac_from_affine(_FqOps, p1), _jac_from_affine(_FqOps, p2)),
    )


def g1_mul(pt, k: int):
    if pt is None:
        return None
    return _jac_to_affine(_FqOps, _jac_mul(_FqOps, _jac_from_affine(_FqOps, pt), k))


def g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], (-pt[1]) % P)


def g2_add(p1, p2):
    return _jac_to_affine(
        _Fq2Ops,
        _jac_add(_Fq2Ops, _jac_from_affine(_Fq2Ops, p1), _jac_from_affine(_Fq2Ops, p2)),
    )


def g2_mul(pt, k: int):
    if pt is None:
        return None
    return _jac_to_affine(_Fq2Ops, _jac_mul(_Fq2Ops, _jac_from_affine(_Fq2Ops, pt), k))


def g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], f2_neg(pt[1]))


# -- subgroup membership ------------------------------------------------

def g1_in_subgroup(pt) -> bool:
    """[r]P == O computed through the x-chain:
    r = x^4 - x^2 + 1, so [r]P = [x^2]([x^2]P - P) + P.  Two 64-bit
    double-chains instead of one 255-bit ladder."""
    if pt is None:
        return True
    F = _FqOps
    j = _jac_from_affine(F, pt)
    u = _jac_mul(F, _jac_mul(F, j, BLS_X), BLS_X)          # [x^2]P
    w = _jac_add(F, u, (j[0], F.neg(j[1]), j[2]))          # [x^2]P - P
    z = _jac_mul(F, _jac_mul(F, w, BLS_X), BLS_X)          # [x^4-x^2]P
    return _jac_to_affine(F, _jac_add(F, z, j)) is None


# psi = twist o frobenius o untwist on E'(Fq2):
#   psi(x, y) = (conj(x) * xi^-((p-1)/3), conj(y) * xi^-((p-1)/2))
_PSI_CX = f2_inv(f2_pow(XI, (P - 1) // 3))
_PSI_CY = f2_inv(f2_pow(XI, (P - 1) // 2))


def g2_psi(pt):
    if pt is None:
        return None
    return (f2_mul(f2_conj(pt[0]), _PSI_CX), f2_mul(f2_conj(pt[1]), _PSI_CY))


def g2_in_subgroup(pt) -> bool:
    """psi(Q) == [x]Q characterizes G2 on the BLS12-381 twist
    (eigenvalue: p ≡ x mod r; completeness per eprint 2021/1130)."""
    if pt is None:
        return True
    return g2_psi(pt) == g2_mul(pt, -BLS_X)


# -- pairing: optimal ate, affine Miller loop with sparse lines ---------
#
# Untwisting (x, y) -> (x/w^2, y/w^3) turns the line through twist
# points T with slope L, evaluated at P=(xP, yP) in G1, into (after
# scaling by the Fq2 constant xi, which the final exponentiation
# kills):
#     l = xi*yP  +  (L*xT - yT) * w^3  -  L*xP * w^5
# i.e. sparse at Fq2-coefficients (c0.a0, c1.a1, c1.a2) of the
# (Fq6, Fq6*w) representation; _mul_sparse exploits that.

_XBITS = bin(BLS_X)[3:]  # MSB consumed by the initial T = Q


def _mul_sparse(f, s0, s4, s5):
    """f * (s0 + s4 w^3 + s5 w^5) with si in Fq2 (w^3 = v w, w^5 = v^2 w)."""
    b = ((s0, F2_ZERO, F2_ZERO), (F2_ZERO, s4, s5))
    return f12_mul(f, b)


def _miller_loop_pairs(pairs):
    """Shared optimal-ate Miller loop over [(P in G1 affine, Q in G2
    twist affine)]: squarings of the accumulator are shared across all
    pairs; slope denominators batch-invert per step.  Returns the
    un-exponentiated f_{|x|} value, conjugated for the negative BLS x.
    """
    prepped = [
        (p, q) for (p, q) in pairs if p is not None and q is not None
    ]
    if not prepped:
        return F12_ONE
    ps = [p for p, _ in prepped]
    qs = [q for _, q in prepped]
    ts = list(qs)
    xiy = [f2_mul_scalar(XI, p[1]) for p in ps]  # xi * yP per pair
    acc = F12_ONE
    for bit in _XBITS:
        acc = f12_sq(acc)
        # doubling step: slope = 3 xT^2 / (2 yT)
        denoms = f2_batch_inv([f2_add(t[1], t[1]) for t in ts])
        for i, t in enumerate(ts):
            xt, yt = t
            lam = f2_mul(f2_mul_scalar(f2_sq(xt), 3), denoms[i])
            acc = _mul_sparse(
                acc,
                xiy[i],
                f2_sub(f2_mul(lam, xt), yt),
                f2_neg(f2_mul_scalar(lam, ps[i][0])),
            )
            x3 = f2_sub(f2_sq(lam), f2_add(xt, xt))
            ts[i] = (x3, f2_sub(f2_mul(lam, f2_sub(xt, x3)), yt))
        if bit == "1":
            # addition step: slope through T and Q
            denoms = f2_batch_inv(
                [f2_sub(t[0], q[0]) for t, q in zip(ts, qs)]
            )
            for i, (t, q) in enumerate(zip(ts, qs)):
                lam = f2_mul(f2_sub(t[1], q[1]), denoms[i])
                acc = _mul_sparse(
                    acc,
                    xiy[i],
                    f2_sub(f2_mul(lam, t[0]), t[1]),
                    f2_neg(f2_mul_scalar(lam, ps[i][0])),
                )
                x3 = f2_sub(f2_sub(f2_sq(lam), t[0]), q[0])
                ts[i] = (x3, f2_sub(f2_mul(lam, f2_sub(t[0], x3)), t[1]))
    return f12_conj(acc)  # BLS parameter is negative


def _pow_x(f):
    """f^x for the (negative) BLS parameter: f^|x| then conjugate —
    valid in the cyclotomic subgroup where conj is inversion."""
    out = F12_ONE
    base = f
    e = BLS_X
    while e:
        if e & 1:
            out = f12_mul(out, base)
        e >>= 1
        if e:
            base = f12_sq(base)
    return f12_conj(out)


def final_exponentiation(f):
    """f^(3 * (p^12-1)/r) via easy part + the x-chain hard part
    (module docstring identity).  The extra fixed cube keeps
    bilinearity and non-degeneracy, so pairing-product checks are
    unaffected."""
    # easy part: f^((p^6-1)(p^2+1))
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frob2(f), f)
    # hard part: f^((x-1)^2 (x+p) (x^2+p^2-1) + 3)
    a = f12_mul(_pow_x(f), f12_conj(f))          # f^(x-1)
    b = f12_mul(_pow_x(a), f12_conj(a))          # a^(x-1)
    c = f12_mul(_pow_x(b), f12_frob(b))          # b^(x+p)
    d = f12_mul(
        f12_mul(_pow_x(_pow_x(c)), f12_frob2(c)),
        f12_conj(c),
    )                                            # c^(x^2+p^2-1)
    return f12_mul(d, f12_mul(f12_sq(f), f))     # * f^3


def multi_miller_loop(pairs):
    """[(P in G1, Q in G2 twist affine), ...] -> un-exponentiated
    product value (kept for API compatibility with the oracle)."""
    return _miller_loop_pairs(pairs)


def miller_loop(q_g2, p_g1):
    return _miller_loop_pairs([(p_g1, q_g2)])


def pairing(p_g1, q_g2):
    """e(P, Q)^3 — a bilinear non-degenerate pairing into GT (the
    fixed cube of the standard reduced ate pairing; see
    final_exponentiation)."""
    return final_exponentiation(miller_loop(q_g2, p_g1))


def pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1, one shared loop + one final exp."""
    return final_exponentiation(_miller_loop_pairs(pairs)) == F12_ONE


# -- serialization (ZCash-style compressed encodings) -------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SIGN = 0x20


def g1_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(48)
        out[0] = _FLAG_COMPRESSED | _FLAG_INFINITY
        return bytes(out)
    x, y = pt
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= _FLAG_COMPRESSED
    if y > (P - 1) // 2:
        out[0] |= _FLAG_SIGN
    return bytes(out)


def g1_to_bytes_uncompressed(pt) -> bytes:
    """96-byte x||y encoding (blst P1Affine.Serialize)."""
    if pt is None:
        out = bytearray(96)
        out[0] = _FLAG_INFINITY
        return bytes(out)
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def g1_from_bytes_uncompressed(data: bytes):
    if len(data) != 96:
        raise ValueError("bad uncompressed G1 encoding")
    if data[0] & _FLAG_INFINITY:
        if any(data[1:]) or data[0] != _FLAG_INFINITY:
            raise ValueError("bad G1 infinity encoding")
        return None
    if data[0] & (_FLAG_COMPRESSED | _FLAG_SIGN):
        raise ValueError("unexpected G1 compression flags")
    x = int.from_bytes(data[:48], "big")
    y = int.from_bytes(data[48:], "big")
    if x >= P or y >= P:
        raise ValueError("G1 coordinate out of range")
    pt = (x, y)
    if not g1_is_on_curve(pt):
        raise ValueError("G1 point not on curve")
    if not g1_in_subgroup(pt):
        raise ValueError("G1 point not in the r-torsion subgroup")
    return pt


def g1_from_bytes(data: bytes):
    if len(data) != 48 or not data[0] & _FLAG_COMPRESSED:
        raise ValueError("bad G1 encoding")
    if data[0] & _FLAG_INFINITY:
        if any(data[1:]) or data[0] & ~(_FLAG_COMPRESSED | _FLAG_INFINITY):
            raise ValueError("bad G1 infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y2 = (pow(x, 3, P) + 4) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("G1 x not on curve")
    if bool(data[0] & _FLAG_SIGN) != (y > (P - 1) // 2):
        y = P - y
    pt = (x, y)
    if not g1_in_subgroup(pt):
        raise ValueError("G1 point not in the r-torsion subgroup")
    return pt


def g2_to_bytes(pt) -> bytes:
    if pt is None:
        out = bytearray(96)
        out[0] = _FLAG_COMPRESSED | _FLAG_INFINITY
        return bytes(out)
    (x0, x1), (y0, y1) = pt
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= _FLAG_COMPRESSED
    big = (y1 > (P - 1) // 2) if y1 else (y0 > (P - 1) // 2)
    if big:
        out[0] |= _FLAG_SIGN
    return bytes(out)


def g2_from_bytes(data: bytes):
    if len(data) != 96 or not data[0] & _FLAG_COMPRESSED:
        raise ValueError("bad G2 encoding")
    if data[0] & _FLAG_INFINITY:
        if any(data[1:]):
            raise ValueError("bad G2 infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y2 = f2_add(f2_mul(f2_sq(x), x), _B2)
    y = f2_sqrt(y2)
    if y is None:
        raise ValueError("G2 x not on curve")
    y0, y1 = y
    big = (y1 > (P - 1) // 2) if y1 else (y0 > (P - 1) // 2)
    if bool(data[0] & _FLAG_SIGN) != big:
        y = f2_neg(y)
    pt = (x, y)
    if not g2_in_subgroup(pt):
        raise ValueError("G2 point not in the r-torsion subgroup")
    return pt


# -- hashing to the curve ----------------------------------------------

def _digest_msg(msg: bytes) -> bytes:
    """Messages beyond MaxMsgLen are SHA-256'd first
    (key_bls12381.go:110-113, :188-190)."""
    if len(msg) > MAX_MSG_LEN:
        return hashlib.sha256(msg).digest()
    return bytes(msg)


def hash_to_g2(msg: bytes):
    """RFC 9380 SSWU hash onto G2 (see crypto/bls_hash_to_g2.py);
    msg is hashed as given — callers apply _digest_msg first."""
    from cometbft_tpu.crypto import bls_hash_to_g2 as _h2c

    return _h2c.hash_to_g2(msg)


# -- BLS signature scheme ----------------------------------------------

class Bls12381PubKey(PubKey):
    __slots__ = ("_bytes", "_pt")

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"bls pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._pt = None

    def _point(self):
        if self._pt is None:
            self._pt = g1_from_bytes_uncompressed(self._bytes)
            if self._pt is None:
                raise ValueError("bls pubkey is the identity")
        return self._pt

    def address(self) -> bytes:
        """SHA256(pubkey)[:20] (key_bls12381.go Address via tmhash)."""
        return hashlib.sha256(self._bytes).digest()[:20]

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """e(pk, H(m)) == e(g1, sig) via one 2-pair loop
        (key_bls12381.go:176-191, min-PK check); routed through the
        native C++ backend when built (crypto/bls_native.py)."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        from cometbft_tpu.crypto import bls_native

        if bls_native.available():
            return bls_native.verify(
                self._bytes, _digest_msg(msg), bytes(sig)
            )
        return self.verify_signature_python(msg, sig)

    def verify_signature_python(self, msg: bytes, sig: bytes) -> bool:
        """The pure tower-field path, never the native backend — the
        dispatch ladder's floor runner (crypto/bls_dispatch.py) when
        ``bls_native`` is demoted or absent."""
        if len(sig) != SIGNATURE_SIZE:
            return False
        try:
            s = g2_from_bytes(sig)
            pk = self._point()
        except ValueError:
            return False
        if s is None:
            return False
        return pairing_product_is_one(
            [(pk, hash_to_g2(_digest_msg(msg))), (g1_neg(G1_GEN), s)]
        )


class Bls12381PrivKey(PrivKey):
    __slots__ = ("_d",)

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(f"bls privkey must be {PRIV_KEY_SIZE} bytes")
        d = int.from_bytes(data, "big")
        if not (1 <= d < R):
            raise ValueError("bls privkey out of range")
        self._d = d

    def bytes(self) -> bytes:
        return self._d.to_bytes(32, "big")

    def type(self) -> str:
        return KEY_TYPE

    def pub_key(self) -> Bls12381PubKey:
        return Bls12381PubKey(
            g1_to_bytes_uncompressed(g1_mul(G1_GEN, self._d))
        )

    def sign(self, msg: bytes) -> bytes:
        """[d] H(m) in G2, compressed (key_bls12381.go:108-118)."""
        from cometbft_tpu.crypto import bls_native

        if bls_native.available():
            return bls_native.sign(self.bytes(), _digest_msg(msg))
        return g2_to_bytes(g2_mul(hash_to_g2(_digest_msg(msg)), self._d))


def gen_priv_key() -> Bls12381PrivKey:
    while True:
        raw = os.urandom(32)
        d = int.from_bytes(raw, "big")
        if 1 <= d < R:
            return Bls12381PrivKey(raw)


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    okm, t, i = b"", b"", 1
    while len(okm) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        okm += t
        i += 1
    return okm[:length]


def priv_key_from_secret(secret: bytes) -> Bls12381PrivKey:
    """Seed-compatible with the reference: blst.KeyGen per
    draft-irtf-cfrg-bls-signature-05 §2.3 (HKDF-SHA256, salt chain from
    "BLS-SIG-KEYGEN-SALT-", L=48), with non-32-byte secrets sha256
    pre-hashed first (key_bls12381.go:63-70)."""
    if len(secret) != 32:
        secret = hashlib.sha256(secret).digest()
    salt = b"BLS-SIG-KEYGEN-SALT-"
    ikm = secret + b"\x00"
    info = (48).to_bytes(2, "big")
    while True:
        salt = hashlib.sha256(salt).digest()
        okm = _hkdf_expand(_hkdf_extract(salt, ikm), info, 48)
        d = int.from_bytes(okm, "big") % R
        if d:
            return Bls12381PrivKey(d.to_bytes(32, "big"))


# -- aggregation (key_bls12381.go:37-38 aggregate APIs) -----------------

def aggregate_signatures(sigs: list[bytes]) -> bytes:
    """Sum of G2 signature points (blst.P2Aggregate)."""
    F = _Fq2Ops
    acc = (F.one, F.one, F.zero)
    for sig in sigs:
        pt = g2_from_bytes(sig)
        if pt is None:
            raise ValueError("cannot aggregate the identity signature")
        acc = _jac_add(F, acc, _jac_from_affine(F, pt))
    return g2_to_bytes(_jac_to_affine(F, acc))


def aggregate_pub_keys(pubs: list[Bls12381PubKey]) -> Bls12381PubKey:
    """Sum of G1 pubkey points (blst.P1Aggregate, for same-message
    fast aggregate)."""
    F = _FqOps
    acc = (F.one, F.one, F.zero)
    for pk in pubs:
        acc = _jac_add(F, acc, _jac_from_affine(F, pk._point()))
    return Bls12381PubKey(
        g1_to_bytes_uncompressed(_jac_to_affine(F, acc))
    )


def aggregate_verify(
    pubs: list[Bls12381PubKey], msgs: list[bytes], agg_sig: bytes
) -> bool:
    """prod_i e(pk_i, H(m_i)) == e(g1, aggsig): n+1 pair-works in one
    shared Miller loop, one final exponentiation."""
    if len(pubs) != len(msgs) or not pubs:
        return False
    if len(agg_sig) != SIGNATURE_SIZE:
        return False
    from cometbft_tpu.crypto import bls_native

    if bls_native.available():
        return bls_native.aggregate_verify(
            [pk.bytes() for pk in pubs],
            [_digest_msg(m) for m in msgs],
            bytes(agg_sig),
        )
    return aggregate_verify_python(pubs, msgs, agg_sig)


def aggregate_verify_python(
    pubs: list[Bls12381PubKey], msgs: list[bytes], agg_sig: bytes
) -> bool:
    """The pure tower-field distinct-message aggregate check — the
    ladder's fallback runner, never the native backend."""
    if len(pubs) != len(msgs) or not pubs:
        return False
    if len(agg_sig) != SIGNATURE_SIZE:
        return False
    try:
        s = g2_from_bytes(agg_sig)
    except ValueError:
        return False
    if s is None:
        return False
    try:
        pairs = [
            (pk._point(), hash_to_g2(_digest_msg(msg)))
            for pk, msg in zip(pubs, msgs)
        ]
    except ValueError:
        return False
    pairs.append((g1_neg(G1_GEN), s))
    return pairing_product_is_one(pairs)


def aggregate_pub_keys_bytes(pub_bytes: list[bytes]) -> bytes:
    """Sum of G1 pubkeys over raw 96-byte encodings, native-accelerated
    when the backend exports it (150 Jacobian adds: ~40 ms native with
    full subgroup validation vs ~350 ms in the tower) — the primitive
    the aggregate-pubkey cache (crypto/bls_dispatch.py) builds entries
    with.  Raises ValueError on malformed/identity inputs or an
    identity sum, matching ``aggregate_pub_keys``."""
    if not pub_bytes:
        raise ValueError("cannot aggregate zero pubkeys")
    from cometbft_tpu.crypto import bls_native

    if bls_native.has_aggregate_pubkeys():
        out = bls_native.aggregate_pubkeys([bytes(p) for p in pub_bytes])
        if out is None:
            raise ValueError("invalid pubkey in aggregation")
        return out
    return aggregate_pub_keys(
        [Bls12381PubKey(p) for p in pub_bytes]
    ).bytes()


def fast_aggregate_verify(
    pubs: list[Bls12381PubKey], msg: bytes, agg_sig: bytes
) -> bool:
    """Same-message aggregate: 2 pair-works total."""
    if not pubs:
        return False
    try:
        agg_pk = aggregate_pub_keys(pubs)
    except ValueError:
        return False
    return agg_pk.verify_signature(msg, agg_sig)


def fast_aggregate_verify_python(
    pubs: list[Bls12381PubKey], msg: bytes, agg_sig: bytes
) -> bool:
    """Same-message aggregate on the pure tower path end to end."""
    if not pubs:
        return False
    try:
        agg_pk = aggregate_pub_keys(pubs)
    except ValueError:
        return False
    return agg_pk.verify_signature_python(msg, agg_sig)


class BlsBatchVerifier:
    """Batch verification of INDEPENDENT (pubkey, msg, sig) triples —
    the BLS side of the crypto.BatchVerifier seam
    (crypto/crypto.go:44; key_bls12381.go has no native batch API, the
    reference verifies serially).  Uses the random-linear-combination
    check
        e(sum z_i s_i, -g2) * prod_i e([z_i] H(m_i), pk_i) == 1
    with fresh 128-bit weights per verify, collapsing n signatures
    into one n+1-pair Miller loop + one final exponentiation (the
    weights ride the cheaper G1 side: [z_i]pk_i).  On failure it
    falls back to per-signature verification so callers still get the
    per-index validity vector."""

    def __init__(self) -> None:
        self._items: list[tuple[Bls12381PubKey, bytes, bytes]] = []

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        if pub_key.type() != KEY_TYPE:
            raise TypeError("BlsBatchVerifier requires bls12_381 keys")
        if len(sig) != SIGNATURE_SIZE:
            raise ValueError("malformed signature size")
        self._items.append((pub_key, bytes(msg), bytes(sig)))

    def __len__(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        n = len(self._items)
        if n == 0:
            return False, []
        from cometbft_tpu.crypto import bls_native

        if bls_native.available():
            weights = [os.urandom(15) + b"\x01" for _ in range(n)]
            ok = bls_native.batch_verify(
                [pk.bytes() for pk, _, _ in self._items],
                [_digest_msg(m) for _, m, _ in self._items],
                [s for _, _, s in self._items],
                weights,
            )
            if ok:
                return True, [True] * n
            results = [
                pk.verify_signature(msg, sig)
                for pk, msg, sig in self._items
            ]
            return all(results), results
        if batch_verify_rlc_python(self._items):
            return True, [True] * n
        results = [
            pk.verify_signature(msg, sig) for pk, msg, sig in self._items
        ]
        return all(results), results


def batch_verify_rlc_python(
    items: list[tuple[Bls12381PubKey, bytes, bytes]],
) -> bool:
    """The pure tower-field random-linear-combination batch check
    (BlsBatchVerifier docstring equation): one n+1-pair Miller loop +
    one final exponentiation, fresh 128-bit weights per call.  False
    means "some signature is invalid OR malformed" — callers wanting
    the per-index vector re-verify serially."""
    if not items:
        return False
    F2 = _Fq2Ops
    try:
        weights = [
            int.from_bytes(os.urandom(16), "big") | 1
            for _ in range(len(items))
        ]
        sig_acc = (F2.one, F2.one, F2.zero)
        pairs = []
        for (pk, msg, sig), z in zip(items, weights):
            s = g2_from_bytes(sig)
            if s is None:
                raise ValueError("identity signature")
            sig_acc = _jac_add(
                F2, sig_acc, _jac_mul(F2, _jac_from_affine(F2, s), z)
            )
            pairs.append(
                (
                    g1_mul(pk._point(), z),
                    hash_to_g2(_digest_msg(msg)),
                )
            )
        pairs.append((g1_neg(G1_GEN), _jac_to_affine(F2, sig_acc)))
        return pairing_product_is_one(pairs)
    except ValueError:
        return False


__all__ = [
    "Bls12381PrivKey",
    "Bls12381PubKey",
    "BlsBatchVerifier",
    "KEY_TYPE",
    "PRIV_KEY_SIZE",
    "PUB_KEY_SIZE",
    "SIGNATURE_SIZE",
    "MAX_MSG_LEN",
    "aggregate_pub_keys",
    "aggregate_pub_keys_bytes",
    "aggregate_signatures",
    "aggregate_verify",
    "aggregate_verify_python",
    "batch_verify_rlc_python",
    "fast_aggregate_verify",
    "fast_aggregate_verify_python",
    "gen_priv_key",
    "hash_to_g2",
    "pairing",
    "pairing_product_is_one",
    "priv_key_from_secret",
]
