"""RFC 9380 hash-to-curve onto the BLS12-381 G2 group.

Implements expand_message_xmd(SHA-256), hash_to_field over Fq2,
the simplified-SWU map onto the 3-isogenous curve
E'': y^2 = x^3 + 240*I*x + 1012*(1+I)  (Z = -(2+I)), the degree-3
isogeny to the twist E': y^2 = x^3 + 4*(1+I), and
endomorphism-accelerated cofactor clearing.

The isogeny coefficient tables are NOT pasted from the RFC appendix —
they are derived from first principles by tools/derive_g2_isogeny.py
(division-polynomial root -> Velu's formulas -> isomorphism scaling),
which also re-checks the map is a homomorphism landing on E'.  The
one degree of freedom a published test vector would pin down is the
sign of the final isomorphism (s = +1/3 vs -1/3, i.e. composition
with point negation); we fix s = +1/3.

Cofactor clearing uses the psi-endomorphism decomposition
    h_eff * Q  =  [x^2-x-1]Q + [x-1]psi(Q) + psi^2(2Q)
(Budroni-Pintore, "Efficient hash maps to G2 on BLS curves"; RFC 9380
appendix G.4 blesses this as equivalent to the suite's h_eff).

The DST is the reference's literal signing domain tag
(crypto/bls12381/key_bls12381.go:27): note the reference signs min-PK
(pubkeys in G1, signatures in G2) while reusing blst's G1-named NUL
tag — we replicate that byte-for-byte for signature compatibility.
"""

from __future__ import annotations

import hashlib

from cometbft_tpu.crypto.bls12381 import (
    BLS_X,
    F2_ZERO,
    P,
    _Fq2Ops,
    _jac_add,
    _jac_dbl,
    _jac_from_affine,
    _jac_mul,
    _jac_to_affine,
    f2_add,
    f2_inv,
    f2_mul,
    f2_mul_scalar,
    f2_neg,
    f2_sq,
    f2_sqrt,
    f2_sub,
    g2_psi,
)

DST = b"BLS_SIG_BLS12381G1_XMD:SHA-256_SSWU_RO_NUL_"

_A = (0, 240)
_B = (1012, 1012)
_Z = ((-2) % P, (-1) % P)
_L = 64  # ceil((ceil(log2(p)) + k) / 8) with k = 128

# Degree-3 isogeny E'' -> E', derived by tools/derive_g2_isogeny.py.
ISO3_XNUM = (
    (0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    (0x0, 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E, 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1, 0x0),
)
ISO3_XDEN = (
    (0x0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (0x1, 0x0),
)
#
# Sign convention note (round-4 fix): a Vélu derivation determines the
# isogeny only up to composition with [-1]; the tool originally emitted
# the negated y-map, which passes every on-curve/subgroup property test
# while making every produced point (hence every signature) the
# NEGATION of what RFC 9380 (and blst, i.e. reference nodes) compute.
# Anchored now to the RFC 9380 appendix J.10.1 known-answer vectors
# (tests/test_bls.py::test_hash_to_g2_rfc9380_j10_vectors).
ISO3_YNUM = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706, 0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0x0, 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C, 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10, 0x0),
)
ISO3_YDEN = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0x0, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12, 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (0x1, 0x0),
)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 section 5.3.1 with H = SHA-256 (b=32, r=64 bytes)."""
    h = hashlib.sha256
    ell = (len_in_bytes + 31) // 32
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds")
    dst_prime = dst + bytes([len(dst)])
    b0 = h(
        b"\x00" * 64
        + msg
        + len_in_bytes.to_bytes(2, "big")
        + b"\x00"
        + dst_prime
    ).digest()
    bi = h(b0 + b"\x01" + dst_prime).digest()
    out = [bi]
    for i in range(2, ell + 1):
        bi = h(bytes(a ^ b for a, b in zip(b0, bi)) + bytes([i]) + dst_prime).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int) -> list:
    """RFC 9380 section 5.2: count elements of Fq2, m=2, L=64."""
    data = expand_message_xmd(msg, DST, count * 2 * _L)
    out = []
    for i in range(count):
        c0 = int.from_bytes(data[2 * i * _L : (2 * i + 1) * _L], "big") % P
        c1 = int.from_bytes(data[(2 * i + 1) * _L : (2 * i + 2) * _L], "big") % P
        out.append((c0, c1))
    return out


def _sgn0(x) -> int:
    """RFC 9380 sgn0 for m=2: parity of the first nonzero coordinate."""
    if x[0] % 2 == 1:
        return 1
    if x[0] == 0:
        return x[1] % 2
    return 0


def _is_square(a) -> bool:
    """Legendre via the norm: a square in Fq2 iff N(a)^((p-1)/2) != -1."""
    n = (a[0] * a[0] + a[1] * a[1]) % P
    return pow(n, (P - 1) // 2, P) != P - 1


def map_to_curve_sswu(u):
    """Simplified SWU (RFC 9380 section 6.6.2) onto E''."""
    u2 = f2_sq(u)
    zu2 = f2_mul(_Z, u2)
    tv1 = f2_add(f2_sq(zu2), zu2)
    neg_b_over_a = f2_mul(f2_neg(_B), f2_inv(_A))
    if tv1 == F2_ZERO:
        x1 = f2_mul(_B, f2_inv(f2_mul(_Z, _A)))
    else:
        x1 = f2_mul(neg_b_over_a, f2_add((1, 0), f2_inv(tv1)))
    gx1 = f2_add(f2_add(f2_mul(f2_sq(x1), x1), f2_mul(_A, x1)), _B)
    if _is_square(gx1):
        x, y = x1, f2_sqrt(gx1)
    else:
        x2 = f2_mul(zu2, x1)
        gx2 = f2_add(f2_add(f2_mul(f2_sq(x2), x2), f2_mul(_A, x2)), _B)
        x, y = x2, f2_sqrt(gx2)
    if _sgn0(u) != _sgn0(y):
        y = f2_neg(y)
    return (x, y)


def _eval_poly(coeffs, x):
    acc = F2_ZERO
    for c in reversed(coeffs):
        acc = f2_add(f2_mul(acc, x), c)
    return acc


def iso3_map(pt):
    """Degree-3 isogeny E'' -> E' (None on the kernel)."""
    if pt is None:
        return None
    x, y = pt
    xden = _eval_poly(ISO3_XDEN, x)
    if xden == F2_ZERO:
        return None
    xo = f2_mul(_eval_poly(ISO3_XNUM, x), f2_inv(xden))
    yo = f2_mul(
        y, f2_mul(_eval_poly(ISO3_YNUM, x), f2_inv(_eval_poly(ISO3_YDEN, x)))
    )
    return (xo, yo)


def clear_cofactor(pt):
    """[h_eff]Q via [x^2-x-1]Q + [x-1]psi(Q) + psi^2(2Q) (x < 0)."""
    if pt is None:
        return None
    F = _Fq2Ops
    x = -BLS_X
    j = _jac_from_affine(F, pt)
    t1 = _jac_mul(F, j, x * x - x - 1)
    psi_q = g2_psi(pt)
    t2 = _jac_mul(F, _jac_from_affine(F, psi_q), x - 1)
    two_q = _jac_to_affine(F, _jac_dbl(F, j))
    t3 = _jac_from_affine(F, g2_psi(g2_psi(two_q)))
    return _jac_to_affine(F, _jac_add(F, _jac_add(F, t1, t2), t3))


def hash_to_g2(msg: bytes):
    """Full RFC 9380 hash_to_curve: two field elements, two SSWU+iso
    maps, point addition on E', cofactor clearing."""
    u0, u1 = hash_to_field_fq2(msg, 2)
    q0 = iso3_map(map_to_curve_sswu(u0))
    q1 = iso3_map(map_to_curve_sswu(u1))
    F = _Fq2Ops
    r = _jac_to_affine(
        F, _jac_add(F, _jac_from_affine(F, q0), _jac_from_affine(F, q1))
    )
    return clear_cofactor(r)


__all__ = [
    "DST",
    "clear_cofactor",
    "expand_message_xmd",
    "hash_to_field_fq2",
    "hash_to_g2",
    "iso3_map",
    "map_to_curve_sswu",
]
