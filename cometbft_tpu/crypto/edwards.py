"""Pure-Python edwards25519 arithmetic — the correctness oracle.

This module is the semantic ground truth for the TPU batch-verify kernel
(cometbft_tpu/ops): the kernel's precomputed tables are generated from it
and its verify() defines the accept/reject behavior the kernel must match
bit-for-bit (differential fuzzing in tests/test_ops_kernel.py).

Semantics: **ZIP-215** (matching the reference's curve25519-voi-backed
verifier, crypto/ed25519/ed25519.go:39):
  1. A (pubkey) and R (sig[0:32]) decode per RFC 8032 §5.1.3 *without*
     the canonical-y check — encodings with y >= p are accepted, and
     x=0-with-sign-bit ("-0") is accepted.
  2. S (sig[32:64]) must be canonical: S < L.
  3. Accept iff [8][S]B == [8]R + [8][k]A (cofactored equation),
     k = SHA-512(R || A || M) mod L.

All group ops use extended twisted Edwards coordinates (X:Y:Z:T) with
a=-1 ("Twisted Edwards Curves Revisited", Hisil et al. 2008).
"""

from __future__ import annotations

import hashlib

# Field and group parameters (RFC 8032 §5.1).
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Base point B (RFC 8032): y = 4/5, x recovered with even... positive sign.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """x with x^2 = (y^2-1)/(d*y^2+1), lsb matching ``sign``; None if the
    quotient is not a square. Accepts x=0 with sign=1 (ZIP-215 "-0")."""
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root of u/v: x = u*v^3 * (u*v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P), (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u % P:
        pass
    elif vxx == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x & 1 != sign:
        x = (P - x) % P
    return x


_BX = _recover_x(_BY, 0)
assert _BX is not None

# Extended coordinates point: (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
Point = tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)
B_POINT: Point = (_BX, _BY, 1, (_BX * _BY) % P)


def pt_add(p: Point, q: Point) -> Point:
    """Unified addition, add-2008-hwcd-3 (complete for a=-1, k=2d)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (2 * t1 * D % P) * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = (b - a) % P, (dd - c) % P, (dd + c) % P, (b + a) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_double(p: Point) -> Point:
    """Doubling, dbl-2008-hwcd."""
    x1, y1, z1, _ = p
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_neg(p: Point) -> Point:
    x, y, z, t = p
    return ((P - x) % P, y, z, (P - t) % P)


def pt_mul(k: int, p: Point) -> Point:
    """Scalar multiplication (double-and-add, MSB first)."""
    q = IDENTITY
    for i in reversed(range(k.bit_length())):
        q = pt_double(q)
        if (k >> i) & 1:
            q = pt_add(q, p)
    return q


def pt_equal(p: Point, q: Point) -> bool:
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def pt_is_identity(p: Point) -> bool:
    x, y, z, _ = p
    return x % P == 0 and (y - z) % P == 0


def pt_to_affine(p: Point) -> tuple[int, int]:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    return (x * zi % P, y * zi % P)


def encode_point(p: Point) -> bytes:
    x, y = pt_to_affine(p)
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def decode_point(s: bytes) -> Point | None:
    """ZIP-215 decoding: non-canonical y accepted (reduced mod p)."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = (enc & ((1 << 255) - 1)) % P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, (x * y) % P)


def decode_point_rfc8032(s: bytes) -> Point | None:
    """Strict RFC 8032 decoding (canonical y, reject -0). Kept for tests
    contrasting ZIP-215 with the strict rules."""
    if len(s) != 32:
        return None
    enc = int.from_bytes(s, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    x = _recover_x(y, sign)
    if x is None:
        return None
    if x == 0 and sign == 1:
        return None
    return (x, y, 1, (x * y) % P)


# -- Ed25519 sign/verify (oracle) -------------------------------------


def _sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    """RFC 8032 §5.1.5: clamped scalar + hash prefix from a 32-byte seed."""
    if len(seed) != 32:
        raise ValueError("ed25519 seed must be 32 bytes")
    h = _sha512(seed)
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return encode_point(pt_mul(a, B_POINT))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 §5.1.6 deterministic signing."""
    a, prefix = secret_expand(seed)
    pub = encode_point(pt_mul(a, B_POINT))
    r = int.from_bytes(_sha512(prefix + msg), "little") % L
    r_enc = encode_point(pt_mul(r, B_POINT))
    k = int.from_bytes(_sha512(r_enc + pub + msg), "little") % L
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """The oracle verifier: ZIP-215 semantics, cofactored equation."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    a_pt = decode_point(pub)
    r_pt = decode_point(sig[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = int.from_bytes(_sha512(sig[:32] + pub + msg), "little") % L
    # [8]([S]B - R - [k]A) == identity
    q = pt_add(pt_mul(s, B_POINT), pt_neg(pt_add(r_pt, pt_mul(k, a_pt))))
    for _ in range(3):
        q = pt_double(q)
    return pt_is_identity(q)


# -- Torsion points (for edge-case tests & differential fuzzing) -------

def small_order_points() -> list[bytes]:
    """Canonical encodings of the 8 small-order (torsion) points.

    Derived by projecting curve points into the torsion subgroup with
    [L]Q — every point's L-multiple has order dividing the cofactor 8.
    """
    for y in range(2, 1000):
        x = _recover_x(y % P, 0)
        if x is None:
            continue
        tor = pt_mul(L, (x, y % P, 1, x * y % P))
        # order-8 generator iff [4]tor is not the identity
        if not pt_is_identity(pt_mul(4, tor)):
            out, cur = [], IDENTITY
            for _ in range(8):
                out.append(encode_point(cur))
                cur = pt_add(cur, tor)
            return out
    raise AssertionError("torsion enumeration failed")
