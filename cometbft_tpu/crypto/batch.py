"""Batch-verifier dispatch by key type (reference: crypto/batch/batch.go:10).

``create_batch_verifier`` returns the best available backend for a key
type: the TPU (JAX/XLA) batch kernel when a device is usable, else the
CPU fallback. The selection is behind this single seam so every caller
(VerifyCommit, light client, blocksync replay, consensus addVote) gets
the device path for free.

This file sits in tools/jitcheck.py's host-sync scan scope (with
ops/ and parallel/): any np.asarray / .item() / device fetch added on
the dispatch path must carry an audited ``# host sync:`` waiver
(docs/device_contracts.md) — today it has none, by design: all device
I/O lives behind the verifier seams it selects.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable

from cometbft_tpu.crypto import BatchVerifier, PubKey
from cometbft_tpu.crypto import ed25519 as _ed
from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.env import flag_from_env, float_from_env

# Device availability is probed in a SUBPROCESS: a wedged accelerator
# plugin can hang `import jax` inside C where the GIL never releases —
# observed to freeze every thread in the node (consensus froze 50 s
# mid-round), so neither the caller's thread NOR a helper thread may
# perform the first import.  Until a probe subprocess proves the
# device usable, callers get the CPU verifier immediately — consensus
# liveness beats batch speed.  When jax is already imported (tests,
# benches, the dryrun), the inline fast path keeps selection
# deterministic.  A failed probe retries after _PROBE_RETRY_S.
_probe_lock = cmtsync.Mutex()
_device_state = {"status": "unknown", "ndev": 0, "failed_at": 0.0}
_PROBE_TIMEOUT_S = float_from_env("CMT_TPU_PROBE_TIMEOUT_S", 20.0, minimum=0.001)
_PROBE_RETRY_S = float_from_env("CMT_TPU_PROBE_RETRY_S", 120.0, minimum=0.001)


def _probe_subprocess() -> None:
    import time

    from cometbft_tpu.utils.device_env import probe_device_count

    # pipe-safe, process-group-killed probe (device_env docstring): a
    # wedged tunnel must cost _PROBE_TIMEOUT_S, never a parent hang
    ndev = probe_device_count(_PROBE_TIMEOUT_S)
    if ndev > 0:
        # the tunnel answers; the in-process import should now be
        # quick (and runs on THIS daemon thread, not a node thread)
        try:
            import jax

            _device_state["ndev"] = len(jax.devices())
            _device_state["status"] = "ready"
            return
        except Exception:
            pass
    _device_state["failed_at"] = time.monotonic()
    _device_state["status"] = "failed"


def _jax_backends_initialized() -> bool:
    """True only when some jax backend has ALREADY initialized in this
    process — merely having `jax` in sys.modules proves nothing (device
    plugins' sitecustomize imports jax at interpreter startup, and the
    HANG lives in the first backend init, i.e. the first
    jax.devices() call, not the import)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:
        return False


def _device_ndev() -> int:
    """Visible device count: 0 while unknown/probing/failed."""
    import time

    st = _device_state["status"]
    if st == "ready":
        return _device_state["ndev"]
    if st == "probing":
        return 0
    if st == "failed" and (
        time.monotonic() - _device_state["failed_at"] < _PROBE_RETRY_S
    ):
        return 0
    with _probe_lock:
        st = _device_state["status"]
        if st == "ready":
            return _device_state["ndev"]
        if st == "probing":
            return 0
        if _jax_backends_initialized():
            # a backend is live in-process: devices() is a cheap read
            try:
                import jax

                _device_state["ndev"] = len(jax.devices())
                _device_state["status"] = "ready"
                return _device_state["ndev"]
            except Exception:
                _device_state["failed_at"] = time.monotonic()
                _device_state["status"] = "failed"
                return 0
        _device_state["status"] = "probing"
        threading.Thread(
            target=_probe_subprocess, daemon=True, name="device-probe"
        ).start()
        return 0


def device_status() -> dict:
    """Read-only snapshot of the device probe state machine for the
    health plane (/debug/perf): {"status": unknown | probing | ready |
    failed, "ndev": visible device count}.  Never triggers a probe —
    the health surfaces must be safe to scrape while the tunnel is
    wedged (the whole point of the plane)."""
    return {
        "status": _device_state["status"],
        "ndev": _device_state["ndev"],
    }


def _ed25519_factory() -> BatchVerifier:
    # Routing decisions that end at the host verifier are recorded
    # here, where they are made; a device-capable verifier defers its
    # decision to batch time (TpuBatchVerifier.plan — it may still
    # fall back on batch size / calibration / ladder demotion).  Tier
    # ACCOUNTING is uniform either way: every verifier this factory
    # returns records crypto_dispatch_tier per BATCH at the ladder's
    # decision point (dispatch.LADDER.note_batch — host-only routes
    # via LadderHostVerifier.verify, device routes via
    # TpuBatchVerifier.execute), so counts are comparable across
    # tiers instead of mixing factory-time and batch-time samples.
    from cometbft_tpu.crypto.dispatch import LadderHostVerifier

    if flag_from_env("CMT_TPU_DISABLE_DEVICE_VERIFY"):
        _crypto_metrics().dispatch_decisions.labels(
            route="host", reason="disabled"
        ).inc()
        return LadderHostVerifier()
    try:
        ndev = _device_ndev()
        if ndev == 0:
            _crypto_metrics().dispatch_decisions.labels(
                route="host", reason="device_unavailable"
            ).inc()
            return LadderHostVerifier()
        if ndev > 1 and not flag_from_env("CMT_TPU_DISABLE_MESH_VERIFY"):
            # multi-chip: shard the batch over a 1-D mesh — every
            # caller of this seam scales across chips transparently
            from cometbft_tpu.parallel.mesh import ShardedTpuBatchVerifier

            return ShardedTpuBatchVerifier()
        from cometbft_tpu.ops.ed25519_verify import TpuBatchVerifier

        return TpuBatchVerifier()
    except Exception:
        _crypto_metrics().dispatch_decisions.labels(
            route="host", reason="device_unavailable"
        ).inc()
        return LadderHostVerifier()


def _bls_factory() -> BatchVerifier:
    # ladder-routed since ISSUE 13: bls_native -> host RLC -> python
    # floor with demotion/watchdog/chaos/accounting inherited — the
    # bare BlsBatchVerifier this used to hand out verified the same
    # math but was invisible to crypto_dispatch_tier and kept running
    # a faulting native library forever
    from cometbft_tpu.crypto.bls_dispatch import BlsLadderVerifier

    return BlsLadderVerifier()


REGISTRY: dict[str, Callable[[], BatchVerifier]] = {
    _ed.KEY_TYPE: _ed25519_factory,
    "bls12_381": _bls_factory,
}


def create_batch_verifier(pub_key: PubKey) -> BatchVerifier:
    """(batch.go:10 CreateBatchVerifier) — raises KeyError for key types
    without a batch implementation; callers fall back to single verify."""
    return REGISTRY[pub_key.type()]()


def supports_batch_verifier(pub_key: PubKey | None) -> bool:
    return pub_key is not None and pub_key.type() in REGISTRY
