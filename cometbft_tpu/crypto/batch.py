"""Batch-verifier dispatch by key type (reference: crypto/batch/batch.go:10).

``create_batch_verifier`` returns the best available backend for a key
type: the TPU (JAX/XLA) batch kernel when a device is usable, else the
CPU fallback. The selection is behind this single seam so every caller
(VerifyCommit, light client, blocksync replay, consensus addVote) gets
the device path for free.
"""

from __future__ import annotations

import os
from typing import Callable

from cometbft_tpu.crypto import BatchVerifier, PubKey
from cometbft_tpu.crypto import ed25519 as _ed


def _ed25519_factory() -> BatchVerifier:
    if os.environ.get("CMT_TPU_DISABLE_DEVICE_VERIFY"):
        return _ed.CpuBatchVerifier()
    try:
        import jax

        if (
            len(jax.devices()) > 1
            and not os.environ.get("CMT_TPU_DISABLE_MESH_VERIFY")
        ):
            # multi-chip: shard the batch over a 1-D mesh — every
            # caller of this seam scales across chips transparently
            from cometbft_tpu.parallel.mesh import ShardedTpuBatchVerifier

            return ShardedTpuBatchVerifier()
        from cometbft_tpu.ops.ed25519_verify import TpuBatchVerifier

        return TpuBatchVerifier()
    except Exception:
        return _ed.CpuBatchVerifier()


def _bls_factory() -> BatchVerifier:
    from cometbft_tpu.crypto import bls12381 as _bls

    return _bls.BlsBatchVerifier()


REGISTRY: dict[str, Callable[[], BatchVerifier]] = {
    _ed.KEY_TYPE: _ed25519_factory,
    "bls12_381": _bls_factory,
}


def create_batch_verifier(pub_key: PubKey) -> BatchVerifier:
    """(batch.go:10 CreateBatchVerifier) — raises KeyError for key types
    without a batch implementation; callers fall back to single verify."""
    return REGISTRY[pub_key.type()]()


def supports_batch_verifier(pub_key: PubKey | None) -> bool:
    return pub_key is not None and pub_key.type() in REGISTRY
