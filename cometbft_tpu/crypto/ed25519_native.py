"""ctypes binding for the native Ed25519 RLC batch verifier
(native/crypto/ed25519_batch.cpp).

One C call checks a whole batch with a random-linear-combination
equation — the host-side analog of the TPU kernel's batched math and
of the reference's ed25519consensus batch verifier
(crypto/ed25519/batch.go). Python computes all SCALARS with big-int
arithmetic (SHA-512 challenges, random 128-bit weights, mod-L
products); C++ does only curve work (ZIP-215 decompression, one
Pippenger MSM, cofactor-8 identity check) with point formulas
mirroring the pure-Python oracle.

Build-on-demand via utils/native_build (same as the frame pump, BLS,
cometkv). Disable with CMT_TPU_NO_NATIVE_ED25519=1.
"""

from __future__ import annotations

import ctypes
import hashlib
import os

from cometbft_tpu.crypto.edwards import B_POINT, L, encode_point
from cometbft_tpu.utils.native_build import NativeLib

_B_ENC = encode_point(B_POINT)


def _configure(lib: ctypes.CDLL) -> None:
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.cmt_ed25519_rlc_verify.restype = ctypes.c_long
    lib.cmt_ed25519_rlc_verify.argtypes = [
        ctypes.c_char_p, i32p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_long, ctypes.c_long,
    ]
    lib.cmt_ed25519_backend.restype = ctypes.c_int
    lib.cmt_ed25519_backend.argtypes = []


_LIB = NativeLib(
    src_rel="native/crypto/ed25519_batch.cpp",
    out_name="libcmted25519.so",
    disable_env="CMT_TPU_NO_NATIVE_ED25519",
    configure=_configure,
)


def load() -> ctypes.CDLL | None:
    """The native library, or None (disabled / no toolchain)."""
    return _LIB.load()


def rlc_verify(
    lib: ctypes.CDLL,
    entries: list[tuple[bytes, bytes, bytes]],
) -> bool | None:
    """One RLC check over ``entries`` = [(pub32, msg, sig64), ...].

    Returns True (every signature valid), False (at least one invalid
    OR a malformed scalar/point — caller re-verifies individually), or
    None when the batch could not run at all. Entries must already
    have sig length 64.

    The equation (edwards.verify_zip215 batched):
      [8]([c]B + sum[z_i](-R_i) + sum[(z_i k_i) mod L](-A_i)) == id
    with c = sum z_i s_i mod L and independent random 128-bit z_i —
    a forged signature survives with probability ~2^-128.
    """
    n = len(entries)
    if n == 0:
        return None

    # unique-key table: commits verify many sigs under few keys, so
    # the C side decompresses each key once
    key_ids: dict[bytes, int] = {}
    idx = (ctypes.c_int32 * n)()
    rs = bytearray()
    za = bytearray()
    zr = bytearray()
    c_acc = 0
    for i, (pub, msg, sig) in enumerate(entries):
        s = int.from_bytes(sig[32:], "little")
        if s >= L:
            return False  # oracle rejects; per-sig path reports lanes
        idx[i] = key_ids.setdefault(pub, len(key_ids))
        rs += sig[:32]
        k = int.from_bytes(
            hashlib.sha512(sig[:32] + pub + msg).digest(), "little"
        ) % L
        z = int.from_bytes(os.urandom(16), "little") | 1
        za += (z * k % L).to_bytes(32, "little")
        zr += z.to_bytes(32, "little")
        c_acc = (c_acc + z * s) % L
    upubs = b"".join(key_ids)  # dict preserves insertion order
    from cometbft_tpu.metrics import crypto_metrics as _cm

    _cm().batch_verify_launches.labels(kernel="host_rlc").inc()
    rc = lib.cmt_ed25519_rlc_verify(
        upubs, idx, bytes(rs), _B_ENC, bytes(za), bytes(zr),
        c_acc.to_bytes(32, "little"), len(key_ids), n,
    )
    if rc == 1:
        return True
    if rc == 0:
        return False
    # a point failed to decode (rc < 0): the oracle path will return
    # False for those lanes — treat like a failed batch
    return False
