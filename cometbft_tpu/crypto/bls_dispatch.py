"""BLS12-381 verification through the failover dispatch ladder.

"Performance of EdDSA and BLS Signatures in Committee-Based Consensus"
(arXiv:2302.00418) quantifies the trade this module closes: a commit
carrying a BLS *aggregate* signature verifies with one pairing-product
check — e(agg_pk, H(m)) == e(g1, agg_sig), two pair-works, one final
exponentiation — where the same commit as N independent signatures
costs an N-signature batch.  Until this module, the BLS plane sat
OUTSIDE the dispatch ladder: ``crypto/batch.py`` handed out a bare
``BlsBatchVerifier`` whose native-vs-python selection was an
unaccounted ``if available()`` with no demotion when the ctypes
library faults, no ``crypto_dispatch_tier`` sample, no watchdog, no
chaos coverage.

:class:`BlsLadderVerifier` gives BLS the exact seam the ed25519 plane
has had since PR 8/9 — ``plan()`` computes the batch's eligible tiers
and filters them through ``dispatch.LADDER.admissible()``;
``execute()`` walks them top-down with typed ``TierFault`` escalation:

- ``bls_native`` — the C++ pairing backend (crypto/bls_native.py):
  RLC batch check for independent triples, one pairing-product for
  aggregates.  Runs under the LaunchWatchdog and inside the chaos
  injection scope (``dispatch.CHAOS_TIERS``), and a fault demotes it
  through the same cool-down/half-open/probe state machine as a lost
  device.
- ``host`` — the pure tower-field RLC batch (one shared Miller loop;
  batch mode only).
- ``python`` — the floor: per-signature (batch mode) or one
  pure-python pairing-product (aggregate mode).  Never demoted,
  never faulted; re-raises, exactly like the ed25519 floor.

Every batch lands in ``crypto_dispatch_tier{tier}`` via
``LADDER.note_batch`` — the one per-batch accounting point — so BLS
verifies are no longer invisible to ``/debug/dispatch``.

**Aggregate-pubkey cache.**  Same-message aggregate verification
needs the G1 sum of the signers' pubkeys.  Validator sets are stable
across many commits, so the sum is cached in a bounded LRU keyed by
SHA-256 over the concatenated pubkeys: a warm serving plane pays ONE
pairing-product per commit and zero EC aggregation (cold native
aggregation ~40 ms at 150 keys, python ~350 ms — the cache is what
makes the ``bls_aggregate_150val`` ledger row beat the ed25519
``verify_commit_150`` batch baseline).
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict

from cometbft_tpu.crypto import BatchVerifier
from cometbft_tpu.crypto import bls12381 as _bls
from cometbft_tpu.crypto import bls_native
from cometbft_tpu.crypto import dispatch as _failover
from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import ring_size_from_env as _int_env
from cometbft_tpu.utils.trace import TRACER as _tracer

#: the BLS family's top ladder rung (dispatch.TIER_ORDER)
BLS_NATIVE_TIER = "bls_native"

DEFAULT_AGG_PK_CACHE_CAP = 1024


def agg_pk_cache_capacity_from_env() -> int:
    """Aggregate-pubkey cache capacity in entries (>= 16); each entry
    is one (validator-set, signer-subset) pair's 96-byte G1 sum."""
    return _int_env("CMT_TPU_BLS_AGG_PK_CACHE", DEFAULT_AGG_PK_CACHE_CAP, 16)


@cmtsync.guarded
class AggPubKeyCache:
    """Bounded LRU of SHA-256(pk_0 || ... || pk_n-1) -> 96-byte G1
    pubkey sum.  Pure EC facts — a sum of points never goes stale — so
    capacity is the only eviction policy.  The key binds the exact
    ordered signer list, so two different signer subsets of one
    validator set never share an entry."""

    _GUARDED_BY = {"_map": "_mtx"}

    def __init__(self, capacity: int | None = None) -> None:
        self.capacity = (
            capacity if capacity is not None
            else agg_pk_cache_capacity_from_env()
        )
        self._mtx = cmtsync.Mutex()
        self._map: OrderedDict[bytes, bytes] = OrderedDict()

    def aggregate(self, pub_bytes: list[bytes]) -> bytes:
        """The cached G1 sum for this exact signer list, computing and
        memoizing on miss (native-accelerated when the backend exports
        ``cmt_bls_aggregate_pubkeys``).  Raises ValueError on
        malformed/identity inputs, which is never cached."""
        key = hashlib.sha256(b"".join(pub_bytes)).digest()
        with self._mtx:
            hit = self._map.get(key)
            if hit is not None:
                self._map.move_to_end(key)
                return hit
        agg = _bls.aggregate_pub_keys_bytes(pub_bytes)
        with self._mtx:
            self._map[key] = agg
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)
        return agg

    def __len__(self) -> int:
        with self._mtx:
            return len(self._map)

    def clear(self) -> None:
        with self._mtx:
            self._map.clear()


#: process-wide singleton — every BLS aggregate verification shares
#: the one pubkey-sum cache (mirrors dispatch.LADDER / health.WATCHDOG)
AGG_PK_CACHE = AggPubKeyCache()


class _BlsPlan:
    """Host-phase output of :meth:`BlsLadderVerifier.plan`: the
    routing decision plus everything ``execute()`` needs — mirrors
    ops/ed25519_verify._VerifyPlan so the verify queue's collector can
    run it off-thread."""

    __slots__ = (
        "n", "mode", "tiers", "items", "agg_pubs", "agg_msgs",
        "agg_sig", "same_msg", "t_plan",
    )

    def __init__(self) -> None:
        self.n = 0
        self.mode = "empty"  # empty | batch | aggregate
        self.tiers: list[str] = []
        self.items: list[tuple] = []
        self.agg_pubs: list = []
        self.agg_msgs: list[bytes] = []
        self.agg_sig = b""
        self.same_msg = False
        self.t_plan = 0.0


class BlsLadderVerifier(BatchVerifier):
    """BatchVerifier provider for bls12_381 keys, dispatch-ladder
    routed (module docstring).  Two modes:

    - **batch** (``add()`` triples): independent (pubkey, msg, sig)
      verification — RLC on the native/host tiers, per-signature
      verdicts on the floor.
    - **aggregate** (``set_aggregate()``): ONE aggregate signature
      over the signer list — the commit shape
      ``types/validation._verify`` selects when the commit actually
      carries ``agg_signature``.  All-or-nothing verdict.
    """

    def __init__(self) -> None:
        self._items: list[tuple] = []
        self._agg: tuple[list, list[bytes], bytes, bool] | None = None
        # ladder tier the last batch ACTUALLY ran on (ed25519 parity)
        self._last_tier: str | None = None

    def add(self, pub_key, msg: bytes, sig: bytes) -> None:
        if pub_key.type() != _bls.KEY_TYPE:
            raise TypeError("BlsLadderVerifier requires bls12_381 keys")
        if len(sig) != _bls.SIGNATURE_SIZE:
            raise ValueError("malformed signature size")
        if self._agg is not None:
            raise ValueError("verifier is in aggregate mode")
        self._items.append((pub_key, bytes(msg), bytes(sig)))

    def set_aggregate(
        self, pub_keys: list, msgs, agg_sig: bytes
    ) -> None:
        """Aggregate mode: ``msgs`` is ONE bytes (same-message fast
        aggregate — the aggregate-commit shape) or a list of per-signer
        messages (distinct-message aggregate)."""
        if self._items:
            raise ValueError("verifier already has batch items")
        if len(agg_sig) != _bls.SIGNATURE_SIZE:
            raise ValueError("malformed aggregate signature size")
        if not pub_keys:
            raise ValueError("aggregate needs at least one signer")
        for pk in pub_keys:
            if pk.type() != _bls.KEY_TYPE:
                raise TypeError(
                    "BlsLadderVerifier requires bls12_381 keys"
                )
        same = isinstance(msgs, (bytes, bytearray))
        msg_list = (
            [bytes(msgs)] if same else [bytes(m) for m in msgs]
        )
        if not same and len(msg_list) != len(pub_keys):
            raise ValueError("one message per signer required")
        self._agg = (list(pub_keys), msg_list, bytes(agg_sig), same)

    def __len__(self) -> int:
        if self._agg is not None:
            return len(self._agg[0])
        return len(self._items)

    # -- the plan()/execute() seam ---------------------------------------

    def plan(self) -> _BlsPlan:
        """Host phase: ladder tier selection.  Eligibility is a pure
        capability check — the native tier exists only when the C++
        backend loads (never triggered here: a cold process must not
        pay the first-use g++ build on the plan path unless a verify
        is actually about to need it, which it is)."""
        plan = _BlsPlan()
        plan.t_plan = time.perf_counter()
        if self._agg is not None:
            plan.mode = "aggregate"
            plan.agg_pubs, plan.agg_msgs, plan.agg_sig, plan.same_msg = (
                self._agg
            )
            plan.n = len(plan.agg_pubs)
        elif self._items:
            plan.mode = "batch"
            plan.items = self._items
            plan.n = len(self._items)
        else:
            return plan
        ladder = _failover.LADDER
        eligible = (
            [BLS_NATIVE_TIER] if bls_native.available() else []
        )
        admissible = ladder.admissible(eligible)
        _crypto_metrics().dispatch_decisions.labels(
            route="bls", reason=plan.mode
        ).inc()
        # cost-ordered walk (ISSUE 14): the BLS tiers self-place
        # through the SAME shape-bucket cost model the device tiers
        # use — zero BLS-specific routing code.  Aggregates offer no
        # host rung (host == python for a pairing-product), so only
        # the admissible native tier is ordered; batch mode orders
        # native against the pure-RLC host rung by measured
        # throughput for this batch's shape.
        if plan.mode == "aggregate":
            walk = ladder.route(
                admissible, plan.n, add_host=False,
                family=_failover.ROUTE_FAMILY_BLS_AGG,
            )
            if not walk:
                # floor-only plan: still one dispatch_route sample
                ladder.note_route(_failover.FLOOR_TIER, plan.n)
            plan.tiers = walk + [_failover.FLOOR_TIER]
        else:
            plan.tiers = ladder.route(
                admissible, plan.n,
                family=_failover.ROUTE_FAMILY_BLS,
            ) + [_failover.FLOOR_TIER]
        return plan

    def execute(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        """Walk the plan's tiers top-down: chaos injection + watchdog
        around the native tier, typed fault escalation demoting a
        failing tier through ``dispatch.LADDER`` (the batch continues
        one rung down), the python floor re-raising — a pure-python
        pairing error is a bug, not an availability problem."""
        if plan.mode == "empty":
            return False, []
        ladder = _failover.LADDER
        last_exc: BaseException | None = None
        self._last_tier = None
        tiers = plan.tiers or [_failover.FLOOR_TIER]
        for tier in tiers:
            if tier not in ("host", _failover.FLOOR_TIER) and (
                not ladder.active(tier)
            ):
                continue  # demoted since plan time (queue parked it)
            t_tier = time.perf_counter()
            try:
                if tier == BLS_NATIVE_TIER:
                    ok, results = self._run_native(plan)
                elif tier == "host":
                    ok, results = self._run_host(plan)
                else:
                    ok, results = self._run_python(plan)
            except Exception as exc:  # noqa: BLE001 — the escalation
                # seam (ed25519_verify.execute parity): any tier
                # failure demotes and walks one rung down; the floor
                # re-raises
                if tier == _failover.FLOOR_TIER:
                    raise
                last_exc = exc
                ladder.tier_fault(
                    tier, reason=_failover.fault_reason(exc),
                    batch=plan.n,
                    duplicate=getattr(
                        exc, "_ladder_watchdog_fired", False
                    ),
                )
                continue
            self._last_tier = tier
            # shape + wall feed the cost model (ed25519 execute
            # parity), in the BLS family matching the plan's mode —
            # the host rung here is pure-RLC BLS, and its timings must
            # never drag the ed25519 host estimate (nor may an
            # aggregate's one-pairing-covers-N rate masquerade as
            # per-signature batch throughput)
            ladder.note_batch(
                tier, batch=plan.n,
                seconds=time.perf_counter() - t_tier,
                family=(
                    _failover.ROUTE_FAMILY_BLS_AGG
                    if plan.mode == "aggregate"
                    else _failover.ROUTE_FAMILY_BLS
                ),
            )
            return ok, results
        raise last_exc if last_exc is not None else RuntimeError(
            "BLS dispatch ladder exhausted without a floor tier"
        )

    def verify(self) -> tuple[bool, list[bool]]:
        return self.execute(self.plan())

    # -- per-tier runners -------------------------------------------------

    def _run_native(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        """The C++ backend under the full health seam: span + chaos
        injection + launch watchdog (a wedged ctypes call becomes a
        signal inside the budget, and the watchdog demotes this tier
        before the stall returns — the r04 shape, inherited)."""
        from cometbft_tpu.crypto import health as _health

        wd = None
        try:
            with _tracer.span(
                "batch_verify", cat="crypto",
                kernel=f"bls_{plan.mode}", batch=plan.n,
            ) as sp:
                with _health.WATCHDOG.watch(
                    tier=BLS_NATIVE_TIER, batch=plan.n
                ) as wd:
                    _failover.CHAOS.inject(BLS_NATIVE_TIER)
                    if plan.mode == "aggregate":
                        ok, results = self._native_aggregate(plan)
                    else:
                        ok, results = self._native_batch(plan)
                sp.set(ok=ok, tier=BLS_NATIVE_TIER)
            return ok, results
        except Exception as exc:
            if wd is not None and wd["fired"]:
                exc._ladder_watchdog_fired = True
            raise

    def _native_aggregate(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        sig = plan.agg_sig
        if plan.same_msg:
            # ONE pairing-product: e(sum pk_i, H(m)) == e(g1, sig).
            # The pubkey sum comes from the LRU (cold: native EC adds;
            # warm: free) — a ValueError from a malformed signer is a
            # VERDICT (invalid aggregate), not a tier fault
            try:
                agg_pk = AGG_PK_CACHE.aggregate(
                    [pk.bytes() for pk in plan.agg_pubs]
                )
            except ValueError:
                return False, [False] * plan.n
            ok = bls_native.verify(
                agg_pk, _bls._digest_msg(plan.agg_msgs[0]), sig
            )
        else:
            ok = bls_native.aggregate_verify(
                [pk.bytes() for pk in plan.agg_pubs],
                [_bls._digest_msg(m) for m in plan.agg_msgs],
                sig,
            )
        return ok, [ok] * plan.n

    def _native_batch(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        weights = [os.urandom(15) + b"\x01" for _ in range(plan.n)]
        ok = bls_native.batch_verify(
            [pk.bytes() for pk, _, _ in plan.items],
            [_bls._digest_msg(m) for _, m, _ in plan.items],
            [s for _, _, s in plan.items],
            weights,
        )
        if ok:
            return True, [True] * plan.n
        # the RLC check says "something is invalid" — per-signature
        # re-verify for the exact verdict vector (reference behavior)
        results = [
            bls_native.verify(
                pk.bytes(), _bls._digest_msg(m), s
            )
            for pk, m, s in plan.items
        ]
        return all(results), results

    def _run_host(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        """The pure tower-field RLC batch — one shared Miller loop
        (batch mode only; plan() gives aggregates no host rung)."""
        if _bls.batch_verify_rlc_python(plan.items):
            return True, [True] * plan.n
        results = [
            pk.verify_signature_python(m, s)
            for pk, m, s in plan.items
        ]
        return all(results), results

    def _run_python(self, plan: _BlsPlan) -> tuple[bool, list[bool]]:
        """The floor: pure per-signature verification (batch) or one
        pure pairing-product (aggregate) — never the native backend,
        which is exactly the tier being fallen back FROM."""
        if plan.mode == "aggregate":
            if plan.same_msg:
                ok = _bls.fast_aggregate_verify_python(
                    plan.agg_pubs, plan.agg_msgs[0], plan.agg_sig
                )
            else:
                ok = _bls.aggregate_verify_python(
                    plan.agg_pubs, plan.agg_msgs, plan.agg_sig
                )
            return ok, [ok] * plan.n
        results = [
            pk.verify_signature_python(m, s)
            for pk, m, s in plan.items
        ]
        return all(results), results


def reset_for_tests() -> None:
    """Wipe the aggregate-pubkey cache (suites that tamper with keys)."""
    AGG_PK_CACHE.clear()


__all__ = [
    "AGG_PK_CACHE",
    "AggPubKeyCache",
    "BLS_NATIVE_TIER",
    "BlsLadderVerifier",
    "agg_pk_cache_capacity_from_env",
    "reset_for_tests",
]
