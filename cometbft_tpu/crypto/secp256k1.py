"""secp256k1 ECDSA (reference: crypto/secp256k1/secp256k1.go).

Bitcoin-style keys: 33-byte compressed pubkeys, addresses =
RIPEMD160(SHA256(pubkey)) (secp256k1.go:146), 64-byte compact r||s
signatures over SHA256(msg) with low-S normalization (secp256k1.go:124
— malleability rejection), deterministic RFC-6979 nonces.

Host-side: secp256k1 is a long-tail key type for app compatibility;
the batch plane stays ed25519/BLS.
"""

from __future__ import annotations

import hashlib
import hmac

from cometbft_tpu.crypto import PrivKey, PubKey

KEY_TYPE = "secp256k1"
PUB_KEY_SIZE = 33
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 64

# Curve: y^2 = x^3 + 7 over F_P, group order N (SEC2 v2 §2.4.1).
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


# -- group law (Jacobian coordinates) ----------------------------------

def _jac_double(pt):
    x, y, z = pt
    if y == 0:
        return (0, 1, 0)
    s = 4 * x * y * y % P
    m = 3 * x * x % P  # a = 0
    x2 = (m * m - 2 * s) % P
    y2 = (m * (s - x2) - 8 * y * y * y * y) % P
    z2 = 2 * y * z % P
    return (x2, y2, z2)


def _jac_add(p1, p2):
    if p1[2] == 0:
        return p2
    if p2[2] == 0:
        return p1
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return (0, 1, 0)
        return _jac_double(p1)
    h = (u2 - u1) % P
    r = (s2 - s1) % P
    h2 = h * h % P
    h3 = h * h2 % P
    u1h2 = u1 * h2 % P
    x3 = (r * r - h3 - 2 * u1h2) % P
    y3 = (r * (u1h2 - x3) - s1 * h3) % P
    z3 = h * z1 * z2 % P
    return (x3, y3, z3)


def _jac_mul(pt, k: int):
    acc = (0, 1, 0)
    while k:
        if k & 1:
            acc = _jac_add(acc, pt)
        pt = _jac_double(pt)
        k >>= 1
    return acc


def _to_affine(pt):
    x, y, z = pt
    if z == 0:
        return None
    zi = _inv(z, P)
    zi2 = zi * zi % P
    return (x * zi2 % P, y * zi2 * zi % P)


_G = (GX, GY, 1)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(data: bytes):
    if len(data) != PUB_KEY_SIZE or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


# -- RFC 6979 deterministic nonce --------------------------------------

def _rfc6979_k(priv: int, msg_hash: bytes) -> int:
    holder = b"\x01" * 32
    key = b"\x00" * 32
    x = priv.to_bytes(32, "big")
    h1 = msg_hash
    key = hmac.new(key, holder + b"\x00" + x + h1, hashlib.sha256).digest()
    holder = hmac.new(key, holder, hashlib.sha256).digest()
    key = hmac.new(key, holder + b"\x01" + x + h1, hashlib.sha256).digest()
    holder = hmac.new(key, holder, hashlib.sha256).digest()
    while True:
        holder = hmac.new(key, holder, hashlib.sha256).digest()
        k = int.from_bytes(holder, "big")
        if 1 <= k < N:
            return k
        key = hmac.new(key, holder + b"\x00", hashlib.sha256).digest()
        holder = hmac.new(key, holder, hashlib.sha256).digest()


class Secp256k1PubKey(PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(
                f"secp256k1 pubkey must be {PUB_KEY_SIZE} bytes"
            )
        self._bytes = bytes(data)

    def address(self) -> bytes:
        """Bitcoin-style RIPEMD160(SHA256(pubkey)) (secp256k1.go:146)."""
        sha = hashlib.sha256(self._bytes).digest()
        return hashlib.new("ripemd160", sha).digest()

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        pt = _decompress(self._bytes)
        if pt is None:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < N and 1 <= s < N):
            return False
        if s > N // 2:
            return False  # low-S only (malleability, secp256k1.go:130)
        e = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
        w = _inv(s, N)
        u1 = e * w % N
        u2 = r * w % N
        res = _jac_add(
            _jac_mul(_G, u1), _jac_mul((pt[0], pt[1], 1), u2)
        )
        aff = _to_affine(res)
        if aff is None:
            return False
        return aff[0] % N == r


class Secp256k1PrivKey(PrivKey):
    __slots__ = ("_d",)

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise ValueError(
                f"secp256k1 privkey must be {PRIV_KEY_SIZE} bytes"
            )
        d = int.from_bytes(data, "big")
        if not (1 <= d < N):
            raise ValueError("secp256k1 privkey out of range")
        self._d = d

    def bytes(self) -> bytes:
        return self._d.to_bytes(32, "big")

    def type(self) -> str:
        return KEY_TYPE

    def pub_key(self) -> Secp256k1PubKey:
        x, y = _to_affine(_jac_mul(_G, self._d))
        return Secp256k1PubKey(_compress(x, y))

    def sign(self, msg: bytes) -> bytes:
        """64-byte r||s with low-S (secp256k1.go:118 Sign)."""
        h = hashlib.sha256(msg).digest()
        e = int.from_bytes(h, "big") % N
        while True:
            k = _rfc6979_k(self._d, h)
            aff = _to_affine(_jac_mul(_G, k))
            r = aff[0] % N
            if r == 0:
                h = hashlib.sha256(h).digest()
                continue
            s = _inv(k, N) * (e + r * self._d) % N
            if s == 0:
                h = hashlib.sha256(h).digest()
                continue
            if s > N // 2:
                s = N - s
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def gen_priv_key() -> Secp256k1PrivKey:
    import os

    while True:
        raw = os.urandom(32)
        try:
            return Secp256k1PrivKey(raw)
        except ValueError:
            continue


def priv_key_from_secret(secret: bytes) -> Secp256k1PrivKey:
    """sha256(secret) -> scalar (secp256k1.go:95 GenPrivKeySecp256k1)."""
    d = int.from_bytes(hashlib.sha256(secret).digest(), "big") % (N - 1) + 1
    return Secp256k1PrivKey(d.to_bytes(32, "big"))


__all__ = [
    "KEY_TYPE",
    "PRIV_KEY_SIZE",
    "PUB_KEY_SIZE",
    "SIGNATURE_SIZE",
    "Secp256k1PrivKey",
    "Secp256k1PubKey",
    "gen_priv_key",
    "priv_key_from_secret",
]
