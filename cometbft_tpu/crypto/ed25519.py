"""Ed25519 keys with ZIP-215 verification (reference: crypto/ed25519/ed25519.go).

Single-signature verify uses a two-tier strategy:
  1. Fast path: the host C implementation (``cryptography``/OpenSSL,
     strict RFC 8032, cofactorless). Any signature it accepts is also
     accepted under ZIP-215 (cofactored form of the same equation holds,
     and its stricter decoding is a subset), so an accept is final.
  2. On reject, fall back to the pure-Python ZIP-215 oracle
     (cometbft_tpu.crypto.edwards) to admit the ZIP-215-only edge cases
     (non-canonical A/R encodings, small-order components) — matching the
     reference's curve25519-voi semantics (crypto/ed25519/ed25519.go:39).

Batch verification is the TPU plane; see cometbft_tpu.ops.ed25519 and the
dispatch in cometbft_tpu.crypto.batch. The CPU batch verifier here is the
correctness fallback mirroring BatchVerifier (ed25519.go:190-222).
"""

from __future__ import annotations

import os
import time

try:  # gated optional dep: environments without `cryptography` fall
    # back to the pure-Python ZIP-215 oracle for every operation —
    # slower (~5 ms/op) but bit-identical semantics (the oracle IS the
    # ground truth the fast path is differentially tested against)
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519 as _ced
except ImportError:  # pragma: no cover - environment-dependent
    InvalidSignature = serialization = _ced = None

from cometbft_tpu.crypto import BatchVerifier, PrivKey, PubKey, tmhash
from cometbft_tpu.crypto import edwards

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIVATE_KEY_SIZE = 64  # seed || pubkey, matching the reference layout
SIGNATURE_SIZE = 64
SEED_SIZE = 32


class Ed25519PubKey(PubKey):
    __slots__ = ("_bytes", "_lib_key")

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise ValueError(f"ed25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._lib_key = None

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes(self) -> bytes:
        return self._bytes

    def type(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if self._lib_key is None:
            try:
                self._lib_key = _ced.Ed25519PublicKey.from_public_bytes(
                    self._bytes
                )
            except Exception:  # incl. _ced=None (no `cryptography`)
                self._lib_key = False
        if self._lib_key:
            try:
                self._lib_key.verify(sig, msg)
                return True
            except InvalidSignature:
                pass
        # ZIP-215 edge cases (and keys OpenSSL refuses to load).
        return edwards.verify_zip215(self._bytes, msg, sig)

    def __repr__(self) -> str:
        return f"PubKeyEd25519{{{self._bytes.hex().upper()}}}"


class Ed25519PrivKey(PrivKey):
    __slots__ = ("_seed", "_lib_key", "_pub")

    def __init__(self, data: bytes):
        """Accepts a 32-byte seed or the 64-byte seed||pubkey layout."""
        if len(data) == PRIVATE_KEY_SIZE:
            data = data[:SEED_SIZE]
        if len(data) != SEED_SIZE:
            raise ValueError("ed25519 private key must be 32 or 64 bytes")
        self._seed = bytes(data)
        if _ced is None:  # no `cryptography`: pure-Python oracle path
            self._lib_key = None
            self._pub = Ed25519PubKey(edwards.public_key(self._seed))
            return
        self._lib_key = _ced.Ed25519PrivateKey.from_private_bytes(self._seed)
        self._pub = Ed25519PubKey(
            self._lib_key.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw
            )
        )

    def bytes(self) -> bytes:
        """64-byte seed || pubkey, the reference's private-key layout."""
        return self._seed + self._pub.bytes()

    def sign(self, msg: bytes) -> bytes:
        if self._lib_key is None:
            return edwards.sign(self._seed, msg)
        return self._lib_key.sign(msg)

    def pub_key(self) -> Ed25519PubKey:
        return self._pub

    def type(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> Ed25519PrivKey:
    return Ed25519PrivKey(os.urandom(SEED_SIZE))


def priv_key_from_secret(secret: bytes) -> Ed25519PrivKey:
    """Deterministic key from a secret (reference GenPrivKeyFromSecret:
    seed = sha256(secret)) — test/tooling use only."""
    return Ed25519PrivKey(tmhash.sum256(secret))


class CpuBatchVerifier(BatchVerifier):
    """Host-side batch verifier — the correctness fallback.

    The production batch path is cometbft_tpu.ops.ed25519.TpuBatchVerifier;
    both must agree bit-for-bit (differential tests).  Batches of
    NATIVE_MIN_BATCH+ go through ONE native random-linear-combination
    check (native/crypto/ed25519_batch.cpp — a single Pippenger MSM
    over the whole batch, the reference's batch.go strategy on this
    host): all-valid batches, the overwhelmingly common case, cost one
    equation; a failed batch falls back to per-signature verification
    for exact per-lane verdicts, exactly as the reference re-verifies
    individually on batch failure.
    """

    #: below this, per-signature verification beats MSM setup
    NATIVE_MIN_BATCH = 16

    def __init__(self) -> None:
        self._entries: list[tuple[Ed25519PubKey, bytes, bytes]] = []

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, Ed25519PubKey):
            raise TypeError("CpuBatchVerifier requires ed25519 keys")
        if len(sig) != SIGNATURE_SIZE:
            raise ValueError("malformed signature size")
        self._entries.append((pub_key, bytes(msg), bytes(sig)))

    def __len__(self) -> int:
        return len(self._entries)

    def verify(self) -> tuple[bool, list[bool]]:
        if not self._entries:
            return False, []
        from cometbft_tpu.metrics import crypto_metrics as _cm
        from cometbft_tpu.utils.trace import TRACER as _tracer

        n = len(self._entries)
        cm = _cm()
        cm.batch_verify_batch_size.observe(n)
        t0 = time.perf_counter()
        with _tracer.span(
            "host_batch_verify", cat="crypto", batch=n
        ) as sp:
            ok, results = self._verify_entries()
            sp.set(ok=ok)
        cm.host_verify_time_seconds.observe(time.perf_counter() - t0)
        return ok, results

    def _verify_entries(self) -> tuple[bool, list[bool]]:
        if len(self._entries) >= self.NATIVE_MIN_BATCH:
            from cometbft_tpu.crypto import ed25519_native as _native

            lib = _native.load()
            if lib is not None:
                got = _native.rlc_verify(
                    lib,
                    [
                        (pk.bytes(), msg, sig)
                        for pk, msg, sig in self._entries
                    ],
                )
                if got is True:
                    return True, [True] * len(self._entries)
                # False/None: per-signature pass below gives exact
                # per-lane verdicts (reference batch.go fallback)
        results = [
            pk.verify_signature(msg, sig)
            for pk, msg, sig in self._entries
        ]
        return all(results), results
