"""Crypto interfaces (reference: crypto/crypto.go:22-52).

The ``BatchVerifier`` protocol is the seam where the TPU execution
backend plugs in: 2 methods, zero leakage of consensus types — exactly
the property that lets an entire validator set's signatures land as a
single device launch (crypto/crypto.go:44, crypto/batch/batch.go:10).
"""

from __future__ import annotations

import abc
from typing import Protocol, runtime_checkable

ADDRESS_SIZE = 20  # tmhash truncated size (crypto/crypto.go:19)


class PubKey(abc.ABC):
    @abc.abstractmethod
    def address(self) -> bytes:
        """20-byte address: sha256(pubkey_bytes)[:20] for ed25519."""

    @abc.abstractmethod
    def bytes(self) -> bytes:
        ...

    @abc.abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        ...

    @abc.abstractmethod
    def type(self) -> str:
        ...

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, PubKey):
            return NotImplemented
        return self.type() == other.type() and self.bytes() == other.bytes()

    def __hash__(self) -> int:
        return hash((self.type(), self.bytes()))


class PrivKey(abc.ABC):
    @abc.abstractmethod
    def bytes(self) -> bytes:
        ...

    @abc.abstractmethod
    def sign(self, msg: bytes) -> bytes:
        ...

    @abc.abstractmethod
    def pub_key(self) -> PubKey:
        ...

    @abc.abstractmethod
    def type(self) -> str:
        ...


@runtime_checkable
class BatchVerifier(Protocol):
    """The TPU seam (crypto/crypto.go:44-52).

    ``add`` enqueues one (pubkey, msg, sig) tuple; ``verify`` executes the
    whole batch — on the TPU backend, as one device launch — and returns
    (all_valid, per_entry_validity).
    """

    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None:
        ...

    def verify(self) -> tuple[bool, list[bool]]:
        ...


class BatchVerificationError(Exception):
    pass
