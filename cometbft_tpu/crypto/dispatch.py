"""Failover dispatch ladder — health-driven tier demotion/promotion.

Two of five bench rounds lost the accelerator mid-run (r03: a wedged
tunnel, r04: hung launches), and in r05 the native host Pippenger
verifier outran the generic device path — yet until this module,
fallback was a scatter of ``except Exception`` blocks with no runtime
demotion, no promotion back, and no proof that consensus stays live
through device loss.  Committee-based consensus only keeps its
finality guarantees if signature verification stays *available*, not
just fast (arXiv:2302.00418, arXiv:2010.07031).  This module is the
one first-class owner of that availability decision:

**The ladder.**  Six tiers in strict preference order::

    keyed_mesh > keyed > generic_mesh > generic > host > python

(the sharded keyed kernel over the full device mesh, the single-device
keyed kernel, the sharded generic kernel, the single-device generic
kernel, the native host Pippenger/RLC batch verifier, and the pure
per-signature Python floor).  ``TpuBatchVerifier.plan()`` asks the
ladder which of a batch's *eligible* tiers are currently admissible;
``execute()`` walks them top-down, so the VerifyQueue launcher and
``ShardedTpuBatchVerifier`` inherit the same policy through the one
seam.  The ``python`` floor is never demoted — consensus liveness is
the invariant the whole ladder exists to protect.

**Demotion** is immediate and evidence-driven: a launch failure, a
watchdog overrun (``crypto/health.py`` LaunchWatchdog), or
``CMT_TPU_DEMOTE_AFTER`` consecutive HealthProber canary failures
demotes the tier with an exponential cool-down
(``CMT_TPU_COOLDOWN_S`` base, doubling per repeat offense up to
``CMT_TPU_COOLDOWN_MAX_S`` — a flapping tier gets exponentially rarer
chances, never a thrash loop).

**Promotion** closes the loop the PR 7 prober measures but nothing
consumed: a demoted tier is re-admitted after ``CMT_TPU_PROMOTE_AFTER``
consecutive healthy canaries once its cool-down has expired.  In
processes with no prober running, cool-down expiry re-admits the tier
for a half-open *trial*: the next batch may select it, and one success
promotes (one failure re-demotes at double the cool-down).

**Chaos mode** (``CMT_TPU_CHAOS=1``): a seeded, deterministic fault
plan (``CMT_TPU_CHAOS_PLAN``) injected at the execute seam — device
loss, launch hang past the watchdog budget, transient mis-launch,
mesh shard loss — so tier-1 can prove consensus keeps committing
heights while the ladder demotes and re-promotes (`make chaos-smoke`,
tests/test_dispatch.py).  Chaos never faults the host/python floor.

Every transition emits a ``crypto/dispatch_transition`` flight event
and feeds ``crypto_dispatch_demotions_total{from,to,reason}`` /
``crypto_dispatch_promotions_total{tier}`` /
``crypto_dispatch_current_tier{tier}`` (one-hot); ``/debug/dispatch``
(metrics server and JSON-RPC route, inspect mode included) serves the
ladder state, cool-downs, and the recent transition trail.  Policy
documentation: docs/dispatch_ladder.md.

This module deliberately imports no jax: host-only nodes (the wedged-
tunnel case) route through it without touching the device stack.
"""

from __future__ import annotations

import os
import random
import time
from collections import deque

from cometbft_tpu.crypto import ed25519 as _ed
from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import FLIGHT
from cometbft_tpu.utils.flight import ring_size_from_env as _int_env
from cometbft_tpu.utils.log import default_logger

#: the full ladder, best tier first (docs/dispatch_ladder.md) — the
#: canonical order every surface (health probes, docs, /debug) shares.
#: ``bls_native`` is the BLS12-381 family's top rung (the native C++
#: pairing backend, crypto/bls_dispatch.py): an ed25519 batch never
#: runs there and a BLS batch never runs on the device tiers, but both
#: families share the ONE availability state machine, so a faulting
#: native BLS library demotes exactly like a faulting device — with
#: cool-down, half-open trials, and probe-driven promotion inherited.
TIER_ORDER = (
    "keyed_mesh", "keyed", "generic_mesh", "generic", "bls_native",
    "host", "python",
)
#: tiers that launch on the accelerator
DEVICE_TIERS = frozenset(
    ("keyed_mesh", "keyed", "generic_mesh", "generic")
)
#: tiers backed by the native BLS12-381 pairing library
BLS_TIERS = frozenset(("bls_native",))
#: tiers the chaos plan may fault: everything above the host/python
#: floor — the accelerator tiers AND the native BLS backend (a
#: crashing ctypes library is exactly the kind of loss the ladder
#: exists to absorb); the floor itself is never chaos'd
CHAOS_TIERS = DEVICE_TIERS | BLS_TIERS
#: tiers that shard over the multi-chip mesh (shard-loss chaos scope)
MESH_TIERS = frozenset(("keyed_mesh", "generic_mesh"))
#: the floor: pure per-signature Python verification — never demoted,
#: never chaos-faulted; consensus liveness rests on it
FLOOR_TIER = "python"

DEFAULT_DEMOTE_AFTER = 3
DEFAULT_PROMOTE_AFTER = 2
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_COOLDOWN_MAX_S = 600.0
#: transition-trail ring depth served at /debug/dispatch
TRANSITION_RING = 64


def _float_env(var: str, default: float, minimum: float) -> float:
    """Validated float env knob (fail-loudly, same contract as
    flight.ring_size_from_env / health._float_env)."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be a number >= {minimum}, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {value}")
    return value


def demote_after_from_env() -> int:
    """Consecutive canary-probe failures that demote a tier."""
    return _int_env("CMT_TPU_DEMOTE_AFTER", DEFAULT_DEMOTE_AFTER, 1)


def promote_after_from_env() -> int:
    """Consecutive healthy canaries that re-admit a demoted tier."""
    return _int_env("CMT_TPU_PROMOTE_AFTER", DEFAULT_PROMOTE_AFTER, 1)


def cooldown_from_env() -> float:
    """Base demotion cool-down seconds (doubles per repeat offense)."""
    return _float_env("CMT_TPU_COOLDOWN_S", DEFAULT_COOLDOWN_S, 0.001)


def cooldown_max_from_env() -> float:
    """Cool-down ceiling for repeat offenders."""
    return _float_env(
        "CMT_TPU_COOLDOWN_MAX_S", DEFAULT_COOLDOWN_MAX_S, 0.001
    )


class TierUnavailable(RuntimeError):
    """A tier cannot serve this batch at all (capability/policy), as
    opposed to failing at runtime — the ladder skips it without
    demotion."""

    def __init__(self, tier: str, reason: str = "") -> None:
        super().__init__(f"tier {tier} unavailable: {reason}")
        self.tier = tier
        self.reason = reason


class TierFault(RuntimeError):
    """A tier failed at runtime (launch failure, device loss) — the
    typed escalation the execute walk converts into a demotion."""

    def __init__(self, tier: str, reason: str = "") -> None:
        super().__init__(f"tier {tier} fault: {reason}")
        self.tier = tier
        self.reason = reason


class ChaosFault(TierFault):
    """A fault injected by the chaos plan (CMT_TPU_CHAOS)."""


def fault_reason(exc: BaseException) -> str:
    """Bounded-cardinality reason label for an escalation exception."""
    if isinstance(exc, ChaosFault):
        return f"chaos:{exc.reason}"
    if isinstance(exc, (TierFault, TierUnavailable)):
        return exc.reason or type(exc).__name__
    return f"launch:{type(exc).__name__}"


# -- the chaos plan ------------------------------------------------------

#: fault kinds the plan may schedule (docs/dispatch_ladder.md):
#: device_loss — every device-tier launch in the window raises;
#: launch_hang — the launch sleeps past the watchdog budget, THEN
#:   raises (the watchdog fires first — the r04 signature);
#: mislaunch   — exactly ONE launch in the window raises (transient);
#: shard_loss  — only the *_mesh tiers raise (one chip gone: the
#:   single-device tiers still work).
CHAOS_KINDS = ("device_loss", "launch_hang", "mislaunch", "shard_loss")


class ChaosPlan:
    """A deterministic fault schedule: windows of (start_s, end_s,
    kind) over seconds-since-chaos-epoch.  Spec grammar (entries
    separated by ``;``):

    - ``kind@START-END`` — an explicit window, e.g.
      ``device_loss@0-2.5``.
    - ``seed=N[,on=S][,off=S][,n=K][,kinds=a|b]`` — K pseudo-random
      fault windows generated from ``random.Random(N)``: quiet gaps
      ~``off`` seconds, faults ~``on`` seconds, kinds drawn from the
      ``|``-list.  Same spec string -> identical schedule, always.
    """

    def __init__(self, windows: list[tuple[float, float, str]]) -> None:
        self.windows = sorted(windows)
        for start, end, kind in self.windows:
            if kind not in CHAOS_KINDS:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: unknown fault kind {kind!r} "
                    f"(one of {'|'.join(CHAOS_KINDS)})"
                )
            if not (0 <= start < end):
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: bad window {start}-{end}"
                )

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        windows: list[tuple[float, float, str]] = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                windows.extend(cls._seeded(entry))
                continue
            try:
                kind, span = entry.split("@", 1)
                a, b = span.split("-", 1)
                windows.append((float(a), float(b), kind.strip()))
            except ValueError:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: cannot parse entry {entry!r} "
                    "(want kind@start-end or seed=N,...)"
                ) from None
        if not windows:
            raise ValueError("CMT_TPU_CHAOS_PLAN: empty plan")
        return cls(windows)

    @staticmethod
    def _seeded(entry: str) -> list[tuple[float, float, str]]:
        params = {"on": 2.0, "off": 6.0, "n": 4.0}
        kinds: list[str] = ["device_loss"]
        seed = 0
        for part in entry.split(","):
            key, _, val = part.partition("=")
            key = key.strip()
            if key == "seed":
                seed = int(val)
            elif key == "kinds":
                kinds = [k for k in val.split("|") if k]
            elif key in params:
                params[key] = float(val)
            else:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: unknown seeded param {key!r}"
                )
        rng = random.Random(seed)
        windows: list[tuple[float, float, str]] = []
        t = 0.0
        for _ in range(int(params["n"])):
            t += params["off"] * (0.5 + rng.random())
            dur = params["on"] * (0.5 + rng.random())
            kind = kinds[rng.randrange(len(kinds))]
            windows.append((t, t + dur, kind))
            t += dur
        return windows

    def applies(self, kind: str, tier: str) -> bool:
        if tier not in CHAOS_TIERS:
            return False  # the host/python floor is never chaos'd
        if kind == "shard_loss":
            return tier in MESH_TIERS
        return True

    def fault_at(
        self, tier: str, t: float, fired: set[int]
    ) -> tuple[int, str] | None:
        """The (window index, kind) faulting ``tier`` at plan time
        ``t``, honoring one-shot semantics for ``mislaunch`` via the
        caller-owned ``fired`` set — pure apart from that set, so unit
        tests drive it with explicit clocks."""
        for idx, (start, end, kind) in enumerate(self.windows):
            if not (start <= t < end):
                continue
            if not self.applies(kind, tier):
                continue
            if kind == "mislaunch" and idx in fired:
                continue
            return idx, kind
        return None


@cmtsync.guarded
class Chaos:
    """The chaos injector: no-op unless ``CMT_TPU_CHAOS=1``.  The plan
    clock starts at the first injection check (or ``start()``), so a
    node's chaos windows are relative to when traffic begins."""

    _GUARDED_BY = {"_epoch": "_mtx", "_fired": "_mtx", "_hits": "_mtx"}

    def __init__(self) -> None:
        self._mtx = cmtsync.Mutex()
        self._epoch: float | None = None
        self._fired: set[int] = set()
        self._hits: dict[str, int] = {}
        self.plan: ChaosPlan | None = None
        self.reload()

    def reload(self) -> None:
        """Re-read the env (tests toggle chaos per-case; production
        reads it once at process start)."""
        plan = None
        if os.environ.get("CMT_TPU_CHAOS"):
            spec = os.environ.get(
                "CMT_TPU_CHAOS_PLAN",
                # default drill: seeded loss-then-recovery cycles
                "seed=0,on=2,off=8,n=8,kinds=device_loss|mislaunch",
            )
            plan = ChaosPlan.parse(spec)
        with self._mtx:
            self.plan = plan
            self._epoch = None
            self._fired = set()
            self._hits = {}

    def enabled(self) -> bool:
        return self.plan is not None

    def start(self) -> None:
        """Pin the chaos epoch now (node assembly calls this when it
        logs the armed plan; otherwise the first inject() pins it)."""
        with self._mtx:
            if self._epoch is None:
                self._epoch = time.monotonic()

    def inject(self, tier: str, probe: bool = False) -> None:
        """The execute-seam (and probe-seam) injection point: raises
        ChaosFault when the plan schedules a fault for ``tier`` now.
        ``launch_hang`` sleeps past the watchdog budget first (so the
        watchdog demotes — the r04 signature) except on the probe
        seam, where the prober's own timeout plays that role."""
        plan = self.plan
        if plan is None or tier not in CHAOS_TIERS:
            return
        with self._mtx:
            if self._epoch is None:
                self._epoch = time.monotonic()
            t = time.monotonic() - self._epoch
            hit = plan.fault_at(tier, t, self._fired)
            if hit is None:
                return
            idx, kind = hit
            if kind == "mislaunch":
                self._fired.add(idx)
            self._hits[kind] = self._hits.get(kind, 0) + 1
        if kind == "launch_hang" and not probe:
            from cometbft_tpu.crypto import health as _health

            time.sleep(_health.WATCHDOG.budget_s * 1.25)
        raise ChaosFault(tier, kind)

    def snapshot(self) -> dict:
        plan = self.plan
        with self._mtx:
            elapsed = (
                round(time.monotonic() - self._epoch, 3)
                if self._epoch is not None else None
            )
            hits = dict(self._hits)
        return {
            "enabled": plan is not None,
            "elapsed_s": elapsed,
            "hits": hits,
            "windows": (
                [
                    {"kind": k, "start_s": a, "end_s": b}
                    for a, b, k in plan.windows
                ]
                if plan is not None else []
            ),
        }


# -- the ladder ----------------------------------------------------------


@cmtsync.guarded
class DispatchLadder:
    """The process-wide tier-availability state machine (module
    docstring).  All verifier seams consult the one ``LADDER``
    singleton, so a tier demoted under consensus traffic is equally
    demoted for blocksync prefetch, probes, and benches."""

    _GUARDED_BY = {
        "_state": "_mtx",
        "_known": "_mtx",
        "_transitions": "_mtx",
        "_gauge_set": "_mtx",
    }

    def __init__(
        self,
        demote_after: int | None = None,
        promote_after: int | None = None,
        cooldown_s: float | None = None,
        cooldown_max_s: float | None = None,
        clock=time.monotonic,
        logger=None,
    ) -> None:
        self._mtx = cmtsync.Mutex()
        self._clock = clock
        self.logger = logger or default_logger().with_fields(
            module="crypto.dispatch"
        )
        self.demote_after = (
            demote_after if demote_after is not None
            else demote_after_from_env()
        )
        self.promote_after = (
            promote_after if promote_after is not None
            else promote_after_from_env()
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None else cooldown_from_env()
        )
        self.cooldown_max_s = (
            cooldown_max_s if cooldown_max_s is not None
            else cooldown_max_from_env()
        )
        # tier -> mutable state dict (guarded by _mtx)
        self._state: dict[str, dict] = {}
        self._known: set[str] = {"host", FLOOR_TIER}
        self._transitions: deque = deque(maxlen=TRANSITION_RING)
        # the one-hot gauge only changes on transitions and _known
        # growth — not per batch, so the hot path skips the rewrite
        self._gauge_set = False

    # -- state helpers (call under _mtx) ---------------------------------

    def _st(self, tier: str) -> dict:  # holds _mtx
        st = self._state.get(tier)
        if st is None:
            st = {
                "demoted": False,
                "fail_streak": 0,      # consecutive probe failures
                "ok_streak": 0,        # healthy canaries while demoted
                "cooldown_until": 0.0,
                "next_cooldown_s": self.cooldown_s,
                "demotions": 0,
                "promotions": 0,
                "last_reason": None,
            }
            self._state[tier] = st
        return st

    def _active_locked(self, tier: str) -> bool:  # holds _mtx
        if tier == FLOOR_TIER:
            return True
        st = self._state.get(tier)
        if st is None or not st["demoted"]:
            return True
        # half-open trial: cool-down expiry re-admits the tier for the
        # next batch (a success promotes, a failure re-demotes at
        # double the cool-down) — so processes with no prober running
        # still recover
        return self._clock() >= st["cooldown_until"]

    def _current_locked(self) -> str:  # holds _mtx
        for tier in TIER_ORDER:
            if tier in self._known and self._active_locked(tier):
                return tier
        return FLOOR_TIER

    def _next_active_below_locked(self, tier: str) -> str:  # holds _mtx
        try:
            idx = TIER_ORDER.index(tier)
        except ValueError:
            return FLOOR_TIER
        for t in TIER_ORDER[idx + 1:]:
            # cross-family rungs never serve each other's batches: a
            # demoted DEVICE tier's work falls to host/python, never
            # to the BLS pairing backend that happens to sit between
            # them in the shared order — the demotion event's ``to``
            # label must name where the batch actually goes
            if tier in DEVICE_TIERS and t in BLS_TIERS:
                continue
            if (t in self._known or t in ("host", FLOOR_TIER)) and (
                self._active_locked(t)
            ):
                return t
        return FLOOR_TIER

    # -- public queries ---------------------------------------------------

    def active(self, tier: str) -> bool:
        """Is ``tier`` currently admissible (not demoted, or past its
        cool-down for a half-open trial)?"""
        with self._mtx:
            return self._active_locked(tier)

    def admissible(self, tiers: list[str]) -> list[str]:
        """Filter an eligibility list to currently-admissible tiers,
        preserving ladder order; also registers them as known (the
        current-tier gauge tracks the best tier this process could
        run, not the whole universe)."""
        with self._mtx:
            refresh = not self._gauge_set or any(
                t not in self._known for t in tiers
            )
            self._known.update(tiers)
            out = [t for t in tiers if self._active_locked(t)]
        if refresh:
            self._set_current_gauge()
        return out

    def current_tier(self) -> str:
        with self._mtx:
            return self._current_locked()

    # -- evidence ---------------------------------------------------------

    def note_batch(self, tier: str) -> None:
        """The ONE per-batch accounting point: every batch-verify call
        records the tier it ACTUALLY ran on here (host-only factory
        verifiers and device verifiers alike — PR 6's split accounting
        unified), and a successful batch on a trial-re-admitted tier
        promotes it."""
        _crypto_metrics().dispatch_tier.labels(tier=tier).inc()
        promote = False
        with self._mtx:
            refresh = not self._gauge_set or tier not in self._known
            self._known.add(tier)
            st = self._st(tier)
            st["fail_streak"] = 0
            if st["demoted"] and self._clock() >= st["cooldown_until"]:
                # only a half-open trial admits a batch onto a demoted
                # tier AFTER its cool-down — that success is the
                # promotion evidence.  A launch that was already in
                # flight when the tier was demoted (watchdog overrun)
                # also lands here, still INSIDE the cool-down; its
                # success must not cancel the demotion.
                promote = True
        if promote:
            self._promote(tier, reason="trial_success")
        elif refresh:
            self._set_current_gauge()

    def tier_fault(
        self, tier: str, reason: str, batch: int = 0,
        duplicate: bool = False,
    ) -> None:
        """A runtime failure on ``tier`` (launch failure, chaos fault,
        table-build error): demote immediately with exponential
        cool-down.  No-op for the python floor.  ``duplicate`` marks
        evidence for an offense already demoted (the launch's watchdog
        fired before its exception escalated here)."""
        if tier == FLOOR_TIER:
            return
        now = self._clock()
        with self._mtx:
            self._known.add(tier)
            st = self._st(tier)
            was_demoted = st["demoted"]
            # a fault on a tier already demoted and still cooling down
            # is duplicate evidence of the SAME offense (the watchdog
            # demotes a wedged launch before its exception escalates
            # here — ``duplicate`` pins the pairing per launch even
            # when the stall outlives the cool-down): both signals are
            # recorded, but the exponential back-off advances once per
            # offense, not once per signal
            dup = duplicate or (
                was_demoted and now < st["cooldown_until"]
            )
            st["demoted"] = True
            st["ok_streak"] = 0
            st["last_reason"] = reason
            if dup:
                cooldown = max(st["cooldown_until"] - now, 0.0)
            else:
                st["cooldown_until"] = now + st["next_cooldown_s"]
                cooldown = st["next_cooldown_s"]
                st["next_cooldown_s"] = min(
                    st["next_cooldown_s"] * 2, self.cooldown_max_s
                )
            st["demotions"] += 1
            to = self._next_active_below_locked(tier)
        self._emit(
            "demote", tier, to, reason,
            cooldown_s=cooldown, batch=batch,
            redemoted=was_demoted,
        )

    def watchdog_fault(self, tier: str) -> None:
        """A launch watchdog overrun on ``tier`` (crypto/health.py):
        the launch is wedged past its budget — demote now, before the
        stalled call even returns."""
        if tier in TIER_ORDER and tier != FLOOR_TIER:
            self.tier_fault(tier, reason="watchdog")

    def note_probe(self, tier: str, ok: bool) -> None:
        """Canary-probe evidence from the HealthProber: N consecutive
        failures demote; M consecutive successes (after cool-down)
        promote a demoted tier."""
        if tier not in TIER_ORDER or tier == FLOOR_TIER:
            return
        demote = promote = False
        now = self._clock()
        with self._mtx:
            self._known.add(tier)
            st = self._st(tier)
            if ok:
                st["fail_streak"] = 0
                if st["demoted"]:
                    st["ok_streak"] += 1
                    if (
                        st["ok_streak"] >= self.promote_after
                        and now >= st["cooldown_until"]
                    ):
                        promote = True
            else:
                st["ok_streak"] = 0
                if not st["demoted"]:
                    st["fail_streak"] += 1
                    if st["fail_streak"] >= self.demote_after:
                        demote = True
                elif now >= st["cooldown_until"]:
                    # a failing canary past cool-down consumes the
                    # half-open trial: the tier re-closes at doubled
                    # cool-down, so a production batch never has to
                    # discover what the prober already knows is dead
                    demote = True
        if demote:
            self.tier_fault(tier, reason="probe_failures")
        elif promote:
            self._promote(tier, reason="probes")

    # -- transitions ------------------------------------------------------

    def _promote(self, tier: str, reason: str) -> None:
        with self._mtx:
            st = self._st(tier)
            if not st["demoted"]:
                return
            st["demoted"] = False
            st["fail_streak"] = 0
            st["ok_streak"] = 0
            st["promotions"] += 1
            st["last_reason"] = reason
            # next_cooldown_s stays elevated: a tier that faults again
            # soon after promotion pays the doubled cool-down — the
            # anti-thrash half of the hysteresis
            to = self._current_locked()
        _crypto_metrics().dispatch_promotions_total.labels(
            tier=tier
        ).inc()
        self._emit("promote", tier, to, reason)

    def _emit(self, kind: str, frm: str, to: str, reason: str,
              **fields) -> None:
        event = {
            "kind": kind, "from": frm, "to": to, "reason": reason,
            "at": time.time(),
        }
        event.update(fields)
        with self._mtx:
            self._transitions.append(event)
        if kind == "demote":
            _crypto_metrics().dispatch_demotions_total.labels(
                **{"from": frm, "to": to, "reason": reason}
            ).inc()
        FLIGHT.record(
            "crypto/dispatch_transition", transition=kind, tier=frm,
            to=to, reason=reason,
        )
        log = self.logger.error if kind == "demote" else self.logger.info
        log(
            f"dispatch ladder {kind}", tier=frm, to=to, reason=reason,
            **{k: v for k, v in fields.items() if k != "at"},
        )
        self._set_current_gauge()

    def _set_current_gauge(self) -> None:
        with self._mtx:
            current = self._current_locked()
            self._gauge_set = True
        gauge = _crypto_metrics().dispatch_current_tier
        for tier in TIER_ORDER:
            gauge.labels(tier=tier).set(1.0 if tier == current else 0.0)

    # -- introspection / tests -------------------------------------------

    def snapshot(self) -> dict:
        now = self._clock()
        with self._mtx:
            tiers = {}
            for tier in TIER_ORDER:
                st = self._state.get(tier)
                if st is None:
                    tiers[tier] = {
                        "known": tier in self._known,
                        "demoted": False,
                    }
                    continue
                tiers[tier] = {
                    "known": tier in self._known,
                    "demoted": st["demoted"],
                    "fail_streak": st["fail_streak"],
                    "ok_streak": st["ok_streak"],
                    "cooldown_remaining_s": round(
                        max(st["cooldown_until"] - now, 0.0), 3
                    ),
                    "next_cooldown_s": st["next_cooldown_s"],
                    "demotions": st["demotions"],
                    "promotions": st["promotions"],
                    "last_reason": st["last_reason"],
                }
            return {
                "order": list(TIER_ORDER),
                "current": self._current_locked(),
                "policy": {
                    "demote_after": self.demote_after,
                    "promote_after": self.promote_after,
                    "cooldown_s": self.cooldown_s,
                    "cooldown_max_s": self.cooldown_max_s,
                },
                "tiers": tiers,
                "transitions": list(self._transitions),
            }

    def reset(self) -> None:
        """Tests only: wipe all tier state and re-read the env knobs."""
        with self._mtx:
            self._state.clear()
            self._known = {"host", FLOOR_TIER}
            self._transitions.clear()
            self._gauge_set = False
        self.demote_after = demote_after_from_env()
        self.promote_after = promote_after_from_env()
        self.cooldown_s = cooldown_from_env()
        self.cooldown_max_s = cooldown_max_from_env()


#: process-wide singletons — every verifier seam, the watchdog, and
#: the prober feed/consult the same ladder (mirrors health.WATCHDOG)
LADDER = DispatchLadder()
CHAOS = Chaos()


def chaos_enabled() -> bool:
    return CHAOS.enabled()


def reset_for_tests() -> None:
    """Wipe ladder state and re-read chaos/policy env — test isolation
    for suites that toggle CMT_TPU_CHAOS / the policy knobs."""
    LADDER.reset()
    CHAOS.reload()


# -- the host-only ladder verifier ---------------------------------------


class LadderHostVerifier(_ed.CpuBatchVerifier):
    """The BatchVerifier ``crypto/batch.py`` hands out when no device
    is usable (probe failed, disabled, wedged tunnel): the host tier
    with the ladder's python floor under it.  Records
    ``crypto_dispatch_tier`` per BATCH at verify time — the same
    decision point device verifiers use — so tier counts are
    comparable across the whole ladder (PR 6's factory-time vs
    batch-time split, unified).  Deliberately jax-free."""

    def verify(self) -> tuple[bool, list[bool]]:
        if not self._entries:
            return False, []
        n = len(self._entries)
        if LADDER.active("host"):
            try:
                ok, results = super().verify()
                LADDER.note_batch("host")
                return ok, results
            except Exception as exc:  # noqa: BLE001 — typed escalation:
                # a native-lib fault demotes the host tier to the
                # python floor instead of vanishing into a bare except
                LADDER.tier_fault(
                    "host", reason=fault_reason(exc), batch=n
                )
        results = [
            pk.verify_signature(msg, sig)
            for pk, msg, sig in self._entries
        ]
        LADDER.note_batch(FLOOR_TIER)
        return all(results), results


# -- the /debug/dispatch payload -----------------------------------------


def debug_dispatch_payload() -> dict:
    """Everything ``/debug/dispatch`` serves: ladder order + per-tier
    state (demoted, cool-downs, streaks), the recent transition trail,
    the chaos plan (docs/dispatch_ladder.md), and the perf ledger's
    latest MEASURED sigs/s per tier next to the configured order —
    with an explicit contradiction list whenever a tier the ladder
    prefers measures slower than one below it (the r05
    host-Pippenger-beats-generic shape), so an operator can see at a
    glance when configuration and evidence disagree."""
    from cometbft_tpu.crypto.health import measured_tier_throughput

    measured = measured_tier_throughput()
    contradictions = []
    for i, hi in enumerate(TIER_ORDER):
        if hi not in measured:
            continue
        for lo in TIER_ORDER[i + 1:]:
            if lo not in measured:
                continue
            hi_v = measured[hi]["sigs_per_sec"]
            lo_v = measured[lo]["sigs_per_sec"]
            if lo_v > hi_v:
                contradictions.append({
                    "preferred": hi,
                    "preferred_sigs_per_sec": hi_v,
                    "faster": lo,
                    "faster_sigs_per_sec": lo_v,
                })
    return {
        "ladder": LADDER.snapshot(),
        "chaos": CHAOS.snapshot(),
        "measured_tier_throughput": measured,
        "order_contradictions": contradictions,
    }


__all__ = [
    "BLS_TIERS",
    "CHAOS",
    "CHAOS_KINDS",
    "CHAOS_TIERS",
    "DEVICE_TIERS",
    "FLOOR_TIER",
    "LADDER",
    "MESH_TIERS",
    "TIER_ORDER",
    "Chaos",
    "ChaosFault",
    "ChaosPlan",
    "DispatchLadder",
    "LadderHostVerifier",
    "TierFault",
    "TierUnavailable",
    "chaos_enabled",
    "cooldown_from_env",
    "cooldown_max_from_env",
    "debug_dispatch_payload",
    "demote_after_from_env",
    "fault_reason",
    "promote_after_from_env",
    "reset_for_tests",
]
