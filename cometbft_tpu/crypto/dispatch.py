"""Failover dispatch ladder — health-driven tier demotion/promotion.

Two of five bench rounds lost the accelerator mid-run (r03: a wedged
tunnel, r04: hung launches), and in r05 the native host Pippenger
verifier outran the generic device path — yet until this module,
fallback was a scatter of ``except Exception`` blocks with no runtime
demotion, no promotion back, and no proof that consensus stays live
through device loss.  Committee-based consensus only keeps its
finality guarantees if signature verification stays *available*, not
just fast (arXiv:2302.00418, arXiv:2010.07031).  This module is the
one first-class owner of that availability decision:

**The ladder.**  Six tiers in strict preference order::

    keyed_mesh > keyed > generic_mesh > generic > host > python

(the sharded keyed kernel over the full device mesh, the single-device
keyed kernel, the sharded generic kernel, the single-device generic
kernel, the native host Pippenger/RLC batch verifier, and the pure
per-signature Python floor).  ``TpuBatchVerifier.plan()`` asks the
ladder which of a batch's *eligible* tiers are currently admissible;
``execute()`` walks them top-down, so the VerifyQueue launcher and
``ShardedTpuBatchVerifier`` inherit the same policy through the one
seam.  The ``python`` floor is never demoted — consensus liveness is
the invariant the whole ladder exists to protect.

**Demotion** is immediate and evidence-driven: a launch failure, a
watchdog overrun (``crypto/health.py`` LaunchWatchdog), or
``CMT_TPU_DEMOTE_AFTER`` consecutive HealthProber canary failures
demotes the tier with an exponential cool-down
(``CMT_TPU_COOLDOWN_S`` base, doubling per repeat offense up to
``CMT_TPU_COOLDOWN_MAX_S`` — a flapping tier gets exponentially rarer
chances, never a thrash loop).

**Promotion** closes the loop the PR 7 prober measures but nothing
consumed: a demoted tier is re-admitted after ``CMT_TPU_PROMOTE_AFTER``
consecutive healthy canaries once its cool-down has expired.  In
processes with no prober running, cool-down expiry re-admits the tier
for a half-open *trial*: the next batch may select it, and one success
promotes (one failure re-demotes at double the cool-down).

**Chaos mode** (``CMT_TPU_CHAOS=1``): a seeded, deterministic fault
plan (``CMT_TPU_CHAOS_PLAN``) injected at the execute seam — device
loss, launch hang past the watchdog budget, transient mis-launch,
mesh shard loss — so tier-1 can prove consensus keeps committing
heights while the ladder demotes and re-promotes (`make chaos-smoke`,
tests/test_dispatch.py).  Chaos never faults the host/python floor.

**Cost-based routing** (ISSUE 14) sits ON TOP of the availability
ladder: the :class:`TierCostModel` keeps per-(tier, pow2-shape-bucket)
throughput estimates — seeded from the perf ledger
(docs/data/perf_ledger.json) at first consult, refined online by an
EWMA over the per-batch timings ``note_batch`` already receives — and
``route()`` orders a batch's admissible tiers by predicted wall time
for *that batch's shape* instead of walking the static preference
order.  "Performance of EdDSA and BLS Signatures in Committee-Based
Consensus" (arXiv:2302.00418) quantifies why the order must be
shape-dependent: which strategy wins flips with batch size, so the one
static walk is wrong at one end or the other (the r05 contradiction —
host Pippenger at 56.8k sigs/s outran the generic device path — made
``/debug/dispatch`` publish ``order_contradictions`` nobody consumed;
the router is that consumer).  The ladder remains the availability
mechanism: cost ordering only PERMUTES the admissible list, demotion /
cool-down / chaos are untouched, and the python floor is always last.
Hysteresis keeps one noisy sample from flapping the routing: estimates
participate only with ledger provenance or ``CMT_TPU_ROUTE_MIN_SAMPLES``
online samples, a reorder needs a ``CMT_TPU_ROUTE_MARGIN`` predicted
gain, EWMA updates are winsorized, and adopted orders hold for
``CMT_TPU_ROUTE_COOLDOWN_S`` per shape bucket.

Every transition emits a ``crypto/dispatch_transition`` flight event
and feeds ``crypto_dispatch_demotions_total{from,to,reason}`` /
``crypto_dispatch_promotions_total{tier}`` /
``crypto_dispatch_current_tier{tier}`` (one-hot); routing decisions
feed ``crypto_dispatch_route{tier,bucket,source}`` and order adoptions
``crypto_route_reorders_total{bucket}``.  ``/debug/dispatch`` (metrics
server and JSON-RPC route, inspect mode included) serves the ladder
state, cool-downs, the recent transition trail, and the live cost
table with the contradictions the router has resolved.  Policy
documentation: docs/dispatch_ladder.md.

This module deliberately imports no jax: host-only nodes (the wedged-
tunnel case) route through it without touching the device stack.
"""

from __future__ import annotations

import os
import random
import time
from collections import deque

from cometbft_tpu.crypto import ed25519 as _ed
from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import FLIGHT
from cometbft_tpu.utils.env import flag_from_env
from cometbft_tpu.utils.flight import ring_size_from_env as _int_env
from cometbft_tpu.utils.log import default_logger

#: the full ladder, best tier first (docs/dispatch_ladder.md) — the
#: canonical order every surface (health probes, docs, /debug) shares.
#: ``bls_native`` is the BLS12-381 family's top rung (the native C++
#: pairing backend, crypto/bls_dispatch.py): an ed25519 batch never
#: runs there and a BLS batch never runs on the device tiers, but both
#: families share the ONE availability state machine, so a faulting
#: native BLS library demotes exactly like a faulting device — with
#: cool-down, half-open trials, and probe-driven promotion inherited.
TIER_ORDER = (
    "keyed_mesh", "keyed", "generic_mesh", "generic", "bls_native",
    "host", "python",
)
#: tiers that launch on the accelerator
DEVICE_TIERS = frozenset(
    ("keyed_mesh", "keyed", "generic_mesh", "generic")
)
#: tiers backed by the native BLS12-381 pairing library
BLS_TIERS = frozenset(("bls_native",))
#: tiers the chaos plan may fault: everything above the host/python
#: floor — the accelerator tiers AND the native BLS backend (a
#: crashing ctypes library is exactly the kind of loss the ladder
#: exists to absorb); the floor itself is never chaos'd
CHAOS_TIERS = DEVICE_TIERS | BLS_TIERS
#: tiers that shard over the multi-chip mesh (shard-loss chaos scope)
MESH_TIERS = frozenset(("keyed_mesh", "generic_mesh"))
#: the floor: pure per-signature Python verification — never demoted,
#: never chaos-faulted; consensus liveness rests on it
FLOOR_TIER = "python"

DEFAULT_DEMOTE_AFTER = 3
DEFAULT_PROMOTE_AFTER = 2
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_COOLDOWN_MAX_S = 600.0
#: transition-trail ring depth served at /debug/dispatch
TRANSITION_RING = 64

# -- cost-routing policy (TierCostModel) --------------------------------
#: online learned samples a (tier, bucket) estimate needs before it may
#: participate in reordering (seeded ledger estimates carry a whole
#: bench run's worth of evidence and participate immediately)
DEFAULT_ROUTE_MIN_SAMPLES = 3
#: predicted throughput gain required before a lower rung outranks a
#: higher one (0.2 = the lower tier must predict >= 20% faster)
DEFAULT_ROUTE_MARGIN = 0.2
#: per-(bucket, candidate-set) reorder cool-down: an adopted order
#: holds at least this long, so estimates hovering at the margin
#: boundary cannot flap the routing per batch
DEFAULT_ROUTE_COOLDOWN_S = 30.0
#: EWMA smoothing for online refinement — one sample moves an
#: established estimate at most alpha * (winsor - 1) = 20%
ROUTE_EWMA_ALPHA = 0.2
#: winsorization bound: a single sample is clamped to
#: [est / 2, est * 2] before the EWMA, so one wild outlier (a paused
#: process, a cold compile) can never flip an established pair on its
#: own — consistent repeats are evidence and still win in 2-3 batches
ROUTE_WINSOR_FACTOR = 2.0
#: shape-bucket ceiling (anything larger shares the top bucket)
MAX_SHAPE_BUCKET = 1 << 20
#: cost-estimate families: "host" means ed25519 CPU-batch for an
#: ed25519 walk but pure-RLC BLS for a BLS batch walk — orders of
#: magnitude apart — and a BLS aggregate (one pairing covers N
#: signers) is not N independent pairings.  Estimates therefore key
#: on (family, tier, bucket): same-name rungs in different families
#: never share (or pollute) a number.
ROUTE_FAMILY_ED25519 = "ed25519"
ROUTE_FAMILY_BLS = "bls"
ROUTE_FAMILY_BLS_AGG = "bls_agg"


def shape_bucket(n: int) -> int:
    """The pow2 ceiling bucket a batch of ``n`` signatures falls in —
    the shape key of the cost model (a 2-sig evidence check and a
    10k-sig commit must never share an estimate: per-launch overhead
    dominates one and amortizes in the other)."""
    if n <= 1:
        return 1
    return min(1 << (n - 1).bit_length(), MAX_SHAPE_BUCKET)


def _float_env(var: str, default: float, minimum: float) -> float:
    """Validated float env knob (fail-loudly, same contract as
    flight.ring_size_from_env / health._float_env)."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be a number >= {minimum}, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {value}")
    return value


def demote_after_from_env() -> int:
    """Consecutive canary-probe failures that demote a tier."""
    return _int_env("CMT_TPU_DEMOTE_AFTER", DEFAULT_DEMOTE_AFTER, 1)


def promote_after_from_env() -> int:
    """Consecutive healthy canaries that re-admit a demoted tier."""
    return _int_env("CMT_TPU_PROMOTE_AFTER", DEFAULT_PROMOTE_AFTER, 1)


def cooldown_from_env() -> float:
    """Base demotion cool-down seconds (doubles per repeat offense)."""
    return _float_env("CMT_TPU_COOLDOWN_S", DEFAULT_COOLDOWN_S, 0.001)


def cooldown_max_from_env() -> float:
    """Cool-down ceiling for repeat offenders."""
    return _float_env(
        "CMT_TPU_COOLDOWN_MAX_S", DEFAULT_COOLDOWN_MAX_S, 0.001
    )


def route_enabled_from_env() -> bool:
    """Cost-based shape-aware routing on/off (default on).  Fail-loudly
    contract: anything but 0/1 raises naming the variable."""
    return flag_from_env("CMT_TPU_ROUTE", default=True)


def route_min_samples_from_env() -> int:
    """Online samples a (tier, bucket) estimate needs to participate
    in reordering."""
    return _int_env(
        "CMT_TPU_ROUTE_MIN_SAMPLES", DEFAULT_ROUTE_MIN_SAMPLES, 1
    )


def route_margin_from_env() -> float:
    """Predicted throughput gain required before the cost model
    reorders a tier pair (0.2 = 20%)."""
    return _float_env("CMT_TPU_ROUTE_MARGIN", DEFAULT_ROUTE_MARGIN, 0.0)


def route_cooldown_from_env() -> float:
    """Per-shape-bucket reorder cool-down seconds."""
    return _float_env(
        "CMT_TPU_ROUTE_COOLDOWN_S", DEFAULT_ROUTE_COOLDOWN_S, 0.0
    )


class TierUnavailable(RuntimeError):
    """A tier cannot serve this batch at all (capability/policy), as
    opposed to failing at runtime — the ladder skips it without
    demotion."""

    def __init__(self, tier: str, reason: str = "") -> None:
        super().__init__(f"tier {tier} unavailable: {reason}")
        self.tier = tier
        self.reason = reason


class TierFault(RuntimeError):
    """A tier failed at runtime (launch failure, device loss) — the
    typed escalation the execute walk converts into a demotion."""

    def __init__(self, tier: str, reason: str = "") -> None:
        super().__init__(f"tier {tier} fault: {reason}")
        self.tier = tier
        self.reason = reason


class ChaosFault(TierFault):
    """A fault injected by the chaos plan (CMT_TPU_CHAOS)."""


def fault_reason(exc: BaseException) -> str:
    """Bounded-cardinality reason label for an escalation exception."""
    if isinstance(exc, ChaosFault):
        return f"chaos:{exc.reason}"
    if isinstance(exc, (TierFault, TierUnavailable)):
        return exc.reason or type(exc).__name__
    return f"launch:{type(exc).__name__}"


# -- the chaos plan ------------------------------------------------------

#: fault kinds the plan may schedule (docs/dispatch_ladder.md):
#: device_loss — every device-tier launch in the window raises;
#: launch_hang — the launch sleeps past the watchdog budget, THEN
#:   raises (the watchdog fires first — the r04 signature);
#: mislaunch   — exactly ONE launch in the window raises (transient);
#: shard_loss  — only the *_mesh tiers raise (one chip gone: the
#:   single-device tiers still work).
CHAOS_KINDS = ("device_loss", "launch_hang", "mislaunch", "shard_loss")


class ChaosPlan:
    """A deterministic fault schedule: windows of (start_s, end_s,
    kind) over seconds-since-chaos-epoch.  Spec grammar (entries
    separated by ``;``):

    - ``kind@START-END`` — an explicit window, e.g.
      ``device_loss@0-2.5``.
    - ``seed=N[,on=S][,off=S][,n=K][,kinds=a|b]`` — K pseudo-random
      fault windows generated from ``random.Random(N)``: quiet gaps
      ~``off`` seconds, faults ~``on`` seconds, kinds drawn from the
      ``|``-list.  Same spec string -> identical schedule, always.
    """

    def __init__(self, windows: list[tuple[float, float, str]]) -> None:
        self.windows = sorted(windows)
        for start, end, kind in self.windows:
            if kind not in CHAOS_KINDS:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: unknown fault kind {kind!r} "
                    f"(one of {'|'.join(CHAOS_KINDS)})"
                )
            if not (0 <= start < end):
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: bad window {start}-{end}"
                )

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        windows: list[tuple[float, float, str]] = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                windows.extend(cls._seeded(entry))
                continue
            try:
                kind, span = entry.split("@", 1)
                a, b = span.split("-", 1)
                windows.append((float(a), float(b), kind.strip()))
            except ValueError:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: cannot parse entry {entry!r} "
                    "(want kind@start-end or seed=N,...)"
                ) from None
        if not windows:
            raise ValueError("CMT_TPU_CHAOS_PLAN: empty plan")
        return cls(windows)

    @staticmethod
    def _seeded(entry: str) -> list[tuple[float, float, str]]:
        params = {"on": 2.0, "off": 6.0, "n": 4.0}
        kinds: list[str] = ["device_loss"]
        seed = 0
        for part in entry.split(","):
            key, _, val = part.partition("=")
            key = key.strip()
            if key == "seed":
                seed = int(val)
            elif key == "kinds":
                kinds = [k for k in val.split("|") if k]
            elif key in params:
                params[key] = float(val)
            else:
                raise ValueError(
                    f"CMT_TPU_CHAOS_PLAN: unknown seeded param {key!r}"
                )
        rng = random.Random(seed)
        windows: list[tuple[float, float, str]] = []
        t = 0.0
        for _ in range(int(params["n"])):
            t += params["off"] * (0.5 + rng.random())
            dur = params["on"] * (0.5 + rng.random())
            kind = kinds[rng.randrange(len(kinds))]
            windows.append((t, t + dur, kind))
            t += dur
        return windows

    def applies(self, kind: str, tier: str) -> bool:
        if tier not in CHAOS_TIERS:
            return False  # the host/python floor is never chaos'd
        if kind == "shard_loss":
            return tier in MESH_TIERS
        return True

    def fault_at(
        self, tier: str, t: float, fired: set[int]
    ) -> tuple[int, str] | None:
        """The (window index, kind) faulting ``tier`` at plan time
        ``t``, honoring one-shot semantics for ``mislaunch`` via the
        caller-owned ``fired`` set — pure apart from that set, so unit
        tests drive it with explicit clocks."""
        for idx, (start, end, kind) in enumerate(self.windows):
            if not (start <= t < end):
                continue
            if not self.applies(kind, tier):
                continue
            if kind == "mislaunch" and idx in fired:
                continue
            return idx, kind
        return None


@cmtsync.guarded
class Chaos:
    """The chaos injector: no-op unless ``CMT_TPU_CHAOS=1``.  The plan
    clock starts at the first injection check (or ``start()``), so a
    node's chaos windows are relative to when traffic begins."""

    _GUARDED_BY = {"_epoch": "_mtx", "_fired": "_mtx", "_hits": "_mtx"}

    def __init__(self) -> None:
        self._mtx = cmtsync.Mutex()
        self._epoch: float | None = None
        self._fired: set[int] = set()
        self._hits: dict[str, int] = {}
        self.plan: ChaosPlan | None = None
        self.reload()

    def reload(self) -> None:
        """Re-read the env (tests toggle chaos per-case; production
        reads it once at process start)."""
        plan = None
        if flag_from_env("CMT_TPU_CHAOS"):
            spec = os.environ.get(
                "CMT_TPU_CHAOS_PLAN",  # env ok: free-form fault plan — ChaosPlan.parse validates fail-loudly naming the variable
                # default drill: seeded loss-then-recovery cycles
                "seed=0,on=2,off=8,n=8,kinds=device_loss|mislaunch",
            )
            plan = ChaosPlan.parse(spec)
        with self._mtx:
            self.plan = plan
            self._epoch = None
            self._fired = set()
            self._hits = {}

    def enabled(self) -> bool:
        return self.plan is not None

    def start(self) -> None:
        """Pin the chaos epoch now (node assembly calls this when it
        logs the armed plan; otherwise the first inject() pins it)."""
        with self._mtx:
            if self._epoch is None:
                self._epoch = time.monotonic()

    def inject(self, tier: str, probe: bool = False) -> None:
        """The execute-seam (and probe-seam) injection point: raises
        ChaosFault when the plan schedules a fault for ``tier`` now.
        ``launch_hang`` sleeps past the watchdog budget first (so the
        watchdog demotes — the r04 signature) except on the probe
        seam, where the prober's own timeout plays that role."""
        plan = self.plan
        if plan is None or tier not in CHAOS_TIERS:
            return
        with self._mtx:
            if self._epoch is None:
                self._epoch = time.monotonic()
            t = time.monotonic() - self._epoch
            hit = plan.fault_at(tier, t, self._fired)
            if hit is None:
                return
            idx, kind = hit
            if kind == "mislaunch":
                self._fired.add(idx)
            self._hits[kind] = self._hits.get(kind, 0) + 1
        if kind == "launch_hang" and not probe:
            from cometbft_tpu.crypto import health as _health

            time.sleep(_health.WATCHDOG.budget_s * 1.25)
        raise ChaosFault(tier, kind)

    def snapshot(self) -> dict:
        plan = self.plan
        with self._mtx:
            elapsed = (
                round(time.monotonic() - self._epoch, 3)
                if self._epoch is not None else None
            )
            hits = dict(self._hits)
        return {
            "enabled": plan is not None,
            "elapsed_s": elapsed,
            "hits": hits,
            "windows": (
                [
                    {"kind": k, "start_s": a, "end_s": b}
                    for a, b, k in plan.windows
                ]
                if plan is not None else []
            ),
        }


# -- the cost model ------------------------------------------------------


class TierCostModel:
    """Per-(tier, pow2-shape-bucket) throughput estimates and the
    shape-aware order they imply (module docstring, "Cost-based
    routing").  NOT independently locked: the owning
    :class:`DispatchLadder` calls every ``*_locked`` method under its
    own ``_mtx`` — the hot path gains no new lock acquisitions, the
    cost update rides the one per-batch ``note_batch`` critical
    section that already exists.

    Estimate lifecycle: a **seeded** entry comes from the perf
    ledger's measured rows and participates immediately (it carries a
    whole bench run's evidence); an online entry starts **warming**
    and participates only after ``min_samples`` batches; either
    becomes **learned** once ``min_samples`` online samples have
    refined it.  Estimates are strictly per-bucket — no cross-shape
    extrapolation, because shape-dependence (which strategy wins flips
    with batch size, arXiv:2302.00418) is exactly what makes
    extrapolation wrong.

    Ordering: starting from the static ladder order, adjacent pairs
    where BOTH tiers have participating estimates for the bucket are
    bubble-swapped when the lower tier predicts a ``margin`` faster
    run; pairs with a missing estimate keep their static relative
    order (evidence permutes the walk, absence of evidence never
    does).  Adopted orders are cached per (bucket, candidate-set) and
    held for ``cooldown_s`` — the flap bound.
    """

    def __init__(
        self,
        enabled: bool | None = None,
        min_samples: int | None = None,
        margin: float | None = None,
        cooldown_s: float | None = None,
    ) -> None:
        self.enabled = (
            enabled if enabled is not None else route_enabled_from_env()
        )
        self.min_samples = (
            min_samples if min_samples is not None
            else route_min_samples_from_env()
        )
        self.margin = (
            margin if margin is not None else route_margin_from_env()
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else route_cooldown_from_env()
        )
        # (family, tier, bucket) -> {sigs_per_sec, samples, source,
        # config} — family-keyed so a BLS batch's host-RLC timing can
        # never drag the ed25519 host estimate (or vice versa)
        self._est: dict[tuple[str, str, int], dict] = {}
        # (family, bucket, static candidates) -> {order, last_reorder,
        # reorders}
        self._orders: dict[tuple[str, int, tuple], dict] = {}
        #: ledger seeding happened (racy read is fine: seeding is
        #: idempotent — seed_locked never overwrites online evidence)
        self.seeded = False

    # -- estimates (call under the ladder's _mtx) ------------------------

    def seed_locked(self, measured: dict) -> int:  # holds ladder _mtx
        """Seed from ``health.measured_tier_throughput()`` output (its
        per-bucket view of single-batch tier-throughput rows).  Online
        evidence outranks a seed: an entry that already has samples is
        never overwritten.  Rows land in the family their tier implies
        — device/host rows are ed25519 benches, ``bls_native`` rows
        are BLS (aggregate when the config says so)."""
        n = 0
        for tier, info in measured.items():
            if tier not in TIER_ORDER or tier == FLOOR_TIER:
                continue
            for bucket, entry in (info.get("buckets") or {}).items():
                if tier in BLS_TIERS:
                    family = (
                        ROUTE_FAMILY_BLS_AGG
                        if "aggregate" in (entry.get("config") or "")
                        else ROUTE_FAMILY_BLS
                    )
                else:
                    family = ROUTE_FAMILY_ED25519
                key = (family, tier, int(bucket))
                st = self._est.get(key)
                if st is not None and st["samples"] > 0:
                    continue
                self._est[key] = {
                    "sigs_per_sec": float(entry["sigs_per_sec"]),
                    "samples": 0,
                    "source": "seeded",
                    "config": entry.get("config"),
                }
                n += 1
        self.seeded = True
        return n

    def observe_locked(
        self, tier: str, batch: int, seconds: float | None,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> None:  # holds ladder _mtx
        """One batch's measured throughput, folded into the (family,
        tier, bucket) EWMA.  Winsorized: the sample is clamped to
        [est/2, est*2] first, so an established estimate moves at most
        20% per batch — one outlier can never clear the reorder margin
        alone."""
        if (
            tier == FLOOR_TIER or tier not in TIER_ORDER
            or batch < 1 or not seconds or seconds <= 0
        ):
            return
        sample = batch / seconds
        key = (family, tier, shape_bucket(batch))
        st = self._est.get(key)
        if st is None:
            self._est[key] = {
                "sigs_per_sec": sample,
                "samples": 1,
                "source": (
                    "learned" if self.min_samples <= 1 else "warming"
                ),
                "config": None,
            }
            return
        prev = st["sigs_per_sec"]
        clamped = min(
            max(sample, prev / ROUTE_WINSOR_FACTOR),
            prev * ROUTE_WINSOR_FACTOR,
        )
        st["sigs_per_sec"] = (
            (1.0 - ROUTE_EWMA_ALPHA) * prev + ROUTE_EWMA_ALPHA * clamped
        )
        st["samples"] += 1
        if st["samples"] >= self.min_samples:
            st["source"] = "learned"

    def _participating_locked(
        self, tier: str, bucket: int,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> dict | None:  # holds ladder _mtx
        st = self._est.get((family, tier, bucket))
        if st is None:
            return None
        if st["source"] in ("seeded", "learned"):
            return st
        return None  # warming: under min_samples, no routing say yet

    # -- ordering (call under the ladder's _mtx) -------------------------

    def desired_locked(
        self, candidates: list[str], bucket: int,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> tuple:  # holds ladder _mtx
        """The cost-implied order: tiers WITH participating estimates
        are reordered among themselves (margin-gated bubble over the
        estimated SUBSEQUENCE, so an estimate-less tier sitting
        between two estimated ones never blocks their comparison —
        keyed/generic/host with generic unmeasured still ranks host
        against keyed) and re-inserted into the position slots the
        estimated tiers occupied; tiers without evidence keep their
        exact static positions.  Bounded passes — the list is <= 7
        tiers."""
        order = list(candidates)
        idxs = [
            i for i, t in enumerate(order)
            if self._participating_locked(t, bucket, family) is not None
        ]
        sub = [order[i] for i in idxs]
        for _ in range(len(sub)):
            swapped = False
            for k in range(len(sub) - 1):
                ea = self._participating_locked(sub[k], bucket, family)
                eb = self._participating_locked(
                    sub[k + 1], bucket, family
                )
                if eb["sigs_per_sec"] > (
                    ea["sigs_per_sec"] * (1.0 + self.margin)
                ):
                    sub[k], sub[k + 1] = sub[k + 1], sub[k]
                    swapped = True
            if not swapped:
                break
        for i, t in zip(idxs, sub):
            order[i] = t
        return tuple(order)

    def order_locked(
        self, candidates: list[str], bucket: int, now: float,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> tuple[tuple, bool, str]:  # holds ladder _mtx
        """-> (order, reordered_now, source) for one batch.  ``source``
        labels how the FIRST tier got its slot: ``static`` when it
        holds its configured position, else the winning estimate's
        provenance (``seeded`` | ``learned``)."""
        static = tuple(candidates)
        if not self.enabled or len(static) < 2:
            return static, False, "static"
        desired = self.desired_locked(candidates, bucket, family)
        key = (family, bucket, static)
        st = self._orders.get(key)
        if st is None:
            st = {"order": static, "last_reorder": None, "reorders": 0}
            self._orders[key] = st
        reordered = False
        if desired != st["order"]:
            last = st["last_reorder"]
            if last is None or now - last >= self.cooldown_s:
                st["order"] = desired
                st["last_reorder"] = now
                st["reorders"] += 1
                reordered = True
        order = st["order"]
        if order[0] == static[0]:
            source = "static"
        else:
            est = self._participating_locked(order[0], bucket, family)
            source = est["source"] if est is not None else "learned"
        return order, reordered, source

    def snapshot_locked(self, now: float) -> dict:  # holds ladder _mtx
        """The live cost table /debug/dispatch serves."""
        table = [
            {
                "family": family,
                "tier": tier,
                "bucket": bucket,
                "sigs_per_sec": round(st["sigs_per_sec"], 1),
                "samples": st["samples"],
                "source": st["source"],
                "config": st["config"],
                "participating": (
                    self._participating_locked(tier, bucket, family)
                    is not None
                ),
            }
            for (family, tier, bucket), st in sorted(self._est.items())
        ]
        orders = [
            {
                "family": family,
                "bucket": bucket,
                "candidates": list(cands),
                "order": list(st["order"]),
                "reorders": st["reorders"],
                "last_reorder_age_s": (
                    round(now - st["last_reorder"], 3)
                    if st["last_reorder"] is not None else None
                ),
            }
            for (family, bucket, cands), st in sorted(
                self._orders.items()
            )
            if st["order"] != cands or st["reorders"]
        ]
        return {
            "enabled": self.enabled,
            "seeded": self.seeded,
            "policy": {
                "min_samples": self.min_samples,
                "margin": self.margin,
                "cooldown_s": self.cooldown_s,
                "ewma_alpha": ROUTE_EWMA_ALPHA,
                "winsor_factor": ROUTE_WINSOR_FACTOR,
            },
            "table": table,
            "orders": orders,
        }


# -- the ladder ----------------------------------------------------------


@cmtsync.guarded
class DispatchLadder:
    """The process-wide tier-availability state machine (module
    docstring).  All verifier seams consult the one ``LADDER``
    singleton, so a tier demoted under consensus traffic is equally
    demoted for blocksync prefetch, probes, and benches."""

    _GUARDED_BY = {
        "_state": "_mtx",
        "_known": "_mtx",
        "_transitions": "_mtx",
        "_gauge_set": "_mtx",
    }

    def __init__(
        self,
        demote_after: int | None = None,
        promote_after: int | None = None,
        cooldown_s: float | None = None,
        cooldown_max_s: float | None = None,
        clock=time.monotonic,
        logger=None,
        cost_model: TierCostModel | None = None,
    ) -> None:
        self._mtx = cmtsync.Mutex()
        self._clock = clock
        self.logger = logger or default_logger().with_fields(
            module="crypto.dispatch"
        )
        self.demote_after = (
            demote_after if demote_after is not None
            else demote_after_from_env()
        )
        self.promote_after = (
            promote_after if promote_after is not None
            else promote_after_from_env()
        )
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None else cooldown_from_env()
        )
        self.cooldown_max_s = (
            cooldown_max_s if cooldown_max_s is not None
            else cooldown_max_from_env()
        )
        # tier -> mutable state dict (guarded by _mtx)
        self._state: dict[str, dict] = {}
        self._known: set[str] = {"host", FLOOR_TIER}
        self._transitions: deque = deque(maxlen=TRANSITION_RING)
        # the one-hot gauge only changes on transitions and _known
        # growth — not per batch, so the hot path skips the rewrite
        self._gauge_set = False
        # unguarded: immutable reference — the cost model's inner
        # state is mutated only while holding _mtx (its *_locked
        # contract); only `seeded`/`enabled` are read lock-free, both
        # benign (set-once / idempotent-seed)
        self._cost = (
            cost_model if cost_model is not None else TierCostModel()
        )

    # -- state helpers (call under _mtx) ---------------------------------

    def _st(self, tier: str) -> dict:  # holds _mtx
        st = self._state.get(tier)
        if st is None:
            st = {
                "demoted": False,
                "fail_streak": 0,      # consecutive probe failures
                "ok_streak": 0,        # healthy canaries while demoted
                "cooldown_until": 0.0,
                "next_cooldown_s": self.cooldown_s,
                "demotions": 0,
                "promotions": 0,
                "last_reason": None,
            }
            self._state[tier] = st
        return st

    def _active_locked(self, tier: str) -> bool:  # holds _mtx
        if tier == FLOOR_TIER:
            return True
        st = self._state.get(tier)
        if st is None or not st["demoted"]:
            return True
        # half-open trial: cool-down expiry re-admits the tier for the
        # next batch (a success promotes, a failure re-demotes at
        # double the cool-down) — so processes with no prober running
        # still recover
        return self._clock() >= st["cooldown_until"]

    def _current_locked(self) -> str:  # holds _mtx
        for tier in TIER_ORDER:
            if tier in self._known and self._active_locked(tier):
                return tier
        return FLOOR_TIER

    def _next_active_below_locked(self, tier: str) -> str:  # holds _mtx
        try:
            idx = TIER_ORDER.index(tier)
        except ValueError:
            return FLOOR_TIER
        for t in TIER_ORDER[idx + 1:]:
            # cross-family rungs never serve each other's batches: a
            # demoted DEVICE tier's work falls to host/python, never
            # to the BLS pairing backend that happens to sit between
            # them in the shared order — the demotion event's ``to``
            # label must name where the batch actually goes
            if tier in DEVICE_TIERS and t in BLS_TIERS:
                continue
            if (t in self._known or t in ("host", FLOOR_TIER)) and (
                self._active_locked(t)
            ):
                return t
        return FLOOR_TIER

    # -- public queries ---------------------------------------------------

    def active(self, tier: str) -> bool:
        """Is ``tier`` currently admissible (not demoted, or past its
        cool-down for a half-open trial)?"""
        with self._mtx:
            return self._active_locked(tier)

    def admissible(self, tiers: list[str]) -> list[str]:
        """Filter an eligibility list to currently-admissible tiers,
        preserving ladder order; also registers them as known (the
        current-tier gauge tracks the best tier this process could
        run, not the whole universe)."""
        with self._mtx:
            refresh = not self._gauge_set or any(
                t not in self._known for t in tiers
            )
            self._known.update(tiers)
            out = [t for t in tiers if self._active_locked(t)]
        if refresh:
            self._set_current_gauge()
        return out

    def current_tier(self) -> str:
        with self._mtx:
            return self._current_locked()

    # -- cost routing -----------------------------------------------------

    def ensure_seeded(self) -> None:
        """Lazily seed the cost model from the perf ledger — the
        "process start" seed, deferred to the first routing consult so
        importing this module never does file I/O.  The ledger read
        runs OUTSIDE the mutex; seeding is idempotent, so a racing
        double-read costs one redundant parse, never a wrong table."""
        if self._cost.seeded or not self._cost.enabled:
            return
        from cometbft_tpu.crypto.health import measured_tier_throughput

        try:
            measured = measured_tier_throughput()
        except Exception as exc:  # noqa: BLE001 — a malformed ledger
            # must not take routing (or the node) down: run unseeded,
            # learn online, and say so once
            measured = {}
            self.logger.error(
                "perf-ledger seed failed; cost model learns online "
                "only", err=repr(exc),
            )
        with self._mtx:
            n = self._cost.seed_locked(measured)
        if n:
            self.logger.info(
                "cost model seeded from perf ledger", entries=n
            )

    def route(
        self, admissible: list[str], batch: int, add_host: bool = True,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> list[str]:
        """Cost-order one batch's walk: the ladder-admissible tiers
        plus the host rung (cross-family ordering is the point — the
        r05 contradiction is host beating a device tier), permuted by
        predicted wall time for this batch's shape bucket.  The caller
        appends the floor; cost ordering never touches it.  Emits
        ``crypto_dispatch_route{tier,bucket,source}`` for the chosen
        first tier and ``crypto_route_reorders_total{bucket}`` when a
        new order is adopted."""
        candidates = list(admissible)
        if add_host and "host" not in candidates:
            candidates.append("host")
        if not candidates:
            return []
        bucket = shape_bucket(batch)
        self.ensure_seeded()
        with self._mtx:
            order, reordered, source = self._cost.order_locked(
                candidates, bucket, self._clock(), family
            )
        cm = _crypto_metrics()
        if reordered:
            cm.route_reorders_total.labels(bucket=str(bucket)).inc()
            FLIGHT.record(
                "crypto/route_reorder", bucket=bucket,
                order=list(order),
            )
            self.logger.info(
                "cost model reordered dispatch walk", bucket=bucket,
                order=list(order), static=candidates,
            )
        cm.dispatch_route.labels(
            tier=order[0], bucket=str(bucket), source=source
        ).inc()
        return list(order)

    def note_route(
        self, tier: str, batch: int, source: str = "static"
    ) -> None:
        """Route accounting for plans that never reach ``route()``
        (the host-only branch: batch below every device threshold) —
        every plan lands in ``crypto_dispatch_route`` exactly once."""
        _crypto_metrics().dispatch_route.labels(
            tier=tier, bucket=str(shape_bucket(batch)), source=source
        ).inc()

    def router_prefers(
        self, faster: str, preferred: str, bucket: int | None
    ) -> bool:
        """Does the cost model, consulted for ``bucket``, rank the
        measured-faster tier above the statically-preferred one in a
        FULL walk?  The ``resolved_by_router`` flag on
        /debug/dispatch's ``order_contradictions`` — pure read, no
        metrics, no order adoption.  Deliberately evaluated over every
        tier with a participating estimate at this bucket, not the
        bare pair: the margin-gated ordering is non-transitive, so a
        pairwise check could claim "resolved" while a real plan()'s
        walk (with a third estimated tier between them) still
        dispatches the slower tier first.  The full-walk form
        under-claims at worst (a batch whose eligibility excludes the
        middle tier may reorder anyway) — the flag stays honest."""
        if bucket is None or not self._cost.enabled:
            return False
        if FLOOR_TIER in (faster, preferred):
            # the floor is never part of the permutation (it is
            # always last), and it is excluded from the candidate
            # walk below — a degraded box CAN ledger a python-tier
            # row that out-measures a barely-alive device tier, and
            # that contradiction must not crash /debug/dispatch
            return False
        family = (
            ROUTE_FAMILY_BLS
            if faster in BLS_TIERS or preferred in BLS_TIERS
            else ROUTE_FAMILY_ED25519
        )
        self.ensure_seeded()
        with self._mtx:
            candidates = [
                t for t in TIER_ORDER
                if t != FLOOR_TIER and (
                    t in (faster, preferred)
                    or self._cost._participating_locked(
                        t, int(bucket), family
                    ) is not None
                )
            ]
            order = self._cost.desired_locked(
                candidates, int(bucket), family
            )
        return order.index(faster) < order.index(preferred)

    def cost_snapshot(self) -> dict:
        self.ensure_seeded()
        with self._mtx:
            return self._cost.snapshot_locked(self._clock())

    # -- evidence ---------------------------------------------------------

    def note_batch(
        self, tier: str, batch: int = 0, seconds: float | None = None,
        family: str = ROUTE_FAMILY_ED25519,
    ) -> None:
        """The ONE per-batch accounting point: every batch-verify call
        records the tier it ACTUALLY ran on here (host-only factory
        verifiers and device verifiers alike — PR 6's split accounting
        unified), and a successful batch on a trial-re-admitted tier
        promotes it.  ``batch``/``seconds`` (the batch's shape and
        measured wall) feed the cost model's per-(tier, bucket) EWMA
        inside the same critical section — online refinement costs the
        hot path zero new lock acquisitions."""
        _crypto_metrics().dispatch_tier.labels(tier=tier).inc()
        promote = False
        with self._mtx:
            refresh = not self._gauge_set or tier not in self._known
            self._known.add(tier)
            self._cost.observe_locked(tier, batch, seconds, family)
            st = self._st(tier)
            st["fail_streak"] = 0
            if st["demoted"] and self._clock() >= st["cooldown_until"]:
                # only a half-open trial admits a batch onto a demoted
                # tier AFTER its cool-down — that success is the
                # promotion evidence.  A launch that was already in
                # flight when the tier was demoted (watchdog overrun)
                # also lands here, still INSIDE the cool-down; its
                # success must not cancel the demotion.
                promote = True
        if promote:
            self._promote(tier, reason="trial_success")
        elif refresh:
            self._set_current_gauge()

    def tier_fault(
        self, tier: str, reason: str, batch: int = 0,
        duplicate: bool = False,
    ) -> None:
        """A runtime failure on ``tier`` (launch failure, chaos fault,
        table-build error): demote immediately with exponential
        cool-down.  No-op for the python floor.  ``duplicate`` marks
        evidence for an offense already demoted (the launch's watchdog
        fired before its exception escalated here)."""
        if tier == FLOOR_TIER:
            return
        now = self._clock()
        with self._mtx:
            self._known.add(tier)
            st = self._st(tier)
            was_demoted = st["demoted"]
            # a fault on a tier already demoted and still cooling down
            # is duplicate evidence of the SAME offense (the watchdog
            # demotes a wedged launch before its exception escalates
            # here — ``duplicate`` pins the pairing per launch even
            # when the stall outlives the cool-down): both signals are
            # recorded, but the exponential back-off advances once per
            # offense, not once per signal
            dup = duplicate or (
                was_demoted and now < st["cooldown_until"]
            )
            st["demoted"] = True
            st["ok_streak"] = 0
            st["last_reason"] = reason
            if dup:
                cooldown = max(st["cooldown_until"] - now, 0.0)
            else:
                st["cooldown_until"] = now + st["next_cooldown_s"]
                cooldown = st["next_cooldown_s"]
                st["next_cooldown_s"] = min(
                    st["next_cooldown_s"] * 2, self.cooldown_max_s
                )
            st["demotions"] += 1
            to = self._next_active_below_locked(tier)
        self._emit(
            "demote", tier, to, reason,
            cooldown_s=cooldown, batch=batch,
            redemoted=was_demoted,
        )

    def watchdog_fault(self, tier: str) -> None:
        """A launch watchdog overrun on ``tier`` (crypto/health.py):
        the launch is wedged past its budget — demote now, before the
        stalled call even returns."""
        if tier in TIER_ORDER and tier != FLOOR_TIER:
            self.tier_fault(tier, reason="watchdog")

    def note_probe(self, tier: str, ok: bool) -> None:
        """Canary-probe evidence from the HealthProber: N consecutive
        failures demote; M consecutive successes (after cool-down)
        promote a demoted tier."""
        if tier not in TIER_ORDER or tier == FLOOR_TIER:
            return
        demote = promote = False
        now = self._clock()
        with self._mtx:
            self._known.add(tier)
            st = self._st(tier)
            if ok:
                st["fail_streak"] = 0
                if st["demoted"]:
                    st["ok_streak"] += 1
                    if (
                        st["ok_streak"] >= self.promote_after
                        and now >= st["cooldown_until"]
                    ):
                        promote = True
            else:
                st["ok_streak"] = 0
                if not st["demoted"]:
                    st["fail_streak"] += 1
                    if st["fail_streak"] >= self.demote_after:
                        demote = True
                elif now >= st["cooldown_until"]:
                    # a failing canary past cool-down consumes the
                    # half-open trial: the tier re-closes at doubled
                    # cool-down, so a production batch never has to
                    # discover what the prober already knows is dead
                    demote = True
        if demote:
            self.tier_fault(tier, reason="probe_failures")
        elif promote:
            self._promote(tier, reason="probes")

    # -- transitions ------------------------------------------------------

    def _promote(self, tier: str, reason: str) -> None:
        with self._mtx:
            st = self._st(tier)
            if not st["demoted"]:
                return
            st["demoted"] = False
            st["fail_streak"] = 0
            st["ok_streak"] = 0
            st["promotions"] += 1
            st["last_reason"] = reason
            # next_cooldown_s stays elevated: a tier that faults again
            # soon after promotion pays the doubled cool-down — the
            # anti-thrash half of the hysteresis
            to = self._current_locked()
        _crypto_metrics().dispatch_promotions_total.labels(
            tier=tier
        ).inc()
        self._emit("promote", tier, to, reason)

    def _emit(self, kind: str, frm: str, to: str, reason: str,
              **fields) -> None:
        event = {
            "kind": kind, "from": frm, "to": to, "reason": reason,
            "at": time.time(),
        }
        event.update(fields)
        with self._mtx:
            self._transitions.append(event)
        if kind == "demote":
            _crypto_metrics().dispatch_demotions_total.labels(
                **{"from": frm, "to": to, "reason": reason}
            ).inc()
        FLIGHT.record(
            "crypto/dispatch_transition", transition=kind, tier=frm,
            to=to, reason=reason,
        )
        log = self.logger.error if kind == "demote" else self.logger.info
        log(
            f"dispatch ladder {kind}", tier=frm, to=to, reason=reason,
            **{k: v for k, v in fields.items() if k != "at"},
        )
        self._set_current_gauge()

    def _set_current_gauge(self) -> None:
        with self._mtx:
            current = self._current_locked()
            self._gauge_set = True
        gauge = _crypto_metrics().dispatch_current_tier
        for tier in TIER_ORDER:
            gauge.labels(tier=tier).set(1.0 if tier == current else 0.0)

    # -- introspection / tests -------------------------------------------

    def snapshot(self) -> dict:
        now = self._clock()
        with self._mtx:
            tiers = {}
            for tier in TIER_ORDER:
                st = self._state.get(tier)
                if st is None:
                    tiers[tier] = {
                        "known": tier in self._known,
                        "demoted": False,
                    }
                    continue
                tiers[tier] = {
                    "known": tier in self._known,
                    "demoted": st["demoted"],
                    "fail_streak": st["fail_streak"],
                    "ok_streak": st["ok_streak"],
                    "cooldown_remaining_s": round(
                        max(st["cooldown_until"] - now, 0.0), 3
                    ),
                    "next_cooldown_s": st["next_cooldown_s"],
                    "demotions": st["demotions"],
                    "promotions": st["promotions"],
                    "last_reason": st["last_reason"],
                }
            return {
                "order": list(TIER_ORDER),
                "current": self._current_locked(),
                "policy": {
                    "demote_after": self.demote_after,
                    "promote_after": self.promote_after,
                    "cooldown_s": self.cooldown_s,
                    "cooldown_max_s": self.cooldown_max_s,
                },
                "tiers": tiers,
                "transitions": list(self._transitions),
            }

    def reset(self) -> None:
        """Tests only: wipe all tier state and re-read the env knobs
        (the cost model is rebuilt empty and unseeded, so the next
        routing consult re-seeds from whatever CMT_TPU_PERF_LEDGER now
        points at)."""
        with self._mtx:
            self._state.clear()
            self._known = {"host", FLOOR_TIER}
            self._transitions.clear()
            self._gauge_set = False
            self._cost = TierCostModel()
        self.demote_after = demote_after_from_env()
        self.promote_after = promote_after_from_env()
        self.cooldown_s = cooldown_from_env()
        self.cooldown_max_s = cooldown_max_from_env()


#: process-wide singletons — every verifier seam, the watchdog, and
#: the prober feed/consult the same ladder (mirrors health.WATCHDOG)
LADDER = DispatchLadder()
CHAOS = Chaos()


def chaos_enabled() -> bool:
    return CHAOS.enabled()


def reset_for_tests() -> None:
    """Wipe ladder state and re-read chaos/policy env — test isolation
    for suites that toggle CMT_TPU_CHAOS / the policy knobs."""
    LADDER.reset()
    CHAOS.reload()


# -- the host-only ladder verifier ---------------------------------------


class LadderHostVerifier(_ed.CpuBatchVerifier):
    """The BatchVerifier ``crypto/batch.py`` hands out when no device
    is usable (probe failed, disabled, wedged tunnel): the host tier
    with the ladder's python floor under it.  Records
    ``crypto_dispatch_tier`` per BATCH at verify time — the same
    decision point device verifiers use — so tier counts are
    comparable across the whole ladder (PR 6's factory-time vs
    batch-time split, unified).  Deliberately jax-free."""

    def verify(self) -> tuple[bool, list[bool]]:
        if not self._entries:
            return False, []
        n = len(self._entries)
        # route accounting parity with the plan() seam: a factory-host
        # verifier's walk is host->python by construction, and on a
        # host-only node (the only place this class serves) it is the
        # ONLY verifier — without this sample the dispatch_route
        # family would be empty exactly where operators read it most
        LADDER.note_route("host", n)
        if LADDER.active("host"):
            try:
                t0 = time.perf_counter()
                ok, results = super().verify()
                LADDER.note_batch(
                    "host", batch=n,
                    seconds=time.perf_counter() - t0,
                )
                return ok, results
            except Exception as exc:  # noqa: BLE001 — typed escalation:
                # a native-lib fault demotes the host tier to the
                # python floor instead of vanishing into a bare except
                LADDER.tier_fault(
                    "host", reason=fault_reason(exc), batch=n
                )
        t0 = time.perf_counter()
        results = [
            pk.verify_signature(msg, sig)
            for pk, msg, sig in self._entries
        ]
        LADDER.note_batch(
            FLOOR_TIER, batch=n, seconds=time.perf_counter() - t0
        )
        return all(results), results


# -- the /debug/dispatch payload -----------------------------------------


def _contradiction_bucket(measured: dict, lo: str, hi: str) -> int | None:
    """The shape bucket a contradiction was measured at: the faster
    (lower) tier's latest measurement's bucket, else the preferred
    tier's — None when neither row carried batch provenance (the
    router is shape-aware; a shapeless contradiction it cannot
    resolve)."""
    for tier in (lo, hi):
        bucket = measured.get(tier, {}).get("bucket")
        if bucket is not None:
            return bucket
    return None


def debug_dispatch_payload() -> dict:
    """Everything ``/debug/dispatch`` serves: ladder order + per-tier
    state (demoted, cool-downs, streaks), the recent transition trail,
    the chaos plan (docs/dispatch_ladder.md), the live cost table
    (TierCostModel), and the perf ledger's latest MEASURED sigs/s per
    tier next to the configured order — with an explicit contradiction
    list whenever a tier the ladder prefers measures slower than one
    below it (the r05 host-Pippenger-beats-generic shape).  Each
    contradiction carries ``resolved_by_router``: True when the cost
    model now ranks the pair correctly for that measured shape, so the
    surface reports the router WORKING instead of a standing
    complaint."""
    from cometbft_tpu.crypto.health import measured_tier_throughput

    measured = measured_tier_throughput()
    contradictions = []
    for i, hi in enumerate(TIER_ORDER):
        # a tier may carry only a bucket view (its rows were latency-
        # united) — the tier-level contradiction scan needs the
        # tier-level number
        if measured.get(hi, {}).get("sigs_per_sec") is None:
            continue
        for lo in TIER_ORDER[i + 1:]:
            if measured.get(lo, {}).get("sigs_per_sec") is None:
                continue
            hi_v = measured[hi]["sigs_per_sec"]
            lo_v = measured[lo]["sigs_per_sec"]
            if lo_v > hi_v:
                bucket = _contradiction_bucket(measured, lo, hi)
                contradictions.append({
                    "preferred": hi,
                    "preferred_sigs_per_sec": hi_v,
                    "faster": lo,
                    "faster_sigs_per_sec": lo_v,
                    "bucket": bucket,
                    "resolved_by_router": LADDER.router_prefers(
                        lo, hi, bucket
                    ),
                })
    return {
        "ladder": LADDER.snapshot(),
        "chaos": CHAOS.snapshot(),
        "cost_model": LADDER.cost_snapshot(),
        "measured_tier_throughput": measured,
        "order_contradictions": contradictions,
    }


__all__ = [
    "BLS_TIERS",
    "CHAOS",
    "CHAOS_KINDS",
    "CHAOS_TIERS",
    "DEVICE_TIERS",
    "FLOOR_TIER",
    "LADDER",
    "MESH_TIERS",
    "TIER_ORDER",
    "Chaos",
    "ChaosFault",
    "ChaosPlan",
    "DispatchLadder",
    "LadderHostVerifier",
    "TierCostModel",
    "TierFault",
    "TierUnavailable",
    "chaos_enabled",
    "cooldown_from_env",
    "cooldown_max_from_env",
    "debug_dispatch_payload",
    "demote_after_from_env",
    "fault_reason",
    "promote_after_from_env",
    "ROUTE_FAMILY_BLS",
    "ROUTE_FAMILY_BLS_AGG",
    "ROUTE_FAMILY_ED25519",
    "reset_for_tests",
    "route_cooldown_from_env",
    "route_enabled_from_env",
    "route_margin_from_env",
    "route_min_samples_from_env",
    "shape_bucket",
]
