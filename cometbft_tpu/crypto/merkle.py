"""RFC-6962-style Merkle tree (reference: crypto/merkle/tree.go, proof.go).

Leaf hash = SHA-256(0x00 || leaf); inner = SHA-256(0x01 || left || right);
hash of the empty list = SHA-256(""). Trees split at the largest power of
two strictly less than n, giving deterministic, proof-friendly structure.
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.crypto import tmhash

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _empty_hash() -> bytes:
    return tmhash.sum256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return tmhash.sum256(LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return tmhash.sum256(INNER_PREFIX + left + right)


def _split_point(n: int) -> int:
    """Largest power of 2 strictly less than n (tree.go getSplitPoint)."""
    if n < 1:
        raise ValueError("need at least one item")
    k = 1 << (n - 1).bit_length() - 1
    return k if k < n else k >> 1


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return _empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(
        hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:])
    )


@dataclass
class Proof:
    """Merkle inclusion proof (crypto/merkle/proof.go:22)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes]

    MAX_AUNTS = 100  # proof.go:19 — bounds untrusted input

    def compute_root(self) -> bytes:
        return _root_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        if (
            self.total < 0
            or self.index < 0
            or self.index >= self.total
            or len(self.aunts) > self.MAX_AUNTS
        ):
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        try:
            return self.compute_root() == root
        except ValueError:
            return False


def _root_from_aunts(
    index: int, total: int, leaf: bytes, aunts: list[bytes]
) -> bytes:
    if total == 0:
        raise ValueError("cannot prove membership in empty tree")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single-leaf tree")
        return leaf
    if not aunts:
        raise ValueError("not enough aunts")
    k = _split_point(total)
    if index < k:
        left = _root_from_aunts(index, k, leaf, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _root_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + an inclusion proof per item (proof.go ProofsFromByteSlices)."""
    trails, root_node = _trails_from_byte_slices(items)
    root = root_node.hash if root_node else _empty_hash()
    proofs = [
        Proof(
            total=len(items),
            index=i,
            leaf_hash=trails[i].hash,
            aunts=trails[i].flatten_aunts(),
        )
        for i in range(len(items))
    ]
    return root, proofs


class _Node:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, hash_: bytes):
        self.hash = hash_
        self.parent: _Node | None = None
        self.left: _Node | None = None  # sibling to include when going up
        self.right: _Node | None = None

    def flatten_aunts(self) -> list[bytes]:
        aunts: list[bytes] = []
        node: _Node | None = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            if node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(
    items: list[bytes],
) -> tuple[list[_Node], _Node | None]:
    n = len(items)
    if n == 0:
        return [], None
    if n == 1:
        node = _Node(leaf_hash(items[0]))
        return [node], node
    k = _split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    assert left_root is not None and right_root is not None
    root = _Node(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root


# -- key/value state proofs (the framework's query-proof format) --------
#
# The reference chains ics23/ProofOperators through crypto/merkle/
# proof_op.go; this framework's native format is simpler: ONE ProofOp
# whose data is a serialized inclusion Proof for the canonical
# key/value leaf below, verified directly against the header app_hash.

#: ProofOp.type for a simple-merkle k/v inclusion proof.
KV_PROOF_OP_TYPE = "cmttpu:simple-merkle:v1"


def kv_leaf(key: bytes, value: bytes) -> bytes:
    """Canonical state leaf: uvarint-length-prefixed key then value.
    Apps hash sorted leaves into their app_hash; the proof-verifying
    RPC client rebuilds the leaf from the query response."""
    from cometbft_tpu.utils.protoio import encode_uvarint

    return (
        encode_uvarint(len(key)) + key + encode_uvarint(len(value)) + value
    )


def proof_to_bytes(p: Proof) -> bytes:
    from cometbft_tpu.utils.protoio import ProtoWriter

    w = ProtoWriter()
    w.varint(1, p.total)
    w.varint(2, p.index)
    w.bytes_(3, p.leaf_hash)
    for aunt in p.aunts:
        w.bytes_(4, aunt)
    return w.finish()


def proof_from_bytes(data: bytes) -> Proof:
    from cometbft_tpu.utils.protoio import ProtoReader

    f = ProtoReader(bytes(data)).to_dict()
    total = int(f.get(1, [0])[0])
    index = int(f.get(2, [0])[0])
    leaf = bytes(f.get(3, [b""])[0])
    aunts = [bytes(a) for a in f.get(4, [])]
    if total < 0 or index < 0 or len(aunts) > Proof.MAX_AUNTS:
        raise ValueError("malformed merkle proof")
    return Proof(total=total, index=index, leaf_hash=leaf, aunts=aunts)
