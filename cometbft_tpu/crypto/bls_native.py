"""Loader for the native (C++) BLS12-381 backend — the framework's
blst-equivalent (native/bls/bls12381.cpp; reference dependency:
supranational/blst via cgo, SURVEY.md §2.9).

Builds the shared library on first use when a C++ toolchain is
available (g++ -O2, ~10s, cached in native/build/) and exposes it via
ctypes.  Callers go through :mod:`cometbft_tpu.crypto.bls12381`,
which routes hot operations here and falls back to its pure-Python
tower implementation when the toolchain or library is unavailable
(CMT_TPU_NO_NATIVE_BLS=1 forces the fallback; the differential test
suite pins native == python byte-for-byte)."""

from __future__ import annotations

import ctypes

from cometbft_tpu.utils.native_build import NativeLib


def _configure(lib) -> None:
    lib.cmt_bls_init.restype = ctypes.c_int
    for name, args in (
        ("cmt_bls_pubkey_validate", [ctypes.c_char_p]),
        (
            "cmt_bls_verify",
            [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
             ctypes.c_char_p],
        ),
        (
            "cmt_bls_aggregate_verify",
            [ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
             ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p],
        ),
        (
            "cmt_bls_batch_verify",
            [ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p,
             ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p,
             ctypes.c_char_p],
        ),
        (
            "cmt_bls_sign",
            [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t,
             ctypes.c_char_p],
        ),
        ("cmt_bls_sk_to_pk", [ctypes.c_char_p, ctypes.c_char_p]),
        (
            "cmt_bls_hash_to_g2_compressed",
            [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p],
        ),
    ):
        fn = getattr(lib, name)
        fn.argtypes = args
        fn.restype = ctypes.c_int
    # newer exports: absent from a pre-built .so shipped before the
    # source grew them (the loader rebuilds stale caches, but a
    # read-only install can't) — probe instead of assuming
    for name, args in (
        (
            "cmt_bls_aggregate_pubkeys",
            [ctypes.c_size_t, ctypes.c_char_p, ctypes.c_char_p],
        ),
    ):
        fn = getattr(lib, name, None)
        if fn is not None:
            fn.argtypes = args
            fn.restype = ctypes.c_int
    lib.cmt_bls_init()


_NATIVE = NativeLib(
    "native/bls/bls12381.cpp", "libcmtbls.so", "CMT_TPU_NO_NATIVE_BLS",
    configure=_configure,
)


def load():
    """The ctypes library (signatures configured, init run), or None."""
    return _NATIVE.load()


def available() -> bool:
    return load() is not None


def loaded() -> bool:
    """True only when the library is ALREADY loaded in this process —
    never triggers the first-use g++ build (~10 s), so health probes
    and capability checks on cold processes stay cheap."""
    return _NATIVE._lib is not None


def has_aggregate_pubkeys() -> bool:
    lib = load()
    return lib is not None and hasattr(lib, "cmt_bls_aggregate_pubkeys")


# -- thin typed wrappers (bytes in/out) ---------------------------------

def verify(pk96: bytes, msg: bytes, sig96: bytes) -> bool:
    lib = load()
    return lib.cmt_bls_verify(pk96, msg, len(msg), sig96) == 1


def aggregate_verify(
    pks: list[bytes], msgs: list[bytes], sig96: bytes
) -> bool:
    lib = load()
    n = len(pks)
    lens = (ctypes.c_size_t * n)(*[len(m) for m in msgs])
    return (
        lib.cmt_bls_aggregate_verify(
            n, b"".join(pks), b"".join(msgs), lens, sig96
        )
        == 1
    )


def batch_verify(
    pks: list[bytes],
    msgs: list[bytes],
    sigs: list[bytes],
    weights16: list[bytes],
) -> bool:
    lib = load()
    n = len(pks)
    lens = (ctypes.c_size_t * n)(*[len(m) for m in msgs])
    return (
        lib.cmt_bls_batch_verify(
            n,
            b"".join(pks),
            b"".join(msgs),
            lens,
            b"".join(sigs),
            b"".join(weights16),
        )
        == 1
    )


def aggregate_pubkeys(pks: list[bytes]) -> bytes | None:
    """Sum of uncompressed G1 pubkeys (96 bytes), or None when the
    export is missing, an input is malformed/identity, or the sum is
    the identity — callers fall back to the Python tower path."""
    lib = load()
    if lib is None or not hasattr(lib, "cmt_bls_aggregate_pubkeys"):
        return None
    out = ctypes.create_string_buffer(96)
    if lib.cmt_bls_aggregate_pubkeys(len(pks), b"".join(pks), out) != 1:
        return None
    return out.raw


def sign(sk32: bytes, msg: bytes) -> bytes:
    lib = load()
    out = ctypes.create_string_buffer(96)
    lib.cmt_bls_sign(sk32, msg, len(msg), out)
    return out.raw


def sk_to_pk(sk32: bytes) -> bytes:
    lib = load()
    out = ctypes.create_string_buffer(96)
    lib.cmt_bls_sk_to_pk(sk32, out)
    return out.raw


def hash_to_g2_compressed(msg: bytes) -> bytes:
    lib = load()
    out = ctypes.create_string_buffer(96)
    lib.cmt_bls_hash_to_g2_compressed(msg, len(msg), out)
    return out.raw


__all__ = [
    "aggregate_pubkeys",
    "aggregate_verify",
    "available",
    "batch_verify",
    "has_aggregate_pubkeys",
    "hash_to_g2_compressed",
    "load",
    "loaded",
    "sign",
    "sk_to_pk",
    "verify",
]
