"""Consensus hashing (reference: crypto/tmhash — SHA-256 + 20-byte sums)."""

from __future__ import annotations

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    """First 20 bytes of SHA-256; used for addresses (crypto/tmhash/hash.go)."""
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]
