"""RPC core — the route handlers over node internals (reference:
rpc/core/, routes at rpc/core/routes.go:15-63).

``Environment`` holds references to the node's components; each public
method is one JSON-RPC route.  WebSocket-only routes (subscribe/
unsubscribe) live in ``ws_routes``.
"""

from __future__ import annotations

import base64
import threading
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils import trustguard
from cometbft_tpu.utils.flight import FLIGHT as _FLIGHT

from cometbft_tpu.abci.types import CheckTxRequest, InfoRequest, QueryRequest
from cometbft_tpu.rpc.jsonrpc import QuotedStr, RPCError
from cometbft_tpu.rpc.serialize import (
    b64,
    block_id_json,
    block_json,
    block_meta_json,
    commit_json,
    exec_tx_result_json,
    hexb,
    time_rfc3339,
    validator_json,
)
from cometbft_tpu.types.block import tx_hash
from cometbft_tpu.types.event_bus import (
    EVENT_TX,
    EventDataTx,
    query_for_event,
)
from cometbft_tpu.utils.pubsub import Query
from cometbft_tpu.version import __version__

SUBSCRIPTION_BUFFER = 200


def _to_int(value, name: str) -> int:
    if value is None or value == "":
        return 0
    try:
        return int(value)
    except (TypeError, ValueError):
        raise RPCError(-32602, f"invalid {name}: {value!r}") from None


def _to_bytes(value, name: str) -> bytes:
    """Accept hex (with/without 0x) or base64; a QUOTED URI arg means
    the literal bytes of the unquoted string (the reference's URI-arg
    semantics for []byte params — `tx="name=ada"` sends b"name=ada",
    http_uri_handler.go)."""
    if isinstance(value, bytes):
        return value
    if not isinstance(value, str):
        raise RPCError(-32602, f"invalid {name}")
    if isinstance(value, QuotedStr):
        return value.encode()
    s = value[2:] if value.startswith("0x") else value
    try:
        return bytes.fromhex(s)
    except ValueError:
        try:
            return base64.b64decode(value, validate=True)
        except Exception:
            raise RPCError(-32602, f"invalid {name}: not hex/base64") from None


class _AsyncTxPool:
    """Bounded fire-and-forget CheckTx workers for broadcast_tx_async.

    ``workers`` daemon threads drain a queue capped at ``depth`` txs;
    ``offer`` never blocks — when the queue is full the tx is DROPPED
    and counted in ``dropped`` (load shed at the RPC edge: async
    broadcast promises no admission verdict, and clients that need one
    use broadcast_tx_sync/commit).  Daemon threads mean node stop and
    interpreter exit never wait behind a backlog."""

    def __init__(self, submit, metrics=None, workers: int = 8,
                 depth: int = 1024):
        import queue as _q

        self._submit = submit
        self._metrics = metrics
        self._q: "_q.Queue[bytes]" = _q.Queue(maxsize=depth)
        self._drop_mtx = cmtsync.Mutex()
        self.dropped = 0
        for i in range(workers):
            threading.Thread(
                target=self._loop, name=f"rpc-checktx-{i}", daemon=True
            ).start()

    def _loop(self) -> None:
        while True:
            self._submit(self._q.get())

    def offer(self, raw: bytes) -> bool:
        import queue as _q

        try:
            self._q.put_nowait(raw)
            return True
        except _q.Full:
            with self._drop_mtx:
                self.dropped += 1
            if self._metrics is not None:
                # visible shed: without this the RPC edge drops txs
                # the checktx_total counters never saw
                self._metrics.checktx_async_dropped.inc()
            return False


class Environment:
    """(rpc/core/env.go:72 Environment)"""

    def __init__(
        self,
        block_store=None,
        state_store=None,
        consensus=None,
        mempool=None,
        switch=None,
        event_bus=None,
        tx_indexer=None,
        block_indexer=None,
        proxy_app=None,
        evidence_pool=None,
        genesis=None,
        node_info=None,
        pub_key=None,
        blocksync_reactor=None,
        statesync_reactor=None,
        unsafe=False,
        metrics=None,
        metrics_registry=None,
    ):
        from cometbft_tpu.metrics import RPCMetrics

        self.block_store = block_store
        self.state_store = state_store
        self.consensus = consensus
        self.mempool = mempool
        self.switch = switch
        self.event_bus = event_bus
        self.tx_indexer = tx_indexer
        self.block_indexer = block_indexer
        self.proxy_app = proxy_app
        self.evidence_pool = evidence_pool
        self.genesis = genesis
        self.node_info = node_info
        # a PubKey, or a zero-arg callable resolving to one (remote
        # signers aren't connected until the node starts)
        self._pub_key = pub_key
        self.blocksync_reactor = blocksync_reactor
        self.statesync_reactor = statesync_reactor
        self.unsafe = unsafe
        self.metrics = metrics if metrics is not None else RPCMetrics()
        #: the node's metric Registry (fleet plane: /debug/fleet reads
        #: SELF's families in-process rather than over the wire)
        self.metrics_registry = metrics_registry
        self._gen_chunks: list[str] | None = None  # lazy (env.go InitGenesisChunks)
        self._subs: dict[str, dict[str, object]] = {}  # client -> query -> sub
        self._subs_mtx = cmtsync.Mutex()
        #: bounded ingest pool for broadcast_tx_async (lazy): the old
        #: thread-per-tx spawn was a thread bomb at sustained-load
        #: rates — thousands of concurrent CheckTx threads convoying
        #: on the admission path.  A few daemon workers drain a
        #: BOUNDED queue instead; overflow is DROPPED (counted on the
        #: pool) — async broadcast is fire-and-forget by contract, and
        #: an unbounded backlog of tx bytes is a memory bomb plus a
        #: drain-everything shutdown hang.
        self._async_pool: _AsyncTxPool | None = None
        self._async_pool_mtx = cmtsync.Mutex()
        #: lazy light-client serving plane (light/serve.py): built on
        #: the first /light_sync request from the node's own stores —
        #: a node that never serves light clients pays nothing
        self._light_server = None
        self._light_server_mtx = cmtsync.Mutex()

    # -- route tables (routes.go:15-63) ---------------------------------


    @property
    def pub_key(self):
        pk = self._pub_key
        return pk() if callable(pk) else pk

    def routes(self) -> dict:
        routes = {
            "health": self.health,
            "status": self.status,
            "net_info": self.net_info,
            "blockchain": self.blockchain,
            "genesis": self.genesis_route,
            "block": self.block,
            "block_by_hash": self.block_by_hash,
            "block_results": self.block_results,
            "commit": self.commit,
            "header": self.header,
            "header_by_hash": self.header_by_hash,
            "tx": self.tx,
            "tx_search": self.tx_search,
            "block_search": self.block_search,
            "validators": self.validators,
            "consensus_state": self.consensus_state,
            "dump_consensus_state": self.dump_consensus_state,
            "consensus_params": self.consensus_params,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "broadcast_evidence": self.broadcast_evidence,
            "abci_query": self.abci_query,
            "abci_info": self.abci_info,
            "genesis_chunked": self.genesis_chunked,
            "check_tx": self.check_tx,
            "wire": self.wire,
            # the light-client serving plane (light/serve.py): verified
            # header ranges, cross-client coalesced + header-cached
            "light_sync": self.light_sync,
            # GET /debug/flight (the path strips to this route name):
            # the always-on flight recorder's recent replication events
            "debug/flight": self.debug_flight,
            # GET /debug/perf: device-health + perf-ledger snapshot
            "debug/perf": self.debug_perf,
            # GET /debug/dispatch: failover-ladder state + chaos plan
            "debug/dispatch": self.debug_dispatch,
            # GET /debug/fleet: cross-node rollup + stitched heights
            "debug/fleet": self.debug_fleet,
            # GET /debug/profile: span-tagged sampling-profiler stacks
            "debug/profile": self.debug_profile,
        }
        if self.unsafe:
            # routes.go:55 AddUnsafeRoutes (config.RPC.Unsafe)
            # reference names (routes.go:61-63) + explicit aliases
            routes["dial_seeds"] = self.unsafe_dial_seeds
            routes["dial_peers"] = self.unsafe_dial_peers
            routes["unsafe_dial_seeds"] = self.unsafe_dial_seeds
            routes["unsafe_dial_peers"] = self.unsafe_dial_peers
            routes["unsafe_flush_mempool"] = self.unsafe_flush_mempool
        return routes

    def ws_routes(self) -> dict:
        return {
            "subscribe": self.subscribe,
            "unsubscribe": self.unsubscribe,
            "unsubscribe_all": self.unsubscribe_all,
        }

    # -- info ------------------------------------------------------------

    def health(self) -> dict:
        return {}

    def status(self) -> dict:
        """(rpc/core/status.go Status)"""
        earliest = self.block_store.base()
        latest = self.block_store.height()
        latest_meta = (
            self.block_store.load_block_meta(latest) if latest else None
        )
        earliest_meta = (
            self.block_store.load_block_meta(earliest) if earliest else None
        )
        syncing = False
        if self.blocksync_reactor is not None:
            syncing = self.blocksync_reactor.is_syncing()
        return {
            "node_info": {
                "id": self.node_info.node_id if self.node_info else "",
                "listen_addr": (
                    self.node_info.listen_addr if self.node_info else ""
                ),
                "network": self.node_info.network if self.node_info else "",
                "version": __version__,
                "moniker": self.node_info.moniker if self.node_info else "",
                "channels": (
                    hexb(self.node_info.channels) if self.node_info else ""
                ),
            },
            "sync_info": {
                "latest_block_hash": (
                    hexb(latest_meta.block_id.hash) if latest_meta else ""
                ),
                "latest_app_hash": (
                    hexb(latest_meta.header.app_hash) if latest_meta else ""
                ),
                "latest_block_height": str(latest),
                "latest_block_time": (
                    time_rfc3339(latest_meta.header.time_ns)
                    if latest_meta
                    else ""
                ),
                "earliest_block_height": str(earliest),
                "earliest_block_hash": (
                    hexb(earliest_meta.block_id.hash) if earliest_meta else ""
                ),
                "catching_up": syncing,
            },
            "validator_info": {
                "address": (
                    hexb(self.pub_key.address()) if self.pub_key else ""
                ),
                "pub_key": (
                    {
                        "type": "tendermint/PubKeyEd25519",
                        "value": b64(self.pub_key.bytes()),
                    }
                    if self.pub_key
                    else None
                ),
                "voting_power": self._own_voting_power(),
            },
        }

    def light_sync(self, from_height=None, to_height=None) -> dict:
        """Serve a VERIFIED header range to a light client (no
        reference analog; light/serve.py): every header's +2/3 commit
        is re-verified server-side — through the verify queue's
        ``light_client`` lane, so concurrent clients' signatures
        coalesce into single launches — unless the trust-period-aware
        header cache already vouches for it."""
        server = self._light_server
        if server is None:
            with self._light_server_mtx:
                server = self._light_server
                if server is None:
                    if self.block_store is None or self.state_store is None:
                        raise ValueError(
                            "light_sync requires block and state stores"
                        )
                    from cometbft_tpu.light.provider import NodeProvider
                    from cometbft_tpu.light.serve import LightHeaderServer

                    chain_id = (
                        self.genesis.chain_id
                        if self.genesis is not None
                        else (
                            self.node_info.network
                            if self.node_info is not None else ""
                        )
                    )
                    server = LightHeaderServer(
                        chain_id,
                        NodeProvider(
                            chain_id, self.block_store, self.state_store,
                            self.evidence_pool,
                        ),
                    )
                    self._light_server = server
        frm = _to_int(from_height, "from_height")
        to = (
            _to_int(to_height, "to_height")
            if to_height is not None else frm
        )
        out = server.sync_range(frm, to)
        out["cache"] = server.cache.stats()
        return out

    def _own_voting_power(self) -> str:
        if self.pub_key is None or self.state_store is None:
            return "0"
        state = self.state_store.load()
        if state is None or state.validators is None:
            return "0"
        _, val = state.validators.get_by_address(self.pub_key.address())
        return str(val.voting_power) if val else "0"

    def net_info(self) -> dict:
        """(rpc/core/net.go NetInfo) — each peer carries its live
        ``connection_status`` (MConnection.status(): flowrate monitors,
        ping RTT, per-channel queue state, last error)."""
        peers = []
        if self.switch is not None:
            for peer in self.switch.peers.copy():
                peers.append(
                    {
                        "node_info": {
                            "id": peer.node_info.node_id,
                            "listen_addr": peer.node_info.listen_addr,
                            "moniker": peer.node_info.moniker,
                            "network": peer.node_info.network,
                        },
                        "is_outbound": peer.is_outbound(),
                        "connection_status": peer.status(),
                        "remote_ip": (
                            peer.socket_addr.host if peer.socket_addr else ""
                        ),
                    }
                )
        return {
            "listening": self.switch is not None
            and self.switch.is_running(),
            "listeners": (
                [str(self.switch.transport.listen_addr)]
                if self.switch and self.switch.transport.listen_addr
                else []
            ),
            "n_peers": str(len(peers)),
            "peers": peers,
        }

    def wire(self) -> dict:
        """Live wire-plane snapshot (no reference analog): the peer
        table with per-channel queue depth/bytes/fill ratio, pending
        send bytes, ping RTT, flowrate throughput, and the last
        connection error — the /net_info subset an operator greps
        when a peer stalls (docs/observability.md runbook)."""
        peers = []
        if self.switch is not None:
            for peer in self.switch.peers.copy():
                peers.append(
                    {
                        "peer_id": peer.id,
                        "moniker": peer.node_info.moniker,
                        "is_outbound": peer.is_outbound(),
                        "is_persistent": peer.is_persistent(),
                        "connection_status": peer.status(),
                    }
                )
        return {"n_peers": str(len(peers)), "peers": peers}

    def debug_flight(self) -> dict:
        """The flight recorder's bounded ring of recent replication
        events (utils/flight.py) — step transitions, WAL writes, ABCI
        calls, blocksync requests, peer errors.  Served on a live node
        AND in inspect mode, so the last ~2k events before a wedge are
        one curl away (docs/observability.md)."""
        from cometbft_tpu.utils.flight import FLIGHT

        return FLIGHT.export()

    def debug_perf(self) -> dict:
        """Device-health/perf snapshot (crypto/health.py): per-tier
        canary health + last probe latencies, launch-watchdog state,
        busy/idle utilization with the host/device overlap ratio, and
        the perf-ledger tail.  Served on a live node AND in inspect
        mode — a wedged accelerator is precisely when the node may not
        be running (docs/observability.md "Device-health plane")."""
        from cometbft_tpu.crypto.health import debug_perf_payload

        return debug_perf_payload()

    def debug_dispatch(self) -> dict:
        """Failover dispatch-ladder snapshot (crypto/dispatch.py):
        ladder order, per-tier demotion/cool-down/streak state, the
        recent transition trail, and the armed chaos plan.  Served on
        a live node AND in inspect mode — post-mortem of a device-lost
        node starts with the transition trail
        (docs/dispatch_ladder.md)."""
        from cometbft_tpu.crypto.dispatch import debug_dispatch_payload

        return debug_dispatch_payload()

    def debug_fleet(self) -> dict:
        """Fleet-plane rollup (utils/fleetobs.py): scrape the metrics
        servers named in CMT_TPU_FLEET_PEERS, merge SELF in-process,
        and return the per-node height/lag/tier/queue table plus the
        stitched cross-node height summary.  Served on a live node
        AND in inspect mode (docs/observability.md "Fleet plane")."""
        import os as _os

        from cometbft_tpu.utils import fleetobs

        scrapes = fleetobs.scrape_fleet(
            fleetobs.fleet_peer_targets(
                _os.environ.get("CMT_TPU_FLEET_PEERS")  # env ok: free-form peer list — fleet_peer_targets validates each address
            ),
            include_self=True,
            self_registry=self.metrics_registry,
        )
        return fleetobs.fleet_payload(scrapes)

    def debug_profile(self, seconds=None) -> dict:
        """Sampling-profiler payload (utils/profiler.py): span-tagged
        folded stacks, per-span sample rollup, and leaf-frame hotspots
        — ``?seconds=N`` limits to the trailing window.  Served on a
        live node AND in inspect mode; honest about being disabled
        (docs/observability.md "Attribution plane")."""
        from cometbft_tpu.utils.profiler import profile_payload

        return profile_payload(
            None if seconds is None else float(seconds)
        )

    def genesis_route(self) -> dict:
        import json as _json

        if len(self._genesis_chunks()) > 1:
            raise RPCError(
                -32603,
                "genesis response is too large, please use the "
                "genesis_chunked API instead",
            )
        return {"genesis": _json.loads(self.genesis.to_json())}

    _GENESIS_CHUNK_SIZE = 16 * 1024 * 1024  # net.go:16 genesisChunkSize

    def _genesis_chunks(self) -> list[str]:
        if self._gen_chunks is None:
            import base64 as _b64

            raw = self.genesis.to_json().encode()
            size = self._GENESIS_CHUNK_SIZE
            self._gen_chunks = [
                _b64.b64encode(raw[i : i + size]).decode()
                for i in range(0, max(len(raw), 1), size)
            ]
        return self._gen_chunks

    def genesis_chunked(self, chunk=0) -> dict:
        """(rpc/core/net.go:115 GenesisChunked)"""
        chunks = self._genesis_chunks()
        cid = _to_int(chunk, "chunk")
        if not 0 <= cid < len(chunks):
            raise RPCError(
                -32602,
                f"there are {len(chunks)} chunks, {cid} is invalid "
                f"(should be between 0 and {len(chunks) - 1})",
            )
        return {
            "chunk": str(cid),
            "total": str(len(chunks)),
            "data": chunks[cid],
        }

    # -- blocks -----------------------------------------------------------

    def _height_or_latest(self, height) -> int:
        h = _to_int(height, "height")
        if h == 0:
            h = self.block_store.height()
        if h < self.block_store.base() or h > self.block_store.height():
            raise RPCError(
                -32603,
                f"height {h} not available "
                f"(base {self.block_store.base()}, "
                f"height {self.block_store.height()})",
            )
        return h

    def blockchain(self, minHeight=None, maxHeight=None) -> dict:
        """(rpc/core/blocks.go BlockchainInfo) — metas, newest first,
        max 20."""
        base, height = self.block_store.base(), self.block_store.height()
        max_h = _to_int(maxHeight, "maxHeight") or height
        min_h = _to_int(minHeight, "minHeight") or base
        max_h = min(max_h, height)
        min_h = max(min_h, base, max_h - 19)
        metas = []
        for h in range(max_h, min_h - 1, -1):
            meta = self.block_store.load_block_meta(h)
            if meta is not None:
                metas.append(block_meta_json(meta))
        return {"last_height": str(height), "block_metas": metas}

    def block(self, height=None) -> dict:
        h = self._height_or_latest(height)
        blk = self.block_store.load_block(h)
        meta = self.block_store.load_block_meta(h)
        if blk is None or meta is None:
            raise RPCError(-32603, f"no block at height {h}")
        return {
            "block_id": block_id_json(meta.block_id),
            "block": block_json(blk),
        }

    def block_by_hash(self, hash=None) -> dict:
        blk = self.block_store.load_block_by_hash(_to_bytes(hash, "hash"))
        if blk is None:
            raise RPCError(-32603, "block not found")
        return self.block(height=blk.header.height)

    def header(self, height=None) -> dict:
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        from cometbft_tpu.rpc.serialize import header_json

        return {"header": header_json(meta.header)}

    def header_by_hash(self, hash=None) -> dict:
        blk = self.block_store.load_block_by_hash(_to_bytes(hash, "hash"))
        if blk is None:
            raise RPCError(-32603, "header not found")
        return self.header(height=blk.header.height)

    def commit(self, height=None) -> dict:
        h = self._height_or_latest(height)
        meta = self.block_store.load_block_meta(h)
        commit = self.block_store.load_block_commit(h)
        canonical = True
        if commit is None:
            commit = self.block_store.load_seen_commit(h)
            canonical = False
        if commit is None:
            raise RPCError(-32603, f"no commit for height {h}")
        return {
            "signed_header": {
                "header": block_meta_json(meta)["header"],
                "commit": commit_json(commit),
            },
            "canonical": canonical,
        }

    def block_results(self, height=None) -> dict:
        """(rpc/core/blocks.go BlockResults)"""
        h = self._height_or_latest(height)
        resp = self.state_store.load_finalize_block_response(h)
        if resp is None:
            raise RPCError(-32603, f"no results for height {h}")
        return {
            "height": str(h),
            "txs_results": [
                exec_tx_result_json(r) for r in resp.tx_results
            ],
            "finalize_block_events": [
                {
                    "type": e.type,
                    "attributes": [
                        {"key": a.key, "value": a.value, "index": a.index}
                        for a in e.attributes
                    ],
                }
                for e in resp.events
            ],
            "app_hash": hexb(resp.app_hash),
            "validator_updates": [
                {"pub_key_type": u.pub_key_type, "power": str(u.power)}
                for u in resp.validator_updates
            ],
        }

    def validators(self, height=None, page=None, per_page=None) -> dict:
        h = self._height_or_latest(height)
        vals = self.state_store.load_validators(h)
        per = min(_to_int(per_page, "per_page") or 30, 100)
        pg = max(_to_int(page, "page") or 1, 1)
        items = list(vals.validators)
        start = (pg - 1) * per
        return {
            "block_height": str(h),
            "validators": [
                validator_json(v) for v in items[start : start + per]
            ],
            "count": str(len(items[start : start + per])),
            "total": str(len(items)),
        }

    def consensus_params(self, height=None) -> dict:
        h = self._height_or_latest(height)
        params = self.state_store.load_consensus_params(h)
        return {
            "block_height": str(h),
            "consensus_params": params.to_json_dict(),
        }

    def consensus_state(self) -> dict:
        """(rpc/core/consensus.go GetConsensusState)"""
        rs = self.consensus.round_state()
        return {
            "round_state": {
                "height": str(rs["height"]),
                "round": rs["round"],
                "step": rs["step_name"],
                "start_time": time_rfc3339(rs["start_time_ns"]),
                "proposal_block_hash": (
                    hexb(rs["proposal_block"].hash())
                    if rs["proposal_block"]
                    else ""
                ),
                "locked_block_hash": (
                    hexb(rs["locked_block"].hash())
                    if rs["locked_block"]
                    else ""
                ),
                "valid_block_hash": (
                    hexb(rs["valid_block"].hash())
                    if rs["valid_block"]
                    else ""
                ),
            }
        }

    def dump_consensus_state(self) -> dict:
        rs = self.consensus.round_state()
        out = self.consensus_state()
        votes = rs["votes"]
        if votes is not None:
            prevotes = votes.prevotes(rs["round"])
            precommits = votes.precommits(rs["round"])
            out["round_state"]["height_vote_set"] = {
                "round": rs["round"],
                "prevotes_bit_array": (
                    repr(prevotes.bit_array()) if prevotes else ""
                ),
                "precommits_bit_array": (
                    repr(precommits.bit_array()) if precommits else ""
                ),
            }
        peers = []
        if self.switch is not None:
            from cometbft_tpu.consensus.reactor import PEER_STATE_KEY

            for peer in self.switch.peers.copy():
                ps = peer.get(PEER_STATE_KEY)
                if ps is None:
                    continue
                prs = ps.snapshot()
                peers.append(
                    {
                        "node_address": peer.id,
                        "peer_state": {
                            "height": str(prs.height),
                            "round": prs.round,
                            "step": prs.step,
                        },
                    }
                )
        out["peers"] = peers
        return out

    # -- txs --------------------------------------------------------------

    def tx(self, hash=None, prove=False) -> dict:
        """(rpc/core/tx.go Tx)"""
        if self.tx_indexer is None:
            raise RPCError(-32603, "tx indexing is disabled")
        entry = self.tx_indexer.get(_to_bytes(hash, "hash"))
        if entry is None:
            raise RPCError(-32603, "tx not found")
        return {
            "hash": hexb(tx_hash(entry["tx"])),
            "height": str(entry["height"]),
            "index": entry["index"],
            "tx_result": exec_tx_result_json(entry["result"]),
            "tx": b64(entry["tx"]),
        }

    def tx_search(self, query=None, page=None, per_page=None,
                  prove=False, order_by=None) -> dict:
        if self.tx_indexer is None:
            raise RPCError(-32603, "tx indexing is disabled")
        if not query:
            raise RPCError(-32602, "query cannot be empty")
        try:
            q = Query.parse(query)
        except Exception as exc:
            raise RPCError(-32602, f"bad query: {exc}") from None
        per = min(_to_int(per_page, "per_page") or 30, 100)
        pg = max(_to_int(page, "page") or 1, 1)
        entries = self.tx_indexer.search(q, limit=pg * per)
        window = entries[(pg - 1) * per : pg * per]
        return {
            "txs": [
                {
                    "hash": hexb(tx_hash(e["tx"])),
                    "height": str(e["height"]),
                    "index": e["index"],
                    "tx_result": exec_tx_result_json(e["result"]),
                    "tx": b64(e["tx"]),
                }
                for e in window
            ],
            "total_count": str(len(entries)),
        }

    def block_search(self, query=None, page=None, per_page=None,
                     order_by=None) -> dict:
        if self.block_indexer is None:
            raise RPCError(-32603, "block indexing is disabled")
        if not query:
            raise RPCError(-32602, "query cannot be empty")
        heights = self.block_indexer.search(Query.parse(query), limit=1000)
        per = min(_to_int(per_page, "per_page") or 30, 100)
        pg = max(_to_int(page, "page") or 1, 1)
        window = heights[(pg - 1) * per : pg * per]
        blocks = []
        for h in window:
            try:
                blocks.append(self.block(height=h))
            except RPCError:
                continue
        return {"blocks": blocks, "total_count": str(len(heights))}

    def unconfirmed_txs(self, limit=None) -> dict:
        lim = min(_to_int(limit, "limit") or 30, 100)
        txs = self.mempool.reap_max_txs(lim)
        return {
            "n_txs": str(len(txs)),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
            "txs": [b64(tx) for tx in txs],
        }

    def unconfirmed_tx(self, hash=None) -> dict:
        """One mempool tx by hash (rpc/core/mempool.go UnconfirmedTx,
        routes.go:40)."""
        h = _to_bytes(hash, "hash")
        tx = self.mempool.get_tx_by_hash(h)
        if tx is None:
            raise RPCError(-32603, f"tx {h.hex()} not found in mempool")
        return {"tx": b64(tx)}

    def num_unconfirmed_txs(self) -> dict:
        return {
            "n_txs": str(self.mempool.size()),
            "total": str(self.mempool.size()),
            "total_bytes": str(self.mempool.size_bytes()),
        }

    # -- broadcast (rpc/core/mempool.go) ----------------------------------

    def _ingest_pool(self) -> "_AsyncTxPool":
        with self._async_pool_mtx:
            if self._async_pool is None:
                self._async_pool = _AsyncTxPool(
                    self._check_tx_quiet, metrics=self.metrics
                )
            return self._async_pool

    def broadcast_tx_async(self, tx=None) -> dict:
        raw = _to_bytes(tx, "tx")
        self._ingest_pool().offer(raw)
        return {"code": 0, "data": "", "log": "", "hash": hexb(tx_hash(raw))}

    def _check_tx_quiet(self, raw: bytes) -> None:
        try:
            with trustguard.wire_context("rpc_tx_async"):
                self.mempool.check_tx(raw)
        except Exception as exc:  # noqa: BLE001
            # async broadcast promises no admission verdict, but a
            # swallowed rejection on the RPC ingress path must leave a
            # breadcrumb (PR 9 convention)
            _FLIGHT.record(
                "rpc_async_checktx_rejected", err=type(exc).__name__
            )

    def check_tx(self, tx=None) -> dict:
        """Run CheckTx against the app WITHOUT adding to the mempool
        (rpc/core/mempool.go:211 CheckTx)."""
        from cometbft_tpu.abci.types import CHECK_TX_TYPE_CHECK, CheckTxRequest

        raw = _to_bytes(tx, "tx")
        res = self.proxy_app.mempool.check_tx(
            CheckTxRequest(tx=raw, type=CHECK_TX_TYPE_CHECK)
        )
        return {
            "code": res.code,
            "data": b64(res.data) if res.data else "",
            "log": res.log,
            "codespace": res.codespace,
            "gas_wanted": str(res.gas_wanted),
            "gas_used": str(res.gas_used),
        }

    def unsafe_flush_mempool(self) -> dict:
        """(mempool.go UnsafeFlushMempool) — drop every pending tx."""
        self.mempool.flush()
        return {}

    def unsafe_dial_seeds(self, seeds=None) -> dict:
        """(rpc/core/net.go:50 UnsafeDialSeeds)"""
        from cometbft_tpu.p2p.netaddr import parse_peer_list

        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        spec = ",".join(seeds) if isinstance(seeds, list) else str(seeds)
        addrs = parse_peer_list(spec)
        self.switch.dial_peers_async(addrs, persistent=False)
        return {"log": "Dialing seeds in progress. See /net_info for details"}

    def unsafe_dial_peers(self, peers=None, persistent=False,
                          unconditional=False, private=False) -> dict:
        """(rpc/core/net.go:63 UnsafeDialPeers)"""
        from cometbft_tpu.p2p.netaddr import parse_peer_list

        if not peers:
            raise RPCError(-32602, "no peers provided")
        spec = ",".join(peers) if isinstance(peers, list) else str(peers)
        addrs = parse_peer_list(spec)
        self.switch.dial_peers_async(
            addrs, persistent=bool(persistent)
        )
        return {"log": "Dialing peers in progress. See /net_info for details"}

    @trustguard.guarded_seam("rpc_tx")
    def broadcast_tx_sync(self, tx=None) -> dict:
        raw = _to_bytes(tx, "tx")
        try:
            res = self.mempool.check_tx(raw)
        except Exception as exc:  # noqa: BLE001
            raise RPCError(-32603, f"tx rejected: {exc}") from None
        return {
            "code": res.code,
            "data": b64(res.data) if res.data else "",
            "log": res.log,
            "hash": hexb(tx_hash(raw)),
        }

    @trustguard.guarded_seam("rpc_tx")
    def broadcast_tx_commit(self, tx=None, timeout=10.0) -> dict:
        """(rpc/core/mempool.go:76 BroadcastTxCommit) — subscribe to the
        tx event BEFORE CheckTx so the commit can't be missed."""
        raw = _to_bytes(tx, "tx")
        h = tx_hash(raw)
        sub = self.event_bus.subscribe(
            f"txc-{h.hex()[:16]}",
            Query.parse(f"tm.event='{EVENT_TX}' AND tx.hash='{h.hex().upper()}'"),
            capacity=1,
        )
        try:
            check = self.mempool.check_tx(raw)
            if check.code != 0:
                return {
                    "check_tx": {"code": check.code, "log": check.log},
                    "tx_result": None,
                    "hash": hexb(h),
                    "height": "0",
                }
            try:
                msg = sub.next(timeout=float(timeout))
            except TimeoutError:
                raise RPCError(
                    -32603, "timed out waiting for tx to be committed"
                ) from None
            data: EventDataTx = msg.data
            return {
                "check_tx": {"code": check.code, "log": check.log},
                "tx_result": exec_tx_result_json(data.result),
                "hash": hexb(h),
                "height": str(data.height),
            }
        except RPCError:
            raise
        except Exception as exc:  # noqa: BLE001
            raise RPCError(-32603, f"tx rejected: {exc}") from None
        finally:
            try:
                self.event_bus.unsubscribe_all(f"txc-{h.hex()[:16]}")
            except Exception:  # noqa: BLE001
                pass

    @trustguard.guarded_seam("rpc_evidence")
    def broadcast_evidence(self, evidence=None) -> dict:
        from cometbft_tpu.types import codec

        ev = codec.decode_evidence(_to_bytes(evidence, "evidence"))
        self.evidence_pool.add_evidence(ev)
        return {"hash": hexb(ev.hash())}

    # -- abci -------------------------------------------------------------

    def abci_query(self, path=None, data=None, height=None,
                   prove=False) -> dict:
        resp = self.proxy_app.query.query(
            QueryRequest(
                path=path or "",
                data=_to_bytes(data, "data") if data else b"",
                height=_to_int(height, "height"),
                prove=bool(prove),
            )
        )
        out = {
            "code": resp.code,
            "log": resp.log,
            "key": b64(resp.key) if resp.key else None,
            "value": b64(resp.value) if resp.value else None,
            "height": str(resp.height),
        }
        if resp.proof_ops:
            out["proofOps"] = {
                "ops": [
                    {
                        "type": op.type,
                        "key": b64(op.key),
                        "data": b64(op.data),
                    }
                    for op in resp.proof_ops
                ]
            }
        return {"response": out}

    def abci_info(self) -> dict:
        resp = self.proxy_app.query.info(InfoRequest())
        return {
            "response": {
                "data": resp.data,
                "version": resp.version,
                "app_version": str(resp.app_version),
                "last_block_height": str(resp.last_block_height),
                "last_block_app_hash": b64(resp.last_block_app_hash),
            }
        }

    # -- subscriptions (WS only; rpc/core/events.go) ----------------------

    def subscribe(self, query=None, _ws_ctx=None) -> dict:
        if _ws_ctx is None:
            raise RPCError(-32603, "subscribe requires a websocket")
        if not query:
            raise RPCError(-32602, "query cannot be empty")
        q = Query.parse(query)
        sub = self.event_bus.subscribe(
            _ws_ctx.client_id, q, capacity=SUBSCRIPTION_BUFFER
        )
        with self._subs_mtx:
            self._subs.setdefault(_ws_ctx.client_id, {})[query] = sub
            self._set_ws_subscriptions_locked()
        threading.Thread(
            target=self._pump_subscription,
            args=(sub, q, _ws_ctx, query),
            daemon=True,
        ).start()
        return {}

    def _pump_subscription(self, sub, q, ws_ctx, query_str) -> None:
        try:
            self._pump_subscription_loop(sub, ws_ctx, query_str)
        finally:
            # a pubsub-canceled subscription (slow consumer) must come
            # off the books too, or ws_subscriptions keeps counting it
            # as live while subscriber_dropped_total says otherwise;
            # idempotent vs unsubscribe/drop_client (both pop first)
            with self._subs_mtx:
                qs = self._subs.get(ws_ctx.client_id)
                if qs is not None and qs.get(query_str) is sub:
                    del qs[query_str]
                    if not qs:
                        del self._subs[ws_ctx.client_id]
                    self._set_ws_subscriptions_locked()

    def _pump_subscription_loop(self, sub, ws_ctx, query_str) -> None:
        while ws_ctx.alive:
            try:
                msg = sub.next(timeout=0.2)
            except TimeoutError:
                continue
            except Exception:  # noqa: BLE001 — canceled
                return
            payload = {
                "jsonrpc": "2.0",
                "id": -1,
                "result": {
                    "query": query_str,
                    "data": {
                        "type": type(msg.data).__name__,
                        "value": _event_data_json(msg.data),
                    },
                    "events": msg.events,
                },
            }
            if not ws_ctx.send(payload):
                return

    def _set_ws_subscriptions_locked(self) -> None:
        self.metrics.ws_subscriptions.set(
            sum(len(qs) for qs in self._subs.values())
        )

    def unsubscribe(self, query=None, _ws_ctx=None) -> dict:
        if _ws_ctx is None:
            raise RPCError(-32603, "unsubscribe requires a websocket")
        with self._subs_mtx:
            self._subs.get(_ws_ctx.client_id, {}).pop(query, None)
            self._set_ws_subscriptions_locked()
        self.event_bus.unsubscribe(_ws_ctx.client_id, Query.parse(query))
        return {}

    def unsubscribe_all(self, _ws_ctx=None) -> dict:
        if _ws_ctx is None:
            raise RPCError(-32603, "unsubscribe_all requires a websocket")
        self.drop_client(_ws_ctx.client_id)
        return {}

    def drop_client(self, client_id: str) -> None:
        with self._subs_mtx:
            self._subs.pop(client_id, None)
            self._set_ws_subscriptions_locked()
        try:
            self.event_bus.unsubscribe_all(client_id)
        except Exception:  # noqa: BLE001
            pass


def _event_data_json(data) -> dict:
    """Best-effort JSON projection of event payloads."""
    from cometbft_tpu.types.event_bus import (
        EventDataNewBlock,
        EventDataNewBlockHeader,
        EventDataTx,
        EventDataVote,
    )
    from cometbft_tpu.rpc.serialize import header_json

    if isinstance(data, EventDataNewBlock):
        return {
            "block": block_json(data.block),
            "block_id": block_id_json(data.block_id),
        }
    if isinstance(data, EventDataNewBlockHeader):
        return {"header": header_json(data.header)}
    if isinstance(data, EventDataTx):
        return {
            "height": str(data.height),
            "index": data.index,
            "tx": b64(data.tx),
            "result": exec_tx_result_json(data.result),
        }
    if isinstance(data, EventDataVote):
        v = data.vote
        return {
            "type": v.type,
            "height": str(v.height),
            "round": v.round,
            "validator_address": hexb(v.validator_address),
        }
    if hasattr(data, "__dict__"):
        return {
            k: str(v) for k, v in vars(data).items() if not k.startswith("_")
        }
    return {"repr": repr(data)}


__all__ = ["Environment", "SUBSCRIPTION_BUFFER"]
