"""RPC plane — JSON-RPC/HTTP/WebSocket API (reference: rpc/)."""

from cometbft_tpu.rpc.client import HTTPClient, LocalClient
from cometbft_tpu.rpc.core import Environment
from cometbft_tpu.rpc.jsonrpc import JSONRPCServer, RPCError

__all__ = [
    "Environment",
    "HTTPClient",
    "JSONRPCServer",
    "LocalClient",
    "RPCError",
]
