"""JSON-RPC 2.0 over HTTP + WebSocket (reference: rpc/jsonrpc/).

- HTTP POST with a JSON-RPC envelope (single or batch) →
  rpc/jsonrpc/server/http_json_handler.go;
- HTTP GET ``/route?arg=val`` URI style →
  rpc/jsonrpc/server/http_uri_handler.go;
- ``/websocket`` upgraded via RFC 6455 (hand-rolled: this image has no
  websocket lib) carrying the same envelopes, used for event
  subscriptions → rpc/jsonrpc/server/ws_handler.go.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.service import BaseService
from cometbft_tpu.utils.trace import TRACER
from cometbft_tpu.utils import sync as cmtsync

# JSON-RPC error codes (rpc/jsonrpc/types/types.go)
ERR_PARSE = -32700
ERR_INVALID_REQUEST = -32600
ERR_METHOD_NOT_FOUND = -32601
ERR_INVALID_PARAMS = -32602
ERR_INTERNAL = -32603

_WS_MAGIC = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


def make_response(req_id, result=None, error: RPCError | None = None) -> dict:
    if error is not None:
        return {
            "jsonrpc": "2.0",
            "id": req_id,
            "error": {
                "code": error.code,
                "message": error.message,
                "data": error.data,
            },
        }
    return {"jsonrpc": "2.0", "id": req_id, "result": result}


# -- WebSocket framing (RFC 6455) ---------------------------------------

def ws_accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_MAGIC).encode()).digest()
    return base64.b64encode(digest).decode()


def ws_read_frame(rfile) -> tuple[int, bytes] | None:
    """Returns (opcode, payload); None on EOF/close/truncation/
    oversize — adversarial streams must never surface struct.error."""
    head = rfile.read(2)
    if len(head) < 2:
        return None
    b1, b2 = head
    opcode = b1 & 0x0F
    masked = bool(b2 & 0x80)
    length = b2 & 0x7F
    if length == 126:
        ext = rfile.read(2)
        if len(ext) < 2:
            return None
        length = struct.unpack(">H", ext)[0]
    elif length == 127:
        ext = rfile.read(8)
        if len(ext) < 8:
            return None
        length = struct.unpack(">Q", ext)[0]
    if length > 16 * 1024 * 1024:
        return None
    if masked:
        mask = rfile.read(4)
        if len(mask) < 4:
            return None
    else:
        mask = b""
    payload = rfile.read(length)
    if len(payload) < length:
        return None
    if masked:
        payload = bytes(
            c ^ mask[i % 4] for i, c in enumerate(payload)
        )
    if opcode == 0x8:  # close
        return None
    return opcode, payload


def ws_write_frame(wfile, payload: bytes, opcode: int = 0x1) -> None:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < 1 << 16:
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    wfile.write(header + payload)
    wfile.flush()


class JSONRPCServer(BaseService):
    """(rpc/jsonrpc/server/http_server.go Serve)

    ``routes``: name → callable(**kwargs) returning a JSON-able dict
    (raise RPCError for structured failures).  ``ws_routes``: routes
    that need the live connection (subscribe/unsubscribe) — they get a
    ``_ws_ctx`` kwarg exposing ``send(dict)`` and ``client_id``.
    """

    def __init__(
        self,
        routes: dict,
        ws_routes: dict | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        on_ws_disconnect=None,
        metrics=None,
        logger: Logger | None = None,
    ):
        super().__init__(
            name="jsonrpc",
            logger=logger or default_logger().with_fields(module="rpc-server"),
        )
        from cometbft_tpu.metrics import RPCMetrics

        self.routes = routes
        self.ws_routes = ws_routes or {}
        self.on_ws_disconnect = on_ws_disconnect
        self.metrics = metrics if metrics is not None else RPCMetrics()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # headers and body flush as separate segments; without
            # NODELAY a kept-alive connection pays Nagle + delayed-ACK
            # (~40 ms) per response
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # route through our logger
                outer.logger.debug("http " + (fmt % args))

            def _send_json(self, obj, status=200):
                body = json.dumps(obj).encode()
                outer.metrics.response_size_bytes.observe(len(body))
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                try:
                    req = json.loads(raw) if raw else {}
                except ValueError:  # JSONDecodeError or UnicodeDecodeError
                    self._send_json(
                        make_response(
                            None, error=RPCError(ERR_PARSE, "parse error")
                        )
                    )
                    return
                if isinstance(req, list):
                    self._send_json([outer._dispatch(r) for r in req])
                else:
                    self._send_json(outer._dispatch(req))

            def do_GET(self):
                url = urlparse(self.path)
                route = url.path.strip("/")
                if route == "websocket":
                    self._upgrade_websocket()
                    return
                if route == "":
                    self._send_json(
                        {"routes": sorted(outer.routes) + sorted(outer.ws_routes)}
                    )
                    return
                params = {k: _parse_uri_arg(v) for k, v in parse_qsl(url.query)}
                self._send_json(
                    outer._dispatch(
                        {
                            "jsonrpc": "2.0",
                            "id": -1,
                            "method": route,
                            "params": params,
                        }
                    )
                )

            def _upgrade_websocket(self):
                key = self.headers.get("Sec-WebSocket-Key", "")
                if not key:
                    self.send_error(400, "missing websocket key")
                    return
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", ws_accept_key(key))
                self.end_headers()
                outer._serve_websocket(self)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, req: dict, ws_ctx=None) -> dict:
        """Instrumented wrapper: in-flight gauge, per-route latency and
        outcome, and an rpc_dispatch span around the handler.  Unknown
        methods collapse to route="_unknown" so a client probing random
        names can't mint unbounded label children."""
        method = req.get("method", "") if isinstance(req, dict) else ""
        known = isinstance(method, str) and (
            method in self.routes or method in self.ws_routes
        )
        route = method if known else "_unknown"
        m = self.metrics
        m.requests_in_flight.inc()
        t0 = time.perf_counter()
        # default covers handlers that raise something other than
        # RPCError/TypeError: the exception propagates, but the route
        # must still count (else requests_total and the duration
        # histogram permanently disagree for crashed requests)
        status = "error"
        try:
            with TRACER.span("rpc_dispatch", cat="rpc", route=route):
                resp = self._dispatch_inner(req, ws_ctx)
            status = "error" if "error" in resp else "ok"
            return resp
        finally:
            m.requests_in_flight.inc(-1)
            m.request_duration_seconds.labels(route=route).observe(
                time.perf_counter() - t0
            )
            m.requests_total.labels(route=route, status=status).inc()

    def _dispatch_inner(self, req: dict, ws_ctx=None) -> dict:
        # the body may decode to null / a scalar / a list element that
        # isn't an object — answer Invalid Request, never crash the
        # connection (fuzz: rpc_jsonrpc_server_test.go)
        if not isinstance(req, dict):
            return make_response(
                -1,
                error=RPCError(
                    ERR_INVALID_REQUEST, "request must be an object"
                ),
            )
        req_id = req.get("id", -1)
        if not isinstance(req_id, (str, int, float, type(None))):
            req_id = -1  # ids must be JSON primitives (rfc: string/number)
        method = req.get("method", "")
        if not isinstance(method, str):
            return make_response(
                req_id,
                error=RPCError(ERR_INVALID_REQUEST, "method must be a string"),
            )
        params = req.get("params") or {}
        if not isinstance(params, dict):
            return make_response(
                req_id,
                error=RPCError(ERR_INVALID_PARAMS, "params must be a map"),
            )
        fn = None
        if ws_ctx is not None and method in self.ws_routes:
            fn = self.ws_routes[method]
            params = dict(params, _ws_ctx=ws_ctx)
        elif method in self.routes:
            fn = self.routes[method]
        if fn is None:
            return make_response(
                req_id,
                error=RPCError(
                    ERR_METHOD_NOT_FOUND, f"unknown method {method!r}"
                ),
            )
        try:
            return make_response(req_id, result=fn(**params))
        except RPCError as exc:
            return make_response(req_id, error=exc)
        except TypeError as exc:
            return make_response(
                req_id, error=RPCError(ERR_INVALID_PARAMS, str(exc))
            )
        except Exception as exc:  # noqa: BLE001 — handler bug or bad state
            # correlation id in both the log line and the client error
            # (internal/rpctrace: operators grep logs by the id a
            # caller reports instead of guessing among errors)
            import uuid as _uuid

            trace_id = _uuid.uuid4().hex[:16]
            self.logger.error("rpc handler error", method=method,
                              err=repr(exc), trace_id=trace_id)
            return make_response(
                req_id,
                error=RPCError(
                    ERR_INTERNAL,
                    f"internal error (trace {trace_id})",
                    str(exc),
                ),
            )

    # -- websocket session (ws_handler.go wsConnection) -------------------

    def _serve_websocket(self, handler) -> None:
        send_mtx = cmtsync.Mutex()
        client_id = f"ws-{id(handler)}"

        class WSContext:
            def __init__(self):
                self.client_id = client_id
                self.alive = True

            def send(self, obj: dict) -> bool:
                try:
                    with send_mtx:
                        ws_write_frame(
                            handler.wfile, json.dumps(obj).encode()
                        )
                    return True
                except OSError:
                    self.alive = False
                    return False

        ctx = WSContext()
        self.metrics.ws_connections.inc()
        try:
            while not self._quit.is_set():
                frame = ws_read_frame(handler.rfile)
                if frame is None:
                    return
                opcode, payload = frame
                if opcode == 0x9:  # ping → pong
                    with send_mtx:
                        ws_write_frame(handler.wfile, payload, opcode=0xA)
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    ctx.send(make_response(
                        None, error=RPCError(ERR_PARSE, "parse error")
                    ))
                    continue
                ctx.send(self._dispatch(req, ws_ctx=ctx))
        except OSError:
            pass
        finally:
            ctx.alive = False
            self.metrics.ws_connections.inc(-1)
            if self.on_ws_disconnect is not None:
                try:
                    self.on_ws_disconnect(client_id)
                except Exception:  # noqa: BLE001
                    pass

    # -- lifecycle --------------------------------------------------------

    def on_start(self) -> None:
        threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="jsonrpc-http",
            daemon=True,
        ).start()
        self.logger.info("rpc server listening", host=self.host,
                         port=self.port)

    def on_stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class QuotedStr(str):
    """A URI arg that arrived explicitly quoted.  The reference's URI
    handler gives quoted args raw-string semantics for byte-typed
    params (`tx="name=ada"` means the literal bytes b"name=ada", while
    unquoted args must be hex/base64 — http_uri_handler.go arg
    parsing); this marker carries the quoted-ness to _to_bytes without
    changing anything for string-typed params."""


def _parse_uri_arg(value: str):
    """URI args arrive as strings; JSON-decode the obvious scalars
    (http_uri_handler.go arg parsing)."""
    if value in ("true", "false"):
        return value == "true"
    try:
        decoded = json.loads(value)
    except (json.JSONDecodeError, ValueError):
        return value
    if isinstance(decoded, str) and value.startswith('"'):
        return QuotedStr(decoded)
    return decoded


__all__ = [
    "ERR_INTERNAL",
    "ERR_INVALID_PARAMS",
    "ERR_INVALID_REQUEST",
    "ERR_METHOD_NOT_FOUND",
    "ERR_PARSE",
    "JSONRPCServer",
    "RPCError",
    "make_response",
    "ws_accept_key",
    "ws_read_frame",
    "ws_write_frame",
]
