"""RPC clients (reference: rpc/client/).

- ``HTTPClient``: JSON-RPC over HTTP via urllib (rpc/client/http);
- ``LocalClient``: direct calls into an Environment, no network
  (rpc/client/local) — the embedding-friendly client.

Both expose the route names as methods via ``call``.
"""

from __future__ import annotations

import json
import urllib.request

from cometbft_tpu.rpc.jsonrpc import RPCError


class HTTPClient:
    """(rpc/client/http/http.go HTTP)"""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._next_id = 0

    def call(self, method: str, **params):
        self._next_id += 1
        payload = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": self._next_id,
                "method": method,
                "params": params,
            }
        ).encode()
        req = urllib.request.Request(
            self.base_url,
            data=payload,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = json.loads(resp.read())
        if "error" in body and body["error"]:
            err = body["error"]
            raise RPCError(
                err.get("code", -32603),
                err.get("message", "unknown"),
                err.get("data", ""),
            )
        return body["result"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(**params):
            return self.call(name, **params)

        return call


class LocalClient:
    """(rpc/client/local/local.go Local)"""

    def __init__(self, env):
        self.env = env
        self._routes = env.routes()

    def call(self, method: str, **params):
        fn = self._routes.get(method)
        if fn is None:
            raise RPCError(-32601, f"unknown method {method!r}")
        return fn(**params)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(**params):
            return self.call(name, **params)

        return call


__all__ = ["HTTPClient", "LocalClient"]
