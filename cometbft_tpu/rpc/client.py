"""RPC clients (reference: rpc/client/).

- ``HTTPClient``: JSON-RPC over persistent HTTP/1.1 connections
  (rpc/client/http);
- ``LocalClient``: direct calls into an Environment, no network
  (rpc/client/local) — the embedding-friendly client;
- ``WSClient``: JSON-RPC over a WebSocket with live event
  subscriptions (rpc/client/http WSEvents).

Both expose the route names as methods via ``call``.
"""

from __future__ import annotations

import base64
import json
import os
import queue
import socket
import threading

from cometbft_tpu.rpc.jsonrpc import RPCError
from cometbft_tpu.utils import sync as cmtsync


class HTTPClient:
    """(rpc/client/http/http.go HTTP)

    Connections are persistent per thread (the server speaks HTTP/1.1
    keep-alive): urllib's one-TCP-handshake-per-call costs real CPU on
    both ends at load — the QA campaign's saturation runs spend it
    thousands of times a minute. A dead kept-alive socket is retried
    once on a fresh connection."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        import urllib.parse

        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._next_id = 0
        parts = urllib.parse.urlsplit(self.base_url)
        if parts.scheme not in ("http", "https"):
            raise ValueError(
                f"unsupported url {base_url!r} (need http:// or https://)"
            )
        self._tls = parts.scheme == "https"
        if not parts.hostname:
            raise ValueError(f"no host in url {base_url!r}")
        self._host = parts.hostname
        self._port = parts.port or (443 if self._tls else 80)
        self._path = parts.path or "/"
        self._local = threading.local()

    #: stale kept-alive socket signatures — the server closed the idle
    #: connection BEFORE reading our request, so a resend cannot
    #: double-submit. Timeouts and mid-response failures are NOT here:
    #: the server may already have processed the (non-idempotent) call.
    #: NB: no http.client.RemoteDisconnected entry — it subclasses
    #: ConnectionResetError, so it still matches this tuple; listing it
    #: was dead weight.  It is raised by getresponse() AFTER the
    #: request was written (sent=True), so what actually keeps it from
    #: being retried is the ``not sent`` gate below — that is the
    #: common stale keep-alive shape (server idle-closed before
    #: reading; our send lands in the socket buffer, the read gets
    #: EOF), and it intentionally surfaces to the caller: by then the
    #: server may have read and processed the call.
    _RETRYABLE = (
        BrokenPipeError,
        ConnectionResetError,
        ConnectionRefusedError,
    )

    def _request(self, payload: bytes) -> dict:
        import http.client
        conn = getattr(self._local, "conn", None)
        reused = conn is not None
        while True:
            if conn is None:
                cls = (
                    http.client.HTTPSConnection
                    if self._tls
                    else http.client.HTTPConnection
                )
                conn = cls(self._host, self._port, timeout=self.timeout)
                conn.connect()
                # http.client writes headers and body as two segments;
                # on a long-lived connection Nagle + delayed ACK stalls
                # the second ~40 ms per request (fresh sockets dodge it
                # via initial quickack, which is why urllib didn't show
                # it) — measured 22 tx/s vs 186 on the loadtime path
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                )
                self._local.conn = conn
            sent = False
            try:
                conn.request(
                    "POST",
                    self._path,
                    body=payload,
                    headers={"Content-Type": "application/json"},
                )
                sent = True
                resp = conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise RPCError(
                        -32603, f"http status {resp.status}",
                        body.decode(errors="replace")[:200],
                    )
                return json.loads(body)
            except Exception as exc:
                try:
                    conn.close()
                except Exception:
                    pass
                self._local.conn = conn = None
                # retry ONCE, and only when a REUSED connection failed
                # during the SEND itself — before the request could
                # have reached the server. Anything after conn.request
                # returned (getresponse, read), a timeout, or a fresh-
                # connection failure surfaces immediately: the server
                # may already have processed the call, and resending a
                # non-idempotent RPC could double-submit it.
                if (
                    reused
                    and not sent
                    and isinstance(exc, HTTPClient._RETRYABLE)
                ):
                    reused = False
                    continue
                raise

    def call(self, method: str, **params):
        self._next_id += 1
        payload = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": self._next_id,
                "method": method,
                "params": params,
            }
        ).encode()
        body = self._request(payload)
        if "error" in body and body["error"]:
            err = body["error"]
            raise RPCError(
                err.get("code", -32603),
                err.get("message", "unknown"),
                err.get("data", ""),
            )
        return body["result"]

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(**params):
            return self.call(name, **params)

        return call


class LocalClient:
    """(rpc/client/local/local.go Local)"""

    def __init__(self, env):
        self.env = env
        self._routes = env.routes()

    def call(self, method: str, **params):
        fn = self._routes.get(method)
        if fn is None:
            raise RPCError(-32601, f"unknown method {method!r}")
        return fn(**params)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def call(**params):
            return self.call(name, **params)

        return call


__all__ = ["HTTPClient", "LocalClient", "WSClient", "WSSubscription"]


class WSSubscription:
    """One active query subscription on a WSClient
    (rpc/client/http WSEvents subscription channel)."""

    def __init__(self, query: str):
        self.query = query
        self._queue: "queue.Queue[dict]" = queue.Queue(maxsize=1024)
        self.closed = False

    def next(self, timeout: float | None = None) -> dict:
        """Next event payload: {"query", "data", "events"}; raises
        TimeoutError when nothing arrives in time."""
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(f"no event within {timeout}s") from None
        if item is None:
            raise ConnectionError("websocket closed")
        return item

    def __iter__(self):
        while True:
            try:
                yield self.next(timeout=None)
            except ConnectionError:
                return


class WSClient:
    """JSON-RPC over a WebSocket with event subscriptions
    (reference: rpc/client/http/http.go WSEvents + rpc/jsonrpc/client/
    ws_client.go).  Wire format matches our server: text frames of
    JSON-RPC objects; subscription events arrive with id == -1 and a
    result.query naming the subscription."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        self._sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\n"
                f"Host: {host}:{port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        self._rfile = self._sock.makefile("rb")
        status = self._rfile.readline()
        if b"101" not in status:
            raise ConnectionError(f"websocket upgrade refused: {status!r}")
        accept = None
        while True:
            line = self._rfile.readline().strip()
            if not line:
                break
            name, _, value = line.partition(b":")
            if name.strip().lower() == b"sec-websocket-accept":
                accept = value.strip().decode()
        from cometbft_tpu.rpc.jsonrpc import ws_accept_key

        if accept != ws_accept_key(key):
            raise ConnectionError("bad websocket accept key")
        self._sock.settimeout(None)
        self._next_id = 0
        self._pending: dict[int, queue.Queue] = {}
        self._subs: dict[str, WSSubscription] = {}
        self._mtx = cmtsync.Mutex()
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()

    # -- framing (client frames are masked per RFC 6455) ----------------

    def _send_frame(self, payload: bytes) -> None:
        import struct as _struct

        header = bytes([0x81])  # FIN | text
        n = len(payload)
        mask = os.urandom(4)
        if n < 126:
            header += bytes([0x80 | n])
        elif n < 1 << 16:
            header += bytes([0x80 | 126]) + _struct.pack(">H", n)
        else:
            header += bytes([0x80 | 127]) + _struct.pack(">Q", n)
        masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        with self._mtx:
            self._sock.sendall(header + mask + masked)

    def _read_loop(self) -> None:
        from cometbft_tpu.rpc.jsonrpc import ws_read_frame

        try:
            while not self._closed:
                frame = ws_read_frame(self._rfile)
                if frame is None:
                    break
                opcode, payload = frame
                if opcode != 0x1:
                    continue
                try:
                    msg = json.loads(payload)
                except ValueError:
                    continue
                self._route(msg)
        except Exception:  # noqa: BLE001 — socket torn down
            pass
        finally:
            self._shutdown()

    def _route(self, msg: dict) -> None:
        msg_id = msg.get("id")
        result = msg.get("result") or {}
        if msg_id == -1 and isinstance(result, dict) and "query" in result:
            sub = self._subs.get(result["query"])
            if sub is not None:
                try:
                    sub._queue.put_nowait(result)
                except queue.Full:
                    pass  # slow consumer: drop (server buffers too)
            return
        q = self._pending.pop(msg_id, None)
        if q is not None:
            q.put(msg)

    def _shutdown(self) -> None:
        self._closed = True
        for sub in self._subs.values():
            while True:
                try:
                    sub._queue.put_nowait(None)
                    break
                except queue.Full:
                    # evict one event so the close sentinel always
                    # lands — a full queue must not hide the shutdown
                    try:
                        sub._queue.get_nowait()
                    except queue.Empty:
                        pass
        for q in self._pending.values():
            q.put(None)

    # -- calls -----------------------------------------------------------

    def call(self, method: str, **params):
        if self._closed:
            raise ConnectionError("websocket client closed")
        waiter: queue.Queue = queue.Queue(maxsize=1)
        with self._mtx:
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = waiter
        self._send_frame(
            json.dumps(
                {
                    "jsonrpc": "2.0",
                    "id": req_id,
                    "method": method,
                    "params": params,
                }
            ).encode()
        )
        try:
            msg = waiter.get(timeout=self.timeout)
        except queue.Empty:
            self._pending.pop(req_id, None)
            raise TimeoutError(f"no response to {method}") from None
        if msg is None:
            raise ConnectionError("websocket closed mid-call")
        if msg.get("error"):
            err = msg["error"]
            raise RPCError(
                err.get("code", -32603),
                err.get("message", "unknown"),
                err.get("data", ""),
            )
        return msg.get("result")

    def subscribe(self, query: str) -> WSSubscription:
        sub = WSSubscription(query)
        self._subs[query] = sub
        self.call("subscribe", query=query)
        return sub

    def unsubscribe(self, query: str) -> None:
        self._subs.pop(query, None)
        self.call("unsubscribe", query=query)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
