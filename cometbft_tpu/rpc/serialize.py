"""JSON serialization of domain types for the RPC surface (reference:
the JSON shapes produced by rpc/core responses via cmtjson).

Conventions mirror the reference wire JSON: 64-bit ints as strings,
hashes as upper-hex, times as RFC3339 with nanoseconds.
"""

from __future__ import annotations

from datetime import datetime, timezone


def hexb(b: bytes) -> str:
    return b.hex().upper()


def b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


def time_rfc3339(ns: int) -> str:
    dt = datetime.fromtimestamp(ns / 1e9, tz=timezone.utc)
    frac = ns % 1_000_000_000
    return dt.strftime("%Y-%m-%dT%H:%M:%S") + f".{frac:09d}Z"


def block_id_json(bid) -> dict:
    return {
        "hash": hexb(bid.hash),
        "parts": {
            "total": bid.part_set_header.total,
            "hash": hexb(bid.part_set_header.hash),
        },
    }


def header_json(h) -> dict:
    return {
        "version": {"block": str(h.version_block), "app": str(h.version_app)},
        "chain_id": h.chain_id,
        "height": str(h.height),
        "time": time_rfc3339(h.time_ns),
        "last_block_id": block_id_json(h.last_block_id),
        "last_commit_hash": hexb(h.last_commit_hash),
        "data_hash": hexb(h.data_hash),
        "validators_hash": hexb(h.validators_hash),
        "next_validators_hash": hexb(h.next_validators_hash),
        "consensus_hash": hexb(h.consensus_hash),
        "app_hash": hexb(h.app_hash),
        "last_results_hash": hexb(h.last_results_hash),
        "evidence_hash": hexb(h.evidence_hash),
        "proposer_address": hexb(h.proposer_address),
    }


def commit_sig_json(cs) -> dict:
    return {
        "block_id_flag": cs.block_id_flag,
        "validator_address": hexb(cs.validator_address),
        "timestamp": time_rfc3339(cs.timestamp_ns),
        "signature": b64(cs.signature) if cs.signature else None,
    }


def commit_json(c) -> dict:
    out = {
        "height": str(c.height),
        "round": c.round,
        "block_id": block_id_json(c.block_id),
        "signatures": [commit_sig_json(cs) for cs in c.signatures],
    }
    if c.agg_signature:
        # the commit-level BLS aggregate (types/block.py); omitted
        # for per-signature commits so their JSON is unchanged
        out["agg_signature"] = b64(c.agg_signature)
    return out


def block_json(b) -> dict:
    from cometbft_tpu.types import codec

    return {
        "header": header_json(b.header),
        "data": {"txs": [b64(tx) for tx in b.data.txs]},
        "evidence": {
            "evidence": [
                {"type": type(ev).__name__, "height": str(ev.height)}
                for ev in b.evidence
            ]
        },
        "last_commit": commit_json(b.last_commit) if b.last_commit else None,
    }


def block_meta_json(meta) -> dict:
    return {
        "block_id": block_id_json(meta.block_id),
        "block_size": str(meta.block_size),
        "header": header_json(meta.header),
        "num_txs": str(meta.num_txs),
    }


#: key type -> amino-style JSON type tag (the reference's
#: crypto/encoding); BLS validator sets must survive the RPC round
#: trip for the light serving plane, so the tag is derived from the
#: key, never hardcoded
PUB_KEY_JSON_TYPES = {
    "ed25519": "tendermint/PubKeyEd25519",
    "secp256k1": "tendermint/PubKeySecp256k1",
    "bls12_381": "tendermint/PubKeyBls12381",
}


def validator_json(v) -> dict:
    key_type = v.pub_key.type()
    try:
        tag = PUB_KEY_JSON_TYPES[key_type]
    except KeyError:
        # fail LOUDLY at the boundary: silently tagging an unknown
        # family as ed25519 would make the far side reconstruct the
        # wrong key class and fail later with a misleading
        # wrong-signature error
        raise ValueError(
            f"no JSON type tag for pub key type {key_type!r} — "
            "add it to rpc/serialize.PUB_KEY_JSON_TYPES"
        ) from None
    return {
        "address": hexb(v.address),
        "pub_key": {
            "type": tag,
            "value": b64(v.pub_key.bytes()),
        },
        "voting_power": str(v.voting_power),
        "proposer_priority": str(v.proposer_priority),
    }


def event_json(ev) -> dict:
    return {
        "type": ev.type,
        "attributes": [
            {"key": a.key, "value": a.value, "index": a.index}
            for a in ev.attributes
        ],
    }


def exec_tx_result_json(r) -> dict:
    return {
        "code": r.code,
        "data": b64(r.data) if r.data else None,
        "log": r.log,
        "info": r.info,
        "gas_wanted": str(r.gas_wanted),
        "gas_used": str(r.gas_used),
        "events": [event_json(e) for e in r.events or ()],
        "codespace": r.codespace,
    }


__all__ = [
    "b64",
    "block_id_json",
    "block_json",
    "block_meta_json",
    "commit_json",
    "commit_sig_json",
    "event_json",
    "exec_tx_result_json",
    "header_json",
    "hexb",
    "time_rfc3339",
    "validator_json",
]
