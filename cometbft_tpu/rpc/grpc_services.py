"""gRPC data services (reference: rpc/grpc/server/services/): version,
block, block-results, and the privileged pruning service.

The reference treats gRPC as a first-class API surface next to
JSON-RPC: explorers stream GetLatestHeight, data companions fetch
blocks/results and drive pruning via the privileged endpoint
(rpc/grpc/server/server.go). Here each service is a generic-handler
gRPC server over this framework's proto wire helpers — block payloads
reuse types/codec.encode_block, so a block fetched over gRPC is
byte-identical to one gossiped on p2p.

The privileged server binds its own address (config [grpc]
privileged_laddr) so operators can firewall pruning control away from
the public data plane, exactly the reference's split.
"""

from __future__ import annotations

import threading

from concurrent import futures

import grpc

from cometbft_tpu.types import codec as tcodec
from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.protoio import ProtoReader, ProtoWriter
from cometbft_tpu.utils.service import BaseService
from cometbft_tpu.version import (
    ABCI_SEMVER,
    BLOCK_PROTOCOL,
    P2P_PROTOCOL,
    __version__,
)

VERSION_SERVICE = "cometbft.services.version.v1.VersionService"
BLOCK_SERVICE = "cometbft.services.block.v1.BlockService"
BLOCK_RESULTS_SERVICE = (
    "cometbft.services.block_results.v1.BlockResultsService"
)
PRUNING_SERVICE = "cometbft.services.pruning.v1.PruningService"


def _parse_addr(addr: str) -> str:
    for prefix in ("grpc://", "tcp://"):
        if addr.startswith(prefix):
            return addr[len(prefix):]
    return addr


def _uvarint_field(raw: bytes, no: int, default: int = 0) -> int:
    f = ProtoReader(bytes(raw)).to_dict()
    vals = f.get(no)
    return int(vals[0]) if vals else default


class _TooManyStreams(Exception):
    """Raised by streaming handlers when the stream cap is hit; the
    dispatch wrapper maps it to RESOURCE_EXHAUSTED (context.abort
    inside a handler would be re-caught and masked as INTERNAL)."""


class _GenericService(grpc.GenericRpcHandler):
    """Dispatch /<service>/<method> to {(service, method): fn} where fn
    is either (bytes) -> bytes (unary) or a generator (streaming)."""

    def __init__(self, table: dict, streaming: set):
        self._table = table
        self._streaming = streaming

    def service(self, details):
        service, _, method = details.method.lstrip("/").partition("/")
        fn = self._table.get((service, method))
        if fn is None:
            return None
        ident = lambda b: b  # noqa: E731

        def unary(request, context):
            try:
                return fn(request)
            except KeyError as exc:
                context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
            except ValueError as exc:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
            except Exception as exc:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, repr(exc))

        def stream(request, context):
            try:
                yield from fn(request, context)
            except _TooManyStreams as exc:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
            except Exception as exc:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL, repr(exc))

        if (service, method) in self._streaming:
            return grpc.unary_stream_rpc_method_handler(
                stream, request_deserializer=ident, response_serializer=ident
            )
        return grpc.unary_unary_rpc_method_handler(
            unary, request_deserializer=ident, response_serializer=ident
        )


class GrpcDataServer(BaseService):
    """Public data plane: version/block/block-results services
    (rpc/grpc/server/server.go Serve)."""

    def __init__(
        self,
        addr: str,
        block_store,
        state_store,
        version_enabled: bool = True,
        block_enabled: bool = True,
        block_results_enabled: bool = True,
        logger: Logger | None = None,
    ):
        super().__init__(
            name="grpc-data",
            logger=logger or default_logger().with_fields(module="grpc"),
        )
        self.block_store = block_store
        self.state_store = state_store
        table: dict = {}
        streaming: set = set()
        if version_enabled:
            table[(VERSION_SERVICE, "GetVersion")] = self._get_version
        if block_enabled:
            table[(BLOCK_SERVICE, "GetByHeight")] = self._get_by_height
            table[(BLOCK_SERVICE, "GetLatestHeight")] = self._latest_heights
            streaming.add((BLOCK_SERVICE, "GetLatestHeight"))
        if block_results_enabled:
            table[(BLOCK_RESULTS_SERVICE, "GetBlockResults")] = (
                self._get_block_results
            )
        # Streams park a worker thread for their whole life; cap them
        # BELOW the pool size so idle height subscribers can never
        # starve the unary endpoints (availability, not fairness).
        self._stream_slots = threading.BoundedSemaphore(8)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16)
        )
        self._server.add_generic_rpc_handlers(
            (_GenericService(table, streaming),)
        )
        self.port = self._server.add_insecure_port(_parse_addr(addr))

    # GetVersionResponse: node(1) str, abci(2) str, p2p(3) u64, block(4) u64
    def _get_version(self, raw: bytes) -> bytes:
        w = ProtoWriter()
        w.string(1, __version__)
        w.string(2, ABCI_SEMVER)
        w.varint(3, P2P_PROTOCOL)
        w.varint(4, BLOCK_PROTOCOL)
        return w.finish()

    # GetByHeightRequest: height(1); Response: block_id(1), block(2)
    def _get_by_height(self, raw: bytes) -> bytes:
        height = _uvarint_field(raw, 1)
        if height == 0:
            height = self.block_store.height()
        meta = self.block_store.load_block_meta(height)
        block = self.block_store.load_block(height)
        if meta is None or block is None:
            raise KeyError(f"no block at height {height}")
        w = ProtoWriter()
        w.message(1, meta.block_id.encode())
        w.message(2, tcodec.encode_block(block))
        return w.finish()

    # GetLatestHeightResponse: height(1) — server streams each new height
    def _latest_heights(self, raw: bytes, context):
        if not self._stream_slots.acquire(blocking=False):
            raise _TooManyStreams("too many concurrent height streams")
        try:
            last = 0
            while context.is_active() and not self._quit.is_set():
                h = self.block_store.height()
                if h > last:
                    last = h
                    w = ProtoWriter()
                    w.varint(1, h)
                    yield w.finish()
                else:
                    # quit-aware wait doubles as the poll interval
                    self._quit.wait(0.1)
        finally:
            self._stream_slots.release()

    # GetBlockResultsRequest: height(1); Response: height(1),
    # finalize_block_response(2, our FinalizeBlockResponse encoding)
    def _get_block_results(self, raw: bytes) -> bytes:
        height = _uvarint_field(raw, 1)
        if height == 0:
            height = self.block_store.height()
        resp = self.state_store.load_finalize_block_response(height)
        if resp is None:
            raise KeyError(f"no block results at height {height}")
        w = ProtoWriter()
        w.varint(1, height)
        w.message(2, resp.encode())
        return w.finish()

    def on_start(self) -> None:
        self._server.start()
        self.logger.info("grpc data server listening", port=self.port)

    def on_stop(self) -> None:
        self._server.stop(grace=1.0)


class GrpcPrivilegedServer(BaseService):
    """Privileged plane: the pruning service a data companion uses to
    move retain heights (rpc/grpc/server/services/pruningservice)."""

    def __init__(self, addr: str, pruner, logger: Logger | None = None):
        super().__init__(
            name="grpc-privileged",
            logger=logger
            or default_logger().with_fields(module="grpc-privileged"),
        )
        self.pruner = pruner
        table = {
            (PRUNING_SERVICE, "SetBlockRetainHeight"): self._set_block,
            (PRUNING_SERVICE, "GetBlockRetainHeight"): self._get_block,
            (PRUNING_SERVICE, "SetBlockResultsRetainHeight"): (
                self._set_results
            ),
            (PRUNING_SERVICE, "GetBlockResultsRetainHeight"): (
                self._get_results
            ),
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers(
            (_GenericService(table, set()),)
        )
        self.port = self._server.add_insecure_port(_parse_addr(addr))

    def _set_block(self, raw: bytes) -> bytes:
        height = _uvarint_field(raw, 1)
        self.pruner.set_companion_block_retain_height(height)
        return b""

    # GetBlockRetainHeightResponse: app_retain_height(1),
    # pruning_service_retain_height(2)
    def _get_block(self, raw: bytes) -> bytes:
        w = ProtoWriter()
        w.varint(1, self.pruner.get_application_retain_height())
        w.varint(2, self.pruner.get_companion_block_retain_height())
        return w.finish()

    def _set_results(self, raw: bytes) -> bytes:
        height = _uvarint_field(raw, 1)
        self.pruner.set_abci_results_retain_height(height)
        return b""

    def _get_results(self, raw: bytes) -> bytes:
        w = ProtoWriter()
        w.varint(1, self.pruner.get_abci_results_retain_height())
        return w.finish()

    def on_start(self) -> None:
        self._server.start()
        self.logger.info("grpc privileged server listening", port=self.port)

    def on_stop(self) -> None:
        self._server.stop(grace=1.0)


class GrpcClient:
    """Thin client for the data + privileged services (the reference's
    rpc/grpc/client package)."""

    def __init__(self, addr: str, timeout: float = 10.0):
        self.addr = _parse_addr(addr)
        self.timeout = timeout
        self._channel = grpc.insecure_channel(self.addr)

    def close(self) -> None:
        self._channel.close()

    def _unary(self, service: str, method: str, payload: bytes) -> bytes:
        fn = self._channel.unary_unary(
            f"/{service}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        return fn(payload, timeout=self.timeout)

    def get_version(self) -> dict:
        raw = self._unary(VERSION_SERVICE, "GetVersion", b"")
        f = ProtoReader(raw).to_dict()
        return {
            "node": bytes(f.get(1, [b""])[0]).decode(),
            "abci": bytes(f.get(2, [b""])[0]).decode(),
            "p2p": int(f.get(3, [0])[0]),
            "block": int(f.get(4, [0])[0]),
        }

    def get_block_by_height(self, height: int = 0):
        w = ProtoWriter()
        w.varint(1, height)
        raw = self._unary(BLOCK_SERVICE, "GetByHeight", w.finish())
        f = ProtoReader(raw).to_dict()
        block_id = tcodec.decode_block_id(bytes(f[1][0]))
        block = tcodec.decode_block(bytes(f[2][0]))
        return block_id, block

    def get_latest_height_stream(self):
        fn = self._channel.unary_stream(
            f"/{BLOCK_SERVICE}/GetLatestHeight",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        for raw in fn(b""):
            yield _uvarint_field(raw, 1)

    def get_block_results(self, height: int = 0):
        from cometbft_tpu.abci.types import FinalizeBlockResponse

        w = ProtoWriter()
        w.varint(1, height)
        raw = self._unary(
            BLOCK_RESULTS_SERVICE, "GetBlockResults", w.finish()
        )
        f = ProtoReader(raw).to_dict()
        return (
            int(f.get(1, [0])[0]),
            FinalizeBlockResponse.decode(bytes(f[2][0])),
        )

    # privileged
    def set_block_retain_height(self, height: int) -> None:
        w = ProtoWriter()
        w.varint(1, height)
        self._unary(PRUNING_SERVICE, "SetBlockRetainHeight", w.finish())

    def get_block_retain_height(self) -> tuple[int, int]:
        raw = self._unary(PRUNING_SERVICE, "GetBlockRetainHeight", b"")
        f = ProtoReader(raw).to_dict()
        return int(f.get(1, [0])[0]), int(f.get(2, [0])[0])

    def set_block_results_retain_height(self, height: int) -> None:
        w = ProtoWriter()
        w.varint(1, height)
        self._unary(
            PRUNING_SERVICE, "SetBlockResultsRetainHeight", w.finish()
        )

    def get_block_results_retain_height(self) -> int:
        raw = self._unary(
            PRUNING_SERVICE, "GetBlockResultsRetainHeight", b""
        )
        return _uvarint_field(raw, 1)
