"""Validated `CMT_TPU_*` env-knob readers — the one contract.

Every knob read in the tree must fail LOUDLY on a malformed value,
naming the variable and its constraint (the `ring_size_from_env`
contract from utils/flight.py, generalized).  A typo'd
``CMT_TPU_CHECKTX_BATCH=8O`` that silently falls back to the default
is a production incident that looks like a perf regression; a
ValueError at import is a one-line fix.

tools/envcheck.py enforces this statically: every ``CMT_TPU_*``
getenv site must route through one of these helpers (or an
equivalently registered validator), be a boolean/presence read that
cannot fail-parse, or carry an audited ``# env ok: <reason>`` waiver.
The same lint checks every knob is documented in
docs/observability.md's env table — and that every documented knob is
still read somewhere.
"""

from __future__ import annotations

import os

__all__ = [
    "int_from_env",
    "float_from_env",
    "flag_from_env",
    "choice_from_env",
    "name_from_env",
]


def int_from_env(var: str, default: int, minimum: int = 0) -> int:
    """A validated integer knob: unset/empty -> default; otherwise an
    integer >= ``minimum`` or a ValueError naming both."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be an integer >= {minimum}, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {value}")
    return value


def float_from_env(var: str, default: float, minimum: float = 0.0) -> float:
    """A validated float knob (same contract as :func:`int_from_env`)."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be a number >= {minimum}, got {raw!r}"
        ) from None
    if value < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {value}")
    return value


def flag_from_env(var: str, default: bool = False) -> bool:
    """A validated on/off knob: unset/empty -> default, "1"/"0" ->
    True/False, anything else a ValueError (a half-typed
    ``CMT_TPU_DETERMINISM=yes`` must not silently disable the guard
    the operator asked for)."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    if raw == "1":
        return True
    if raw == "0":
        return False
    raise ValueError(f"{var} must be '1' or '0' (or unset), got {raw!r}")


def choice_from_env(var: str, default: str, choices: tuple[str, ...]) -> str:
    """A validated enum knob: the value must be one of ``choices``
    (a silently ignored ``CMT_TPU_COLS_IMPL=matmull`` typo would
    quietly bench the wrong kernel)."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    if raw not in choices:
        raise ValueError(
            f"{var} must be one of {sorted(choices)}, got {raw!r}"
        )
    return raw


def name_from_env(var: str, default: str | None = None) -> str | None:
    """A validated free-form label knob (e.g. ``CMT_TPU_SCENARIO``):
    unset/empty -> default; otherwise a short ``[a-z0-9_-]`` token —
    the value rides metrics labels and JSON payloads, so an arbitrary
    string is an injection surface, not a name."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    raw = raw.strip()
    if len(raw) > 64 or not all(
        c.isascii() and (c.isalnum() or c in "_-") for c in raw
    ):
        raise ValueError(
            f"{var} must be a short [A-Za-z0-9_-] label (<= 64 chars), "
            f"got {raw!r}"
        )
    return raw
