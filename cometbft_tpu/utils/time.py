"""Canonical time (reference: libs/time/time.go).

All timestamps in the system are unix-epoch nanoseconds.  ``now_ns``
is the single clock source so tests can monkeypatch it in one place
(the reference's cmttime.Now, canonicalized to ms there; we keep ns
and canonicalize only in encodings).
"""

from __future__ import annotations

import time


def now_ns() -> int:
    return time.time_ns()


def sleep_ns(ns: int) -> None:
    if ns > 0:
        time.sleep(ns / 1e9)
