"""Event pub/sub with a query DSL (reference: libs/pubsub/pubsub.go:93,
libs/pubsub/query/query.go).

Subscribers register a client id + query ("tm.event='NewBlock' AND
tx.height > 5"); published messages carry a map of composite-keyed
event attributes the queries match against.  Feeds WebSocket
subscribers and the tx/block indexers.
"""

from __future__ import annotations

import queue
import re
import threading
from dataclasses import dataclass, field
from typing import Any
from cometbft_tpu.utils import sync as cmtsync


class PubSubError(Exception):
    pass


class QueryError(PubSubError):
    pass


# -- query DSL ---------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<and>AND\b)
      | (?P<contains>CONTAINS\b)
      | (?P<exists>EXISTS\b)
      | (?P<op><=|>=|=|<|>)
      | (?P<str>'[^']*')
      | (?P<num>-?\d+(?:\.\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)


@dataclass(frozen=True)
class _Condition:
    key: str
    op: str  # '=', '<', '>', '<=', '>=', 'CONTAINS', 'EXISTS'
    value: str | float | None = None

    def matches(self, events: dict[str, list[str]]) -> bool:
        vals = events.get(self.key)
        if vals is None:
            return False
        if self.op == "EXISTS":
            return True
        if self.op == "CONTAINS":
            return any(str(self.value) in v for v in vals)
        if self.op == "=":
            if isinstance(self.value, float):
                return any(_as_num(v) == self.value for v in vals)
            return any(v == self.value for v in vals)
        # numeric comparisons
        for v in vals:
            n = _as_num(v)
            if n is None:
                continue
            if (
                (self.op == "<" and n < self.value)
                or (self.op == ">" and n > self.value)
                or (self.op == "<=" and n <= self.value)
                or (self.op == ">=" and n >= self.value)
            ):
                return True
        return False


def _as_num(s: str) -> float | None:
    try:
        return float(s)
    except ValueError:
        return None


class Query:
    """Conjunctive query over event attributes (query/query.go)."""

    def __init__(self, conditions: tuple[_Condition, ...], source: str):
        self.conditions = conditions
        self._source = source

    @classmethod
    def parse(cls, s: str) -> "Query":
        if not s.strip():
            raise QueryError("empty query")
        tokens: list[tuple[str, str]] = []
        pos = 0
        while pos < len(s):
            m = _TOKEN_RE.match(s, pos)
            if m is None or m.end() == pos:
                if s[pos:].strip():
                    raise QueryError(f"cannot parse query at: {s[pos:]!r}")
                break
            pos = m.end()
            for name, val in m.groupdict().items():
                if val is not None:
                    tokens.append((name, val))
        conds: list[_Condition] = []
        i = 0
        while i < len(tokens):
            kind, val = tokens[i]
            if kind != "key":
                raise QueryError(f"expected attribute key, got {val!r}")
            if i + 1 >= len(tokens):
                raise QueryError("truncated query")
            okind, oval = tokens[i + 1]
            if okind == "exists":
                conds.append(_Condition(val, "EXISTS"))
                i += 2
            elif okind in ("op", "contains"):
                if i + 2 >= len(tokens):
                    raise QueryError("missing operand")
                vkind, vval = tokens[i + 2]
                if vkind == "str":
                    operand: str | float = vval[1:-1]
                elif vkind == "num":
                    operand = float(vval)
                else:
                    raise QueryError(f"bad operand {vval!r}")
                op = "CONTAINS" if okind == "contains" else oval
                if op in ("<", ">", "<=", ">=") and not isinstance(
                    operand, float
                ):
                    raise QueryError(f"operator {op} needs a number")
                conds.append(_Condition(val, op, operand))
                i += 3
            else:
                raise QueryError(f"expected operator after {val!r}")
            if i < len(tokens):
                akind, aval = tokens[i]
                if akind != "and":
                    raise QueryError(f"expected AND, got {aval!r}")
                i += 1
        return cls(tuple(conds), s)

    def matches(self, events: dict[str, list[str]]) -> bool:
        return all(c.matches(events) for c in self.conditions)

    def __str__(self) -> str:
        return self._source

    def __eq__(self, other) -> bool:
        return isinstance(other, Query) and self.conditions == other.conditions

    def __hash__(self) -> int:
        return hash(self.conditions)


ALL = Query((), "ALL")  # matches everything (query.All)


# -- server ------------------------------------------------------------

@dataclass
class Message:
    data: Any
    events: dict[str, list[str]] = field(default_factory=dict)


class Subscription:
    """One client's subscription; delivered via a bounded queue
    (pubsub.go Subscription)."""

    def __init__(self, client_id: str, query: Query, capacity: int):
        self.client_id = client_id
        self.query = query
        self._q: queue.Queue[Message] = queue.Queue(maxsize=max(capacity, 1))
        self._canceled = threading.Event()
        self.cancel_reason: str | None = None

    def next(self, timeout: float | None = None) -> Message:
        """Block for the next message; raises PubSubError if canceled."""
        while True:
            if self._canceled.is_set() and self._q.empty():
                raise PubSubError(
                    f"subscription canceled: {self.cancel_reason}"
                )
            try:
                return self._q.get(timeout=0.05 if timeout is None else min(timeout, 0.05))
            except queue.Empty:
                if timeout is not None:
                    timeout -= 0.05
                    if timeout <= 0:
                        raise TimeoutError("no message") from None

    def try_next(self) -> Message | None:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def _deliver(self, msg: Message) -> bool:
        try:
            self._q.put_nowait(msg)
            return True
        except queue.Full:
            return False

    def _cancel(self, reason: str) -> None:
        self.cancel_reason = reason
        self._canceled.set()

    @property
    def canceled(self) -> bool:
        return self._canceled.is_set()


class Server:
    """Pub/sub hub (pubsub.go Server).  Slow subscribers are canceled
    rather than blocking publishers (out-of-capacity policy).

    ``on_drop(client_id)`` fires once per out-of-capacity cancellation
    — the event bus feeds it into the subscriber-drop counter without
    this module depending on the metrics plane."""

    def __init__(self, capacity: int = 100, on_drop=None):
        self._mtx = cmtsync.RMutex()
        self._capacity = capacity
        self._on_drop = on_drop
        self._subs: dict[tuple[str, Query], Subscription] = {}

    def subscribe(
        self, client_id: str, query: Query | str, capacity: int | None = None
    ) -> Subscription:
        if isinstance(query, str):
            query = Query.parse(query)
        with self._mtx:
            key = (client_id, query)
            if key in self._subs:
                raise PubSubError(
                    f"already subscribed: {client_id} / {query}"
                )
            sub = Subscription(
                client_id, query, capacity or self._capacity
            )
            self._subs[key] = sub
            return sub

    def unsubscribe(self, client_id: str, query: Query | str) -> None:
        if isinstance(query, str):
            query = Query.parse(query)
        with self._mtx:
            sub = self._subs.pop((client_id, query), None)
            if sub is None:
                raise PubSubError("subscription not found")
            sub._cancel("unsubscribed")

    def unsubscribe_all(self, client_id: str) -> None:
        with self._mtx:
            keys = [k for k in self._subs if k[0] == client_id]
            if not keys:
                raise PubSubError("subscription not found")
            for k in keys:
                self._subs.pop(k)._cancel("unsubscribed")

    def publish(self, data: Any, events: dict[str, list[str]] | None = None):
        msg = Message(data=data, events=events or {})
        with self._mtx:
            dead = []
            for key, sub in self._subs.items():
                if sub.query.matches(msg.events):
                    if not sub._deliver(msg):
                        sub._cancel("out of capacity")
                        dead.append(key)
            for key in dead:
                del self._subs[key]
        for key in dead:
            if self._on_drop is not None:
                try:
                    self._on_drop(key[0])
                except Exception:  # noqa: BLE001 — telemetry must not kill publish
                    pass

    def queue_depths(self) -> dict[str, int]:
        """Deepest undelivered-message queue per client id — the
        backpressure signal the event-bus gauge exposes."""
        with self._mtx:
            out: dict[str, int] = {}
            for (cid, _), sub in self._subs.items():
                depth = sub._q.qsize()
                if depth > out.get(cid, -1):
                    out[cid] = depth
            return out

    def num_clients(self) -> int:
        with self._mtx:
            return len({cid for cid, _ in self._subs})

    def num_client_subscriptions(self, client_id: str) -> int:
        with self._mtx:
            return sum(1 for cid, _ in self._subs if cid == client_id)
