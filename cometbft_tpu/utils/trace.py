"""Lightweight span tracing for the device execution path.

The metrics plane (utils/metrics.py) answers "how much / how often";
this module answers "what happened inside THIS commit".  Spans are
context managers with thread-local parenting, retained in a bounded
ring buffer and exported as Chrome trace-event JSON (the
``traceEvents`` object format) that chrome://tracing and Perfetto load
directly — the round-4/5 dispatch-calibration incident (mid-size
batches silently routed to a high-RTT device for a full round) is
exactly the shape of problem a launch-level timeline makes visible
without an ad-hoc bench run.

Design constraints, in order:

- **Hot-path cost**: spans wrap whole consensus steps, VerifyCommit
  calls, and device launches — never per-signature work.  A disabled
  tracer returns one shared no-op span object, so the disabled path
  allocates nothing.
- **Bounded retention**: completed spans land in a ``deque(maxlen=N)``
  (CMT_TPU_TRACE_RING, default 4096) — a long-running node keeps the
  most recent window, never an unbounded log.
- **No dependencies**: stdlib only; importable from every plane
  (crypto, ops, consensus, tools) without dragging jax in.

Surfaces: the metrics HTTP server serves ``/trace`` next to
``/metrics``; the Inspector exposes a ``trace`` JSON-RPC route; and
bench.py / tools/device_campaign.py dump the same JSON next to their
results for provenance.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque


class _NopSpan:
    """Shared do-nothing span — the disabled tracer's return value.

    A singleton so ``tracer.span(...)`` allocates nothing when tracing
    is off (mirrors the metrics plane's ``_Nop``)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args) -> None:
        pass


_NOP_SPAN = _NopSpan()

#: the shared no-op span, for call sites that need an explicitly inert
#: context manager (e.g. consensus skipping spans during WAL replay)
NOP_SPAN = _NOP_SPAN

_DEFAULT_RING = 4096

#: live tracers whose cached pid must be refreshed in fork children
_PID_TRACERS: "weakref.WeakSet[SpanTracer]" = weakref.WeakSet()

if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: [
            setattr(t, "_pid", os.getpid()) for t in _PID_TRACERS
        ]
    )


class _Span:
    """One in-flight span; records a complete ("ph": "X") event on exit."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_parent")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args) -> None:
        """Attach result data discovered mid-span (e.g. batch verdict)."""
        self.args.update(args)

    def __enter__(self):
        stack = self._tracer._stack()
        self._parent = stack[-1].name if stack else None
        stack.append(self)
        # per-thread current-span map: what the sampling profiler
        # (utils/profiler.py) reads to tag a sampled stack with the
        # pipeline stage it ran under.  Plain dict store — atomic under
        # the GIL, and this is the lexical-span hot path.
        self._tracer._active[threading.get_ident()] = self.name
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        end = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        tid = threading.get_ident()
        if stack:
            self._tracer._active[tid] = stack[-1].name
        else:
            self._tracer._active.pop(tid, None)
        if exc_type is not None:
            self.args["error"] = exc_type.__name__
        self._tracer._record(
            self.name, self.cat, self._t0, end - self._t0, self.args,
            self._parent,
        )
        return False


class SpanTracer:
    """Bounded ring of completed spans, Chrome-trace-JSON exportable.

    ``span(name, **args)`` is the lexical entry point; spans started on
    the same thread nest (thread-local parent stack, the parent's name
    lands in the child's args).  ``add_complete`` records a span after
    the fact from explicit perf_counter timestamps — used by the
    consensus state machine, whose steps begin and end at different
    call sites.
    """

    def __init__(
        self,
        capacity: int | None = None,
        enabled: bool | None = None,
    ):
        if capacity is None:
            # same validation contract as CMT_TPU_FLIGHT_DEPTH
            from cometbft_tpu.utils.flight import ring_size_from_env

            capacity = ring_size_from_env(
                "CMT_TPU_TRACE_RING", _DEFAULT_RING
            )
        if enabled is None:
            from cometbft_tpu.utils.env import flag_from_env

            enabled = flag_from_env("CMT_TPU_TRACE", default=True)
        self.enabled = enabled
        self._events: deque[dict] = deque(maxlen=max(capacity, 1))
        self._mtx = threading.Lock()
        self._tls = threading.local()
        #: tid -> innermost OPEN lexical span name; entries are removed
        #: when a thread's span stack drains, so the map stays bounded
        #: by threads with a span in flight (read by the profiler)
        self._active: dict[int, str] = {}
        #: perf_counter origin; event ts values are microseconds since
        #: this instant (Chrome traces need any consistent monotonic us)
        self.epoch = time.perf_counter()
        #: wall clock captured at the same instant as ``epoch`` — the
        #: anchor that lets the fleet aggregator place this ring's
        #: (monotonic-derived) span timestamps on a cross-node wall
        #: timeline: wall_of(ts_us) = epoch_wall + ts_us/1e6
        self.epoch_wall = time.time()
        self._dropped = 0
        # getpid() is a real syscall on sandboxed kernels (~10us) —
        # cache it; _PID_TRACERS refreshes after fork
        self._pid = os.getpid()
        #: tid -> thread name, captured at record time — a track must
        #: keep its name after its thread exits
        self._thread_names: dict[int, str] = {}
        _PID_TRACERS.add(self)

    # -- recording -----------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def span(self, name: str, cat: str = "app", **args):
        """A context-manager span; the shared no-op when disabled."""
        if not self.enabled:
            return _NOP_SPAN
        return _Span(self, name, cat, args)

    def add_complete(
        self,
        name: str,
        start: float,
        duration_s: float,
        cat: str = "app",
        args: dict | None = None,
    ) -> None:
        """Record a span from explicit ``time.perf_counter()`` values
        (``start`` in perf_counter time, not trace microseconds)."""
        if not self.enabled:
            return
        self._record(name, cat, start, duration_s, args or {}, None)

    def _record(
        self,
        name: str,
        cat: str,
        start: float,
        duration_s: float,
        args: dict,
        parent: str | None,
    ) -> None:
        if parent is not None:
            args = dict(args, parent=parent)
        thread = threading.current_thread()
        event = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": round(max(start - self.epoch, 0.0) * 1e6, 1),
            "dur": round(max(duration_s, 0.0) * 1e6, 1),
            "pid": self._pid,
            "tid": thread.ident,
            "args": args,
        }
        with self._mtx:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append(event)
            self._thread_names[thread.ident] = thread.name
            if len(self._thread_names) > 1024:
                live = {e["tid"] for e in self._events}
                self._thread_names = {
                    t: n
                    for t, n in self._thread_names.items()
                    if t in live
                }

    # -- introspection -------------------------------------------------

    def current_spans(self) -> dict[int, str]:
        """Snapshot of tid -> innermost open lexical span name — the
        attribution seam the sampling profiler tags samples with.
        Spans recorded via ``add_complete`` (the consensus step spans)
        never appear here: they are reconstructed after the fact, not
        open while their work runs."""
        return dict(self._active)

    # -- export --------------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot of retained span events, oldest first."""
        with self._mtx:
            return list(self._events)

    def export(self) -> dict:
        """Chrome trace-event JSON (object form) — load in Perfetto /
        chrome://tracing.  Thread-name metadata events are synthesized
        (names captured at record time, so a track keeps its name
        after its thread exits) so tracks read as thread names, not
        bare idents."""
        with self._mtx:
            events = list(self._events)
            names = dict(self._thread_names)
        pid = self._pid
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": names.get(tid, f"thread-{tid}")},
            }
            for tid in sorted({e["tid"] for e in events})
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "dropped_spans": self._dropped,
                # fleet plane: the wall anchor for cross-node stitching
                "wall_epoch": self.epoch_wall,
                "pid": pid,
            },
        }

    def export_json(self) -> str:
        return json.dumps(self.export(), default=str)

    def dump(self, path: str) -> None:
        """Atomically write the export to ``path`` (tmp + rename);
        the shared provenance-dump helper for bench/campaign drivers."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.export_json())
        os.replace(tmp, path)

    def clear(self) -> None:
        with self._mtx:
            self._events.clear()
            self._thread_names.clear()
            self._dropped = 0

    def set_enabled(self, enabled: bool) -> None:
        self.enabled = bool(enabled)


#: process-wide tracer — all planes record here, all surfaces read here
TRACER = SpanTracer()


__all__ = ["NOP_SPAN", "SpanTracer", "TRACER"]
