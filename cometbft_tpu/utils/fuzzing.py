"""Coverage-guided fuzzing engine.

Reference analog: test/fuzz/ (Go native fuzzing with corpora wired into
OSS-Fuzz, test/fuzz/README.md, oss-fuzz-build.sh).  Python has no
libFuzzer here, so this is a small in-tree engine with the same
feedback loop:

- **Coverage feedback** via ``sys.monitoring`` (PEP 669): the LINE
  callback fires once per never-before-executed line (the callback
  DISABLEs its line after the first hit, so steady-state overhead is
  near zero) — an exec that fires any callback discovered new code and
  its input joins the corpus.
- **Corpus**: seed inputs plus every coverage-growing mutant, stored as
  content-addressed files, checked into the repo so CI replays them as
  regression tests (tests/data/fuzz_corpus/<target>/).
- **Crashes**: any exception outside the target's allowed set is saved
  to tests/data/fuzz_crashes/<target>/ — the replay pass turns each
  old crash into a permanent regression check.
- **Mutators**: generic byte-level (bit/byte flips, insert/delete/
  duplicate, truncation, splice) plus protocol-shaped helpers (varint
  boundary values, length-prefix corruption) that match the
  length-delimited wire formats this codebase parses.
"""

from __future__ import annotations

import hashlib
import os
import random
import sys
import time
from dataclasses import dataclass, field

_MAGIC = (
    b"\x00", b"\xff", b"\x80", b"\x7f", b"\x80\x80\x80\x80\x80\x80\x80\x80\x80\x01",
    b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\x01", b"\xfe\xff\xff\xff\x0f",
    b"\x0a", b"\x12", b"\x1a",  # common field-1/2/3 length-delimited tags
)


def mutate(rng: random.Random, data: bytes, corpus: list[bytes]) -> bytes:
    """One mutation step; always returns a (possibly empty) new buffer."""
    b = bytearray(data)
    for _ in range(rng.choice((1, 1, 1, 2, 3))):
        op = rng.randrange(9)
        if op == 0 and b:  # bit flip
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
        elif op == 1 and b:  # random byte
            b[rng.randrange(len(b))] = rng.randrange(256)
        elif op == 2:  # insert magic / random run
            i = rng.randrange(len(b) + 1)
            ins = (
                rng.choice(_MAGIC)
                if rng.random() < 0.5
                else bytes(rng.randrange(256) for _ in range(rng.randrange(1, 8)))
            )
            b[i:i] = ins
        elif op == 3 and b:  # delete a run
            i = rng.randrange(len(b))
            del b[i : i + rng.randrange(1, 9)]
        elif op == 4 and b:  # duplicate a block
            i = rng.randrange(len(b))
            j = min(len(b), i + rng.randrange(1, 17))
            b[i:i] = b[i:j]
        elif op == 5 and b:  # truncate
            del b[rng.randrange(len(b)) :]
        elif op == 6 and corpus:  # splice with another corpus entry
            other = rng.choice(corpus)
            if other:
                i = rng.randrange(len(b) + 1)
                j = rng.randrange(len(other))
                b = bytearray(bytes(b[:i]) + other[j:])
        elif op == 7 and b:  # varint-ish boundary overwrite
            i = rng.randrange(len(b))
            m = rng.choice(_MAGIC)
            b[i : i + len(m)] = m
        elif op == 8 and len(b) >= 2:  # swap two bytes
            i, j = rng.randrange(len(b)), rng.randrange(len(b))
            b[i], b[j] = b[j], b[i]
    return bytes(b)


@dataclass
class FuzzReport:
    target: str
    execs: int = 0
    corpus_size: int = 0
    new_entries: int = 0
    crashes: list[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    def __str__(self) -> str:
        return (
            f"{self.target}: {self.execs} execs in {self.elapsed_s:.1f}s, "
            f"corpus {self.corpus_size} (+{self.new_entries}), "
            f"{len(self.crashes)} crashes"
        )


_TOOL_NAME = "cmt-fuzz"
_tool_id: int | None = None


def _acquire_tool_id() -> int | None:
    """Claim a sys.monitoring tool id for this process, once.

    Never hijack an id another tool (e.g. coverage.py's sysmon core on
    COVERAGE_ID) already owns: prefer COVERAGE_ID when free, else the
    first free id, else None — the fuzzer then runs without coverage
    feedback rather than corrupting someone else's instrumentation."""
    global _tool_id
    if _tool_id is not None:
        return _tool_id
    # sys.monitoring is 3.12+ (PEP 669); on older interpreters the
    # sensor degrades to hits=0 (pure random fuzzing), same as when
    # every tool id is taken
    mon = getattr(sys, "monitoring", None)
    if mon is None:
        return None
    candidates = [mon.COVERAGE_ID] + [
        i for i in range(6) if i != mon.COVERAGE_ID
    ]
    for tid in candidates:
        owner = mon.get_tool(tid)
        if owner == _TOOL_NAME:
            _tool_id = tid
            return tid
        if owner is None:
            try:
                mon.use_tool_id(tid, _TOOL_NAME)
            except ValueError:
                continue
            _tool_id = tid
            return tid
    return None


class _CoverageSensor:
    """New-line detector: the LINE hook disables each line after its
    first report, so only first-ever executions cost anything.  With
    no free monitoring tool id the sensor degrades to hits=0 (pure
    random fuzzing) instead of stepping on another tool."""

    def __init__(self) -> None:
        self.hits = 0
        self._tid: int | None = None

    def __enter__(self):
        self._tid = _acquire_tool_id()
        if self._tid is not None:
            mon = sys.monitoring
            mon.register_callback(
                self._tid, mon.events.LINE, self._on_line
            )
            mon.set_events(self._tid, mon.events.LINE)
        return self

    def __exit__(self, *exc):
        if self._tid is not None:
            mon = sys.monitoring
            mon.set_events(self._tid, 0)
            mon.register_callback(self._tid, mon.events.LINE, None)

    def _on_line(self, code, line):
        self.hits += 1
        return sys.monitoring.DISABLE


class GuidedFuzzer:
    """One fuzz target: callable(bytes), a tuple of allowed exception
    types (typed rejections), seed inputs, and on-disk corpus/crash
    directories."""

    def __init__(
        self,
        name: str,
        target,
        allowed: tuple[type[BaseException], ...],
        corpus_dir: str,
        crash_dir: str,
        seeds: list[bytes] | None = None,
        seed_rng: int = 0,
    ) -> None:
        self.name = name
        self.target = target
        self.allowed = allowed
        self.corpus_dir = corpus_dir
        self.crash_dir = crash_dir
        self.rng = random.Random(seed_rng)
        os.makedirs(corpus_dir, exist_ok=True)
        os.makedirs(crash_dir, exist_ok=True)
        self.corpus: list[bytes] = []
        seen = set()
        for s in seeds or []:
            h = hashlib.sha1(s).hexdigest()[:16]
            if h not in seen:
                seen.add(h)
                self.corpus.append(s)
        for fn in sorted(os.listdir(corpus_dir)):
            with open(os.path.join(corpus_dir, fn), "rb") as f:
                data = f.read()
            h = hashlib.sha1(data).hexdigest()[:16]
            if h not in seen:
                seen.add(h)
                self.corpus.append(data)

    # -- persistence ---------------------------------------------------

    def _save(self, dirpath: str, data: bytes) -> str:
        name = hashlib.sha1(data).hexdigest()[:16] + ".bin"
        path = os.path.join(dirpath, name)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return name

    # -- execution -----------------------------------------------------

    def _exec_one(self, data: bytes, report: FuzzReport) -> bool:
        """Run the target once; returns True if new coverage appeared."""
        before = self._sensor.hits
        try:
            self.target(data)
        except self.allowed:
            pass
        except Exception as exc:  # noqa: BLE001 — the fuzzer's whole point
            name = self._save(self.crash_dir, data)
            report.crashes.append(
                f"{name}: {type(exc).__name__}: {exc}"
            )
        report.execs += 1
        return self._sensor.hits > before

    def replay(self, extra_dir: str | None = None) -> FuzzReport:
        """Re-run the corpus (and past crashes) as regression checks."""
        report = FuzzReport(target=self.name)
        t0 = time.monotonic()
        with _CoverageSensor() as self._sensor:
            for data in self.corpus:
                self._exec_one(data, report)
            for d in filter(None, (extra_dir, self.crash_dir)):
                for fn in sorted(os.listdir(d)):
                    if fn.endswith(".bin"):
                        with open(os.path.join(d, fn), "rb") as f:
                            self._exec_one(f.read(), report)
        report.corpus_size = len(self.corpus)
        report.elapsed_s = time.monotonic() - t0
        return report

    def run(
        self, max_execs: int = 5000, time_budget_s: float = 30.0
    ) -> FuzzReport:
        """Replay the corpus, then mutate under coverage feedback."""
        report = FuzzReport(target=self.name)
        t0 = time.monotonic()
        with _CoverageSensor() as self._sensor:
            for data in self.corpus:
                self._exec_one(data, report)
            deadline = t0 + time_budget_s
            while (
                report.execs < max_execs and time.monotonic() < deadline
            ):
                parent = (
                    self.rng.choice(self.corpus) if self.corpus else b""
                )
                child = mutate(self.rng, parent, self.corpus)
                if len(child) > 1 << 20:
                    continue  # keep inputs bounded
                if self._exec_one(child, report):
                    self.corpus.append(child)
                    self._save(self.corpus_dir, child)
                    report.new_entries += 1
        report.corpus_size = len(self.corpus)
        report.elapsed_s = time.monotonic() - t0
        return report
