"""Critical-path extraction — a committed height's wall, decomposed
into a fixed stage taxonomy.

The fleet plane (PR 15) measures THAT ``height_latency_p95_4node`` is
~500 ms; this module says WHICH STAGE owns it.  Given a height's span
tree — local (one tracer ring) or stitched cross-node (fleetobs's
offset-corrected trees) — the proposal-origin→commit-end wall is
decomposed into the taxonomy below.  Every decomposition satisfies
``sum(stages.values()) == wall`` exactly (residual is defined as the
remainder, floored at zero), so stage budgets reconcile with the SLO
latency by construction, and a missing span NEVER crashes the walk —
its time degrades into ``residual``.

Stage taxonomy (keep the table in docs/observability.md "Attribution
plane" in sync — tools/metrics_lint.py enforces it):

==============  =========================================================
stage           wall interval it owns
==============  =========================================================
proposal_wait   height start (or tree start) → the proposer's SEND
                stamp: waiting for a proposal to exist at all
gossip_hop      proposer's send stamp → proposal received locally (or
                on the slowest replica, cross-node): network transit
verify_spec     ``verify_queue/prepare`` time inside the vote window —
                the host phase: SHA-512 prehash, speculative-cache
                consult (hits resolve here), plan/packing
verify_launch   ``verify_queue/launch`` time inside the vote window —
                the gated device/host execute phase
quorum_wait     proposal received → +2/3 precommit, minus the verify
                time above: waiting on the NETWORK to vote
store_save      ``store/save_block`` — the atomic block+commit write
wal_fsync       ``wal/write_end_height`` — the height-boundary fsync
abci_execute    ``exec/apply_block`` — FinalizeBlock/Commit round trip
                through the application
index           ``indexer/index_block`` overlap with the height wall
                (async; the post-commit tail is the next height's
                problem)
residual        wall minus everything above: scheduling gaps, timeout
                waits, anything unattributed — an honest "don't know"
==============  =========================================================

Consumers: consensus ``_finalize_commit`` (feeds the
``AttributionMetrics`` family per committed height), the fleet smoke
(per-stage ``height_stage_p95_{stage}_4node`` ledger rows), perfdiff's
regression explanation, and ``/debug/fleet`` stage budgets.  Stdlib
only; never imported by a hot path at import time.
"""

from __future__ import annotations

#: the fixed taxonomy, in pipeline order (dominance ties break toward
#: the earlier stage)
STAGES = (
    "proposal_wait",
    "gossip_hop",
    "verify_spec",
    "verify_launch",
    "quorum_wait",
    "store_save",
    "wal_fsync",
    "abci_execute",
    "index",
    "residual",
)

#: span name -> the commit-pipeline stage it measures
_COMMIT_SPANS = {
    "store/save_block": "store_save",
    "wal/write_end_height": "wal_fsync",
    "exec/apply_block": "abci_execute",
    "indexer/index_block": "index",
}

_SPAN_ROOT = "height/pipeline"
_SPAN_PROPOSAL = "height/proposal_received"
_SPAN_ORIGIN_WALL = "height/proposal_origin_wall"
_SPAN_HOP = "p2p/recv_hop"
_SPAN_QUORUM_PREVOTE = "height/quorum_prevote"
_SPAN_QUORUM_PRECOMMIT = "height/quorum_precommit"
_SPAN_VERIFY_PREP = "verify_queue/prepare"
_SPAN_VERIFY_LAUNCH = "verify_queue/launch"
#: WAN-emulation hold (p2p/conn/netem.py) — injected, not intrinsic,
#: wall; frames are multiplexed so the span carries no height tag
_SPAN_NETEM = "p2p/netem_hold"


def _clip(start: float, end: float, lo: float, hi: float) -> float:
    """Overlap length of [start, end] with [lo, hi] (>= 0)."""
    return max(0.0, min(end, hi) - max(start, lo))


def _union_len(
    intervals: list[tuple[float, float]], lo: float, hi: float
) -> float:
    """Total length of the union of ``intervals`` clipped to
    [lo, hi] — two overlapping verify launches must not double-bill
    the vote window."""
    clipped = sorted(
        (max(s, lo), min(e, hi))
        for s, e in intervals
        if min(e, hi) > max(s, lo)
    )
    total, cur_s, cur_e = 0.0, None, None
    for s, e in clipped:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _empty_stages() -> dict[str, float]:
    return {s: 0.0 for s in STAGES}


def _is_height(ev: dict, height: int) -> bool:
    try:
        return int((ev.get("args") or {}).get("height")) == height
    except (TypeError, ValueError):
        return False


def _decompose_window(
    t0: float,
    t1: float,
    t_send: float | None,
    t_prop: float | None,
    t_qpc: float | None,
    commit_durs: dict[str, float],
    verify_prep: list[tuple[float, float]],
    verify_launch: list[tuple[float, float]],
) -> dict[str, float]:
    """The shared stage math, all times in SECONDS on one axis.
    Degrades monotonically: any missing mark zeroes its stage(s) and
    the time lands in residual."""
    stages = _empty_stages()
    wall = max(t1 - t0, 0.0)
    if wall <= 0.0:
        return stages

    def clamp(t):
        return None if t is None else min(max(t, t0), t1)

    t_send, t_prop, t_qpc = clamp(t_send), clamp(t_prop), clamp(t_qpc)
    if t_prop is not None:
        if t_send is not None and t_send <= t_prop:
            stages["proposal_wait"] = t_send - t0
            stages["gossip_hop"] = t_prop - t_send
        else:
            # no origin stamp (self-proposed, or an untagged sender):
            # the whole pre-proposal interval is proposal_wait
            stages["proposal_wait"] = t_prop - t0
    # the vote window: proposal landed -> +2/3 precommit
    if t_prop is not None and t_qpc is not None and t_qpc >= t_prop:
        prep_u = _union_len(verify_prep, t_prop, t_qpc)
        launch_u = _union_len(verify_launch, t_prop, t_qpc)
        both_u = _union_len(verify_prep + verify_launch, t_prop, t_qpc)
        # prep overlaps launch by design (the double-buffer overlap
        # proof); bill the window once, split by each side's share
        if prep_u + launch_u > 0.0:
            stages["verify_spec"] = both_u * prep_u / (prep_u + launch_u)
            stages["verify_launch"] = both_u - stages["verify_spec"]
        stages["quorum_wait"] = max(0.0, (t_qpc - t_prop) - both_u)
    for stage, dur in commit_durs.items():
        stages[stage] = max(dur, 0.0)
    attributed = sum(stages.values())
    stages["residual"] = max(0.0, wall - attributed)
    # over-attribution (clock fuzz on stitched trees, an index span
    # wider than its clip) is squeezed back so the budget still sums
    # to the wall the SLO row reports
    if attributed > wall and attributed > 0.0:
        scale = wall / attributed
        for s in STAGES:
            stages[s] *= scale
    return stages


# -- local decomposition (one tracer ring) --------------------------------


def decompose_local(
    events: list[dict], height: int, wall_epoch: float | None = None
) -> dict | None:
    """Decompose one committed height from a single ring's span
    events (trace-export ``traceEvents`` or ``SpanTracer.events()``
    shape: ts/dur in microseconds on one epoch).  Returns ``{height,
    wall_s, stages}`` or None when the height has no committed root
    span."""
    root = None
    marks: dict[str, float] = {}
    commit_spans: dict[str, tuple[float, float]] = {}
    verify_prep: list[tuple[float, float]] = []
    verify_launch: list[tuple[float, float]] = []
    origin_send_wall: float | None = None

    for ev in events:
        if ev.get("ph") not in (None, "X"):
            continue
        name = ev.get("name")
        ts = float(ev.get("ts", 0.0)) / 1e6
        dur = float(ev.get("dur", 0.0)) / 1e6
        if name == _SPAN_VERIFY_PREP:
            verify_prep.append((ts, ts + dur))
            continue
        if name == _SPAN_VERIFY_LAUNCH:
            verify_launch.append((ts, ts + dur))
            continue
        if not _is_height(ev, height):
            continue
        if name == _SPAN_ROOT:
            if root is None or ts >= root[0]:
                root = (ts, ts + dur)
        elif name == _SPAN_PROPOSAL:
            marks.setdefault("prop", ts)
        elif name in (_SPAN_ORIGIN_WALL, _SPAN_HOP):
            args = ev.get("args") or {}
            sw = args.get("send_wall") or args.get("origin_send_wall")
            if sw is not None:
                try:
                    sw = float(sw)
                except (TypeError, ValueError):
                    continue
                if origin_send_wall is None or sw < origin_send_wall:
                    origin_send_wall = sw
        elif name == _SPAN_QUORUM_PRECOMMIT:
            marks["qpc"] = max(marks.get("qpc", ts), ts)
        elif name in _COMMIT_SPANS:
            stage = _COMMIT_SPANS[name]
            prev = commit_spans.get(stage)
            if prev is None or ts >= prev[0]:
                commit_spans[stage] = (ts, ts + dur)
    if root is None:
        return None
    t0, t1 = root
    t_send = None
    if origin_send_wall is not None and wall_epoch is not None:
        t_send = origin_send_wall - wall_epoch
    commit_durs = {
        stage: _clip(s, e, t0, t1)
        for stage, (s, e) in commit_spans.items()
    }
    stages = _decompose_window(
        t0, t1, t_send, marks.get("prop"), marks.get("qpc"),
        commit_durs, verify_prep, verify_launch,
    )
    return {
        "height": int(height),
        "wall_s": round(max(t1 - t0, 0.0), 6),
        "stages": {s: round(v, 6) for s, v in stages.items()},
    }


def committed_heights(events: list[dict]) -> list[int]:
    """Heights with a ``height/pipeline`` root in the ring, sorted."""
    out = set()
    for ev in events:
        if ev.get("name") != _SPAN_ROOT:
            continue
        h = (ev.get("args") or {}).get("height")
        try:
            out.add(int(h))
        except (TypeError, ValueError):
            continue
    return sorted(out)


# -- cross-node decomposition (fleetobs stitched trees) -------------------


def decompose_stitched(
    scrapes, height: int, corrections: dict[str, float] | None = None
) -> dict | None:
    """Decompose one height across a fleet of scrapes
    (utils/fleetobs.NodeScrape), on the offset-corrected wall axis.

    Wall matches :func:`fleetobs.height_latencies_ms` exactly:
    earliest corrected origin send → latest corrected commit end.  The
    commit-pipeline stages come from the GATING node (latest commit
    end — the replica the SLO actually waited for); gossip_hop runs to
    the SLOWEST replica's proposal receipt for the same reason.
    Returns None when no node committed the height."""
    from cometbft_tpu.utils import fleetobs

    if corrections is None:
        corrections = fleetobs.clock_corrections(scrapes)
    origin_corr = {}
    for fid, name in fleetobs.node_identities(scrapes).items():
        origin_corr[fid[:16]] = corrections.get(name, 0.0)

    first_send = None
    commit_end = None
    gating = None  # (scrape, local t0..t1 seconds, shift to wall)
    prop_latest = None
    qpc_latest = None
    netem_holds: list[tuple[float, float]] = []
    for s in scrapes:
        epoch = s.wall_epoch
        if epoch is None:
            continue
        shift = epoch - corrections.get(s.name, 0.0)
        for ev in s.span_events():
            name = ev.get("name")
            ts = float(ev.get("ts", 0.0)) / 1e6
            dur = float(ev.get("dur", 0.0)) / 1e6
            if name == _SPAN_NETEM:
                netem_holds.append((shift + ts, shift + ts + dur))
                continue
            if name in (_SPAN_ORIGIN_WALL, _SPAN_HOP, _SPAN_PROPOSAL):
                if not _is_height(ev, height):
                    continue
                args = ev.get("args") or {}
                sw = args.get("send_wall") or args.get(
                    "origin_send_wall"
                )
                if sw is not None:
                    try:
                        sw = float(sw) - origin_corr.get(
                            args.get("origin") or "", 0.0
                        )
                    except (TypeError, ValueError):
                        sw = None
                    if sw is not None and (
                        first_send is None or sw < first_send
                    ):
                        first_send = sw
                if name == _SPAN_PROPOSAL:
                    w = shift + ts
                    if prop_latest is None or w > prop_latest:
                        prop_latest = w
            elif name == _SPAN_QUORUM_PRECOMMIT and _is_height(
                ev, height
            ):
                w = shift + ts
                if qpc_latest is None or w > qpc_latest:
                    qpc_latest = w
            elif name == _SPAN_ROOT and _is_height(ev, height):
                end = shift + ts + dur
                if commit_end is None or end > commit_end:
                    commit_end = end
                    gating = (s, ts, ts + dur, shift)
    if gating is None:
        return None
    g, g_t0, g_t1, g_shift = gating
    t0 = first_send if first_send is not None else g_shift + g_t0
    t1 = commit_end
    # commit-pipeline + verify intervals from the gating node, on the
    # corrected wall axis
    commit_spans: dict[str, tuple[float, float]] = {}
    verify_prep: list[tuple[float, float]] = []
    verify_launch: list[tuple[float, float]] = []
    for ev in g.span_events():
        name = ev.get("name")
        ts = g_shift + float(ev.get("ts", 0.0)) / 1e6
        dur = float(ev.get("dur", 0.0)) / 1e6
        if name == _SPAN_VERIFY_PREP:
            verify_prep.append((ts, ts + dur))
        elif name == _SPAN_VERIFY_LAUNCH:
            verify_launch.append((ts, ts + dur))
        elif name in _COMMIT_SPANS and _is_height(ev, height):
            stage = _COMMIT_SPANS[name]
            prev = commit_spans.get(stage)
            if prev is None or ts >= prev[0]:
                commit_spans[stage] = (ts, ts + dur)
    commit_durs = {
        stage: _clip(s, e, t0, t1)
        for stage, (s, e) in commit_spans.items()
    }
    stages = _decompose_window(
        t0, t1, first_send, prop_latest, qpc_latest,
        commit_durs, verify_prep, verify_launch,
    )
    # injected (netem) wall overlapping this height's window: the
    # seconds during which at least one emulated link was holding a
    # frame — read gossip_hop minus this for the INTRINSIC hop wall
    # (docs/observability.md "Scenario plane").  Kept beside, not
    # inside, the stage taxonomy: stages must keep summing to wall_s.
    injected = _union_len(netem_holds, t0, t1)
    return {
        "height": int(height),
        "wall_s": round(max(t1 - t0, 0.0), 6),
        "gating_node": g.name,
        "stages": {s: round(v, 6) for s, v in stages.items()},
        "injected_s": round(injected, 6),
    }


def stage_budgets(
    scrapes, corrections: dict[str, float] | None = None
) -> dict[int, dict]:
    """Every committed height in the fleet, decomposed — the
    ``/debug/fleet`` stage-budget table and the fleet smoke's ledger
    input."""
    from cometbft_tpu.utils import fleetobs

    if corrections is None:
        corrections = fleetobs.clock_corrections(scrapes)
    heights: set[int] = set()
    for s in scrapes:
        heights.update(committed_heights(s.span_events()))
    out: dict[int, dict] = {}
    for h in sorted(heights):
        d = decompose_stitched(scrapes, h, corrections=corrections)
        if d is not None:
            out[h] = d
    return out


def budget_at_percentile(
    budgets: dict[int, dict], p: float = 95.0
) -> dict | None:
    """The stage budget OF the percentile height: nearest-rank on
    wall_s picks an actual height, and that height's decomposition is
    returned — so the per-stage ledger rows sum (with residual) to the
    latency row they explain, by construction."""
    if not budgets:
        return None
    ranked = sorted(budgets.values(), key=lambda d: d["wall_s"])
    import math

    idx = max(
        0, min(len(ranked) - 1, math.ceil(p / 100.0 * len(ranked)) - 1)
    )
    return ranked[idx]


# -- runtime hook (consensus _finalize_commit) ----------------------------


def dominant_stage(stages: dict[str, float]) -> str:
    """The stage that owns the height (ties break in pipeline order)."""
    best = STAGES[0]
    for s in STAGES:
        if stages.get(s, 0.0) > stages.get(best, 0.0):
            best = s
    return best


def observe_height(height: int, tracer=None, metrics=None) -> dict | None:
    """Decompose ``height`` from the live ring and feed the
    AttributionMetrics family: every stage's seconds into the
    ``attribution_height_stage_seconds`` histogram, and the dominant
    stage one-hot into ``attribution_height_critical_stage``.
    Best-effort by contract — observability must never fail a commit."""
    try:
        if tracer is None:
            from cometbft_tpu.utils.trace import TRACER as tracer
        if metrics is None:
            from cometbft_tpu.metrics import attribution_metrics

            metrics = attribution_metrics()
        d = decompose_local(
            tracer.events(), height, wall_epoch=tracer.epoch_wall
        )
        if d is None:
            return None
        dom = dominant_stage(d["stages"])
        for stage in STAGES:
            metrics.height_stage_seconds.labels(stage=stage).observe(
                d["stages"].get(stage, 0.0)
            )
            metrics.height_critical_stage.labels(stage=stage).set(
                1.0 if stage == dom else 0.0
            )
        d["critical_stage"] = dom
        return d
    except Exception:  # noqa: BLE001 — observability, never liveness
        return None


__all__ = [
    "STAGES",
    "budget_at_percentile",
    "committed_heights",
    "decompose_local",
    "decompose_stitched",
    "dominant_stage",
    "observe_height",
    "stage_budgets",
]
