"""Deterministic protobuf wire-format writer/reader.

The consensus-critical encodings (vote sign-bytes, header fields, wire
messages) must be byte-deterministic. The reference relies on gogoproto
marshalling (types/canonical.go:57, libs/protoio); here we implement the
wire format directly — fields are always emitted in ascending field-number
order with no unknown fields, which makes determinism a construction-time
property instead of a library promise.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Iterator


# single-byte varints (field keys, small lengths, counts) dominate the
# call profile — the QA campaign measured 1.29M encode_uvarint calls in
# a 60 s saturation run, almost all < 0x80 — so they come from a table
_UV1 = [bytes([i]) for i in range(0x80)]


def encode_uvarint(n: int) -> bytes:
    if n < 0x80:
        if n < 0:
            raise ValueError("uvarint must be non-negative")
        return _UV1[n]
    out = bytearray()
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def decode_uvarint(buf: bytes, offset: int = 0) -> tuple[int, int]:
    """Returns (value, next_offset)."""
    result = 0
    shift = 0
    while True:
        if offset >= len(buf):
            raise ValueError("truncated uvarint")
        b = buf[offset]
        offset += 1
        # Reject 64-bit overflow before accumulating (binary.Uvarint parity:
        # at most 10 bytes, and the 10th byte may only contribute bit 63).
        if shift > 63 or (shift == 63 and (b & 0x7F) > 1):
            raise ValueError("uvarint overflows 64 bits")
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, offset
        shift += 7


def read_uvarint_from(read_exact, max_value: int = 1 << 63) -> int:
    """Decode a uvarint from a stream via ``read_exact(1)`` calls,
    rejecting values above ``max_value`` before any allocation happens.
    Shared by the p2p transport and MConnection packet reader so
    length-cap enforcement lives in one place."""
    result, shift = 0, 0
    while True:
        b = read_exact(1)[0]
        if shift > 63:
            raise ValueError("uvarint overflows 64 bits")
        result |= (b & 0x7F) << shift
        if result > max_value:
            raise ValueError(f"uvarint {result} exceeds cap {max_value}")
        if not (b & 0x80):
            return result
        shift += 7


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class ProtoWriter:
    """Appends protobuf fields; caller must emit in ascending tag order."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def _key(self, field: int, wire_type: int) -> None:
        key = (field << 3) | wire_type
        # fields <= 15 (every message here) key in one table byte
        self._buf += _UV1[key] if key < 0x80 else encode_uvarint(key)

    def varint(self, field: int, value: int) -> None:
        """int32/int64/uint64/bool/enum. Negative ints use two's complement
        64-bit (protobuf int64 semantics)."""
        if value == 0:
            return
        self._key(field, 0)
        self._buf += encode_uvarint(value & 0xFFFFFFFFFFFFFFFF)

    def svarint(self, field: int, value: int) -> None:
        """sint64 (zigzag)."""
        if value == 0:
            return
        self._key(field, 0)
        self._buf += encode_uvarint(_zigzag(value))

    def bool_(self, field: int, value: bool) -> None:
        self.varint(field, 1 if value else 0)

    def sfixed64(self, field: int, value: int) -> None:
        if value == 0:
            return
        self._key(field, 1)
        self._buf += struct.pack("<q", value)

    def fixed64(self, field: int, value: int) -> None:
        if value == 0:
            return
        self._key(field, 1)
        self._buf += struct.pack("<Q", value)

    def bytes_(self, field: int, value: bytes) -> None:
        if not value:
            return
        self._key(field, 2)
        self._buf += encode_uvarint(len(value))
        self._buf += value

    def string(self, field: int, value: str) -> None:
        self.bytes_(field, value.encode("utf-8"))

    def message(self, field: int, value: bytes | None) -> None:
        """Embedded message; ``None`` omits, ``b''`` emits an empty message
        (proto3 presence for message fields)."""
        if value is None:
            return
        self._key(field, 2)
        self._buf += encode_uvarint(len(value))
        self._buf += value

    def finish(self) -> bytes:
        return bytes(self._buf)


def length_prefixed(payload: bytes) -> bytes:
    """Length-delimited framing used for sign-bytes and wire I/O
    (reference: libs/protoio delimited writer; types/vote.go:151)."""
    return encode_uvarint(len(payload)) + payload


def read_length_prefixed(buf: bytes, offset: int = 0) -> tuple[bytes, int]:
    n, offset = decode_uvarint(buf, offset)
    if offset + n > len(buf):
        raise ValueError("truncated length-prefixed payload")
    return buf[offset : offset + n], offset + n


class ProtoReader:
    """Minimal field iterator for decoding our own messages."""

    def __init__(self, buf: bytes):
        self.buf = buf

    def fields(self) -> Iterator[tuple[int, int, int | bytes]]:
        """Yields (field_number, wire_type, value)."""
        buf, off = self.buf, 0
        while off < len(buf):
            key, off = decode_uvarint(buf, off)
            field, wt = key >> 3, key & 7
            if field == 0:
                # proto3 field numbers start at 1; rejecting 0 also cuts
                # off degenerate all-zero buffers immediately
                raise ValueError("invalid field number 0")
            if wt == 0:
                val, off = decode_uvarint(buf, off)
                yield field, wt, val
            elif wt == 1:
                if off + 8 > len(buf):
                    raise ValueError("truncated fixed64")
                yield field, wt, struct.unpack_from("<Q", buf, off)[0]
                off += 8
            elif wt == 2:
                ln, off = decode_uvarint(buf, off)
                if off + ln > len(buf):
                    raise ValueError("truncated bytes field")
                yield field, wt, buf[off : off + ln]
                off += ln
            elif wt == 5:
                if off + 4 > len(buf):
                    raise ValueError("truncated fixed32")
                yield field, wt, struct.unpack_from("<I", buf, off)[0]
                off += 4
            else:
                raise ValueError(f"unsupported wire type {wt}")

    def to_dict(self) -> dict[int, list[int | bytes]]:
        out: dict[int, list[int | bytes]] = {}
        for field, _, val in self.fields():
            out.setdefault(field, []).append(val)
        return out


def sfixed64_from_u64(v: int) -> int:
    return struct.unpack("<q", struct.pack("<Q", v))[0]


def int64_from_varint(v: int) -> int:
    return sfixed64_from_u64(v & 0xFFFFFFFFFFFFFFFF)
