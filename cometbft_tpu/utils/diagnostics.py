"""Runtime diagnostics — the pprof analog
(reference: node/node.go:589 startPprofServer + net/http/pprof).

A tiny HTTP server (config [rpc] pprof_laddr) exposing what a
Python/JAX node can usefully dump:

  /debug/stacks    every thread's current stack (goroutine-dump analog)
  /debug/threads   thread table with names/daemon flags
  /debug/gc        gc counters + top object types by count
  /debug/profile?seconds=N   cProfile of the whole process for N
                   seconds (pprof-style CPU profile, pstats text)
  /debug/jax/start_trace?dir=...  start the XLA device profiler
  /debug/jax/stop_trace           stop it (trace viewable in
                   TensorBoard/XProf — the TPU-side profiler hook)
  /debug/jax/memory               per-device live-buffer stats

``install_stack_dump_signal`` registers SIGUSR1 to append all stacks
to <home>/data/stacks.dump — crash forensics for `debug kill`
(cmd/cometbft/commands/debug/kill.go sends SIGABRT for the same
purpose)."""

from __future__ import annotations

import faulthandler
import gc
import io
import json
import signal
import sys
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from cometbft_tpu.utils.log import Logger, default_logger
from cometbft_tpu.utils.service import BaseService


def format_stacks() -> str:
    """All thread stacks, named (runtime.Stack analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(
            line.rstrip()
            for line in traceback.format_stack(frame)
        )
    return "\n".join(out) + "\n"


def gc_summary(top: int = 20) -> dict:
    counts: dict[str, int] = {}
    for obj in gc.get_objects():
        name = type(obj).__name__
        counts[name] = counts.get(name, 0) + 1
    return {
        "collections": gc.get_count(),
        "threshold": gc.get_threshold(),
        "top_types": sorted(
            counts.items(), key=lambda kv: -kv[1]
        )[:top],
    }


def cpu_profile(seconds: float, interval: float = 0.01) -> str:
    """Statistical whole-process profile: sample every thread's stack
    at ``interval`` for ``seconds`` and aggregate frame hit counts.
    (cProfile only instruments its own thread — useless from an HTTP
    handler; sampling sys._current_frames sees consensus/verify/p2p
    threads too, pprof-style.)"""
    import time

    seconds = max(0.05, min(seconds, 120.0))
    counts: dict[str, int] = {}
    samples = 0
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack = traceback.extract_stack(frame)
            for fr in stack[-12:]:
                key = f"{fr.filename}:{fr.lineno} {fr.name}"
                counts[key] = counts.get(key, 0) + 1
            if stack:
                leaf = stack[-1]
                key = f"LEAF {leaf.filename}:{leaf.lineno} {leaf.name} [{names.get(tid, tid)}]"
                counts[key] = counts.get(key, 0) + 1
        samples += 1
        time.sleep(interval)
    buf = io.StringIO()
    buf.write(
        f"statistical profile: {samples} samples over {seconds}s "
        f"({interval*1e3:.0f}ms interval)\n\nhits  frame\n"
    )
    for key, n in sorted(counts.items(), key=lambda kv: -kv[1])[:80]:
        buf.write(f"{n:5d}  {key}\n")
    return buf.getvalue()


class DiagnosticsServer(BaseService):
    """(node.go startPprofServer)"""

    def __init__(self, addr: str, logger: Logger | None = None):
        super().__init__(
            name="diagnostics",
            logger=logger or default_logger().with_fields(module="pprof"),
        )
        host_port = addr.split("://")[-1]
        host, _, port = host_port.rpartition(":")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                outer.logger.debug("pprof " + (fmt % args))

            def _send(self, body: str, ctype="text/plain", status=200):
                raw = body.encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):
                url = urlparse(self.path)
                params = dict(parse_qsl(url.query))
                try:
                    self._route(url.path, params)
                except Exception as exc:  # noqa: BLE001
                    self._send(f"error: {exc!r}\n", status=500)

            def _route(self, path: str, params: dict):
                if path == "/debug/stacks":
                    self._send(format_stacks())
                elif path == "/debug/threads":
                    rows = [
                        {
                            "name": t.name,
                            "ident": t.ident,
                            "daemon": t.daemon,
                            "alive": t.is_alive(),
                        }
                        for t in threading.enumerate()
                    ]
                    self._send(json.dumps(rows), "application/json")
                elif path == "/debug/gc":
                    self._send(
                        json.dumps(gc_summary()), "application/json"
                    )
                elif path == "/debug/profile":
                    secs = float(params.get("seconds", "5"))
                    self._send(cpu_profile(secs))
                elif path == "/debug/jax/start_trace":
                    import jax

                    trace_dir = params.get("dir", "/tmp/jax-trace")
                    jax.profiler.start_trace(trace_dir)
                    self._send(f"tracing to {trace_dir}\n")
                elif path == "/debug/jax/stop_trace":
                    import jax

                    jax.profiler.stop_trace()
                    self._send("trace stopped\n")
                elif path == "/debug/jax/memory":
                    import jax

                    stats = []
                    for dev in jax.devices():
                        try:
                            stats.append(
                                {
                                    "device": str(dev),
                                    **(dev.memory_stats() or {}),
                                }
                            )
                        except Exception:  # noqa: BLE001
                            stats.append({"device": str(dev)})
                    self._send(json.dumps(stats), "application/json")
                else:
                    self._send(
                        "routes: /debug/{stacks,threads,gc,profile,"
                        "jax/start_trace,jax/stop_trace,jax/memory}\n",
                        status=404,
                    )

        self._httpd = ThreadingHTTPServer(
            (host or "127.0.0.1", int(port or 0)), Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]

    def on_start(self) -> None:
        threading.Thread(
            target=self._httpd.serve_forever,
            name="diagnostics-http",
            daemon=True,
        ).start()
        self.logger.info("diagnostics server listening", port=self.port)

    def on_stop(self) -> None:
        self._httpd.shutdown()


def install_stack_dump_signal(dump_path: str) -> None:
    """SIGUSR1 → append all thread stacks to ``dump_path`` (the
    `debug kill` handshake; also useful on wedged nodes)."""
    f = open(dump_path, "a")  # noqa: SIM115 — lives for the process
    faulthandler.register(signal.SIGUSR1, file=f, all_threads=True)


__all__ = [
    "DiagnosticsServer",
    "cpu_profile",
    "format_stacks",
    "gc_summary",
    "install_stack_dump_signal",
]
