"""Fleet observability aggregator — N nodes' rings on ONE timeline.

Four per-process observability planes exist (metrics, span tracer,
flight recorder, device health), but a localnet is a SYSTEM: a
height's wall-clock starts when the proposer stamps the proposal, not
when each replica's `_height_t0` sees it arrive.  This module scrapes
N nodes' ``/metrics``, ``/trace`` and ``/debug/flight`` surfaces,
aligns them on wall clock (each trace ring exports its
``wall_epoch`` anchor; flight events are wall-stamped by contract),
and produces:

- one merged Chrome trace-event file (pid = node) a human loads in
  Perfetto to SEE proposal → gossip hop → quorum → commit across the
  fleet;
- stitched per-height trees keyed by (height, round, origin) — the
  ``p2p/recv_hop`` spans recorded by trace-context-stamped gossip are
  the joints;
- cross-node proposal→commit height latencies (the
  ``height_latency_p95_4node`` SLO the perf ledger gates);
- a fleet rollup (per-node committed height + lag, one-hot dispatch
  tier, verify-queue depths, hop-latency aggregates) — the skew/lag
  table an operator reads first, served live via ``/debug/fleet``.

Clock alignment (docs/observability.md "Fleet plane"): the merged
timeline and stitched latencies are OFFSET-CORRECTED onto the first
scrape's clock using the mesh's own pong-piggyback estimates —
:func:`node_identities` recovers which scrape is which node from the
``p2p_peer_clock_offset_seconds`` gauges (every node names its
peers, so its own id is the one it never names), and
:func:`clock_corrections` reads the reference node's estimate for
each.  The estimates are ms-scale (RTT halved), so trust the
corrected timeline to about a link RTT, not to microseconds; nodes
the gauges can't identify (pre-fleet peers, the first ~10 s before a
stamped pong) fall back to raw wall clock, which on a same-box
localnet is exact anyway.  No third-party deps (stdlib + the
in-repo sync/metrics seams); never imported by a hot path.
"""

from __future__ import annotations

import json
import math
import re
import time
import urllib.request
from dataclasses import dataclass, field

from cometbft_tpu.utils import sync as cmtsync

#: span names the stitcher joins into a height tree
_SPAN_COMMIT = "height/pipeline"
_SPAN_HOP = "p2p/recv_hop"
_SPAN_PROPOSAL = "height/proposal_received"
_SPAN_ORIGIN_WALL = "height/proposal_origin_wall"
_SPAN_QUORUM = ("height/quorum_prevote", "height/quorum_precommit")


@dataclass
class NodeScrape:
    """One node's three surfaces, as scraped (or read in-process)."""

    name: str
    target: str | None = None  # base URL; None = read in-process
    metrics: list = field(default_factory=list)  # (series, labels, value)
    flight: list = field(default_factory=list)   # wall-stamped events
    trace: dict = field(default_factory=dict)    # Chrome export object
    error: str | None = None

    @property
    def wall_epoch(self) -> float | None:
        """The trace ring's wall anchor (None from pre-fleet nodes)."""
        return (self.trace.get("otherData") or {}).get("wall_epoch")

    def span_events(self) -> list[dict]:
        return [
            e
            for e in self.trace.get("traceEvents", ())
            if e.get("ph") == "X"
        ]


# -- prometheus text parsing ---------------------------------------------

_SERIES_RE = re.compile(
    r'^([A-Za-z_:][\w:]*)(\{(.*)\})?\s+(\S+)\s*$'
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prom_text(text: str) -> list[tuple[str, dict, float]]:
    """Minimal text-exposition parser for the families the rollup
    reads (counters/gauges + histogram _sum/_count/_bucket lines)."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        name, _, rawlabels, rawvalue = m.groups()
        labels = (
            {
                k: v.replace('\\"', '"').replace("\\\\", "\\")
                for k, v in _LABEL_RE.findall(rawlabels)
            }
            if rawlabels
            else {}
        )
        try:
            value = float(rawvalue)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def series(
    scrape: NodeScrape, suffix: str, labels: dict | None = None
) -> list[tuple[dict, float]]:
    """All samples of a series, matched by SUFFIX (``/metrics`` names
    carry the registry namespace prefix; callers speak the
    lint/doc-level ``<subsystem>_<field>`` names) with an optional
    label-subset filter."""
    want = labels or {}
    out = []
    for name, lbl, value in scrape.metrics:
        if name != suffix and not name.endswith("_" + suffix):
            continue
        if all(lbl.get(k) == v for k, v in want.items()):
            out.append((lbl, value))
    return out


def series_value(
    scrape: NodeScrape, suffix: str, labels: dict | None = None
) -> float | None:
    got = series(scrape, suffix, labels)
    return got[0][1] if got else None


# -- scraping -------------------------------------------------------------


def _base_url(target: str) -> str:
    return target if "://" in target else f"http://{target}"


def _get(url: str, timeout: float) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def scrape_node(
    target: str, name: str | None = None, timeout: float = 2.0
) -> NodeScrape:
    """Scrape one node's metrics server (all three surfaces).  Errors
    land in ``NodeScrape.error`` — one dead node must not blank the
    fleet view — and in the ``fleet_scrapes`` counter."""
    from cometbft_tpu.metrics import fleet_metrics

    base = _base_url(target)
    name = name or target
    s = NodeScrape(name=name, target=base)
    t0 = time.perf_counter()
    try:
        s.metrics = parse_prom_text(
            _get(base + "/metrics", timeout).decode("utf-8", "replace")
        )
        s.trace = json.loads(_get(base + "/trace", timeout))
        s.flight = json.loads(_get(base + "/debug/flight", timeout)).get(
            "events", []
        )
        fleet_metrics().scrapes.labels(node=name, result="ok").inc()
    except Exception as exc:  # noqa: BLE001 — a dead peer is a data point
        s.error = repr(exc)
        fleet_metrics().scrapes.labels(node=name, result="error").inc()
    fleet_metrics().scrape_seconds.observe(time.perf_counter() - t0)
    return s


def self_scrape(name: str = "self", registry=None) -> NodeScrape:
    """Read this process's own surfaces directly (no HTTP): the
    ``/debug/fleet`` handler holds the registry/TRACER/FLIGHT handles,
    so a wire round trip through its own server would only add
    latency and a serialization/parse cycle for identical data."""
    from cometbft_tpu.utils.flight import FLIGHT
    from cometbft_tpu.utils.trace import TRACER

    s = NodeScrape(name=name, target=None)
    if registry is not None:
        s.metrics = parse_prom_text(registry.expose())
    s.trace = TRACER.export()
    s.flight = FLIGHT.events()
    return s


def scrape_fleet(
    targets: list[str],
    names: list[str] | None = None,
    timeout: float = 2.0,
    include_self: bool = False,
    self_name: str = "self",
    self_registry=None,
) -> list[NodeScrape]:
    """Scrape every target CONCURRENTLY (one dead peer's connect
    timeout must cost the fleet view max(timeout), not N x timeout —
    /debug/fleet serves from a request handler).

    Concurrency is BOUNDED by ``CMT_TPU_FLEET_SCRAPE_POOL`` (default
    8): one thread per target was fine at 4 nodes and is a thread
    burst at 32 — the scenario fleet scales node-count, the pool does
    not.  Workers are named ``fleet-scrape*`` and joined before
    return, so the thread-leak gate can hold this seam to zero."""
    out: list[NodeScrape] = []
    if include_self:
        out.append(self_scrape(self_name, self_registry))
    if not targets:
        return out
    from concurrent.futures import ThreadPoolExecutor

    from cometbft_tpu.utils.env import int_from_env

    bound = int_from_env("CMT_TPU_FLEET_SCRAPE_POOL", 8, minimum=1)

    def one(i_t):
        i, t = i_t
        n = names[i] if names and i < len(names) else None
        return scrape_node(t, name=n, timeout=timeout)

    with ThreadPoolExecutor(
        max_workers=min(bound, len(targets)),
        thread_name_prefix="fleet-scrape",
    ) as pool:
        out.extend(pool.map(one, enumerate(targets)))
    return out


# -- clock alignment ------------------------------------------------------


def node_identities(scrapes: list[NodeScrape]) -> dict[str, str]:
    """full node id -> scrape name, derived from the mesh's own
    offset gauges: every node's ``p2p_peer_clock_offset_seconds``
    names its PEERS, so in a full mesh a node's own id is exactly the
    one id every other node names and it never names itself.  A node
    with no stamped-pong samples yet (first ~10 s, or a pre-fleet
    peer) stays unmapped — its correction falls back to zero."""
    per: dict[str, set[str]] = {}
    for s in scrapes:
        per[s.name] = {
            lbl.get("peer_id", "")
            for lbl, _ in series(s, "p2p_peer_clock_offset_seconds")
        }
    all_ids = set().union(*per.values()) if per else set()
    out: dict[str, str] = {}
    for s in scrapes:
        if not per[s.name]:
            continue  # no peer evidence — can't isolate its own id
        own = all_ids - per[s.name]
        if len(own) == 1:
            out[next(iter(own))] = s.name
    return out


def clock_corrections(scrapes: list[NodeScrape]) -> dict[str, float]:
    """scrape name -> estimated ``that_node_wall - reference_wall``
    (reference = first scrape), i.e. the seconds to SUBTRACT from a
    node's wall stamps to land on the reference clock.  Uses the
    reference node's own pong-piggyback offset gauges, routed through
    :func:`node_identities`; anything underdetermined corrects by 0
    (same-box localnets are already aligned)."""
    if not scrapes:
        return {}
    name_to_id = {v: k for k, v in node_identities(scrapes).items()}
    ref = scrapes[0]
    ref_off = {
        lbl.get("peer_id", ""): v
        for lbl, v in series(ref, "p2p_peer_clock_offset_seconds")
    }
    corr = {ref.name: 0.0}
    for s in scrapes[1:]:
        fid = name_to_id.get(s.name)
        corr.setdefault(
            s.name, float(ref_off.get(fid, 0.0)) if fid else 0.0
        )
    return corr


def _origin_corrections(
    scrapes: list[NodeScrape], corrections: dict[str, float]
) -> dict[str, float]:
    """origin id PREFIX (as hop/proposal spans carry, ``id[:16]``) ->
    the origin node's clock correction."""
    out = {}
    for fid, name in node_identities(scrapes).items():
        out[fid[:16]] = corrections.get(name, 0.0)
    return out


# -- merged timeline ------------------------------------------------------


def _fleet_t0(
    scrapes: list[NodeScrape], corrections: dict[str, float]
) -> float:
    anchors = [
        s.wall_epoch - corrections.get(s.name, 0.0)
        for s in scrapes
        if s.wall_epoch
    ]
    anchors += [
        ev["t"] - corrections.get(s.name, 0.0)
        for s in scrapes
        for ev in s.flight
        if "t" in ev
    ]
    return min(anchors) if anchors else 0.0


def merge_traces(
    scrapes: list[NodeScrape],
    corrections: dict[str, float] | None = None,
) -> dict:
    """One Chrome trace across the fleet: pid = node index (named via
    process_name metadata), every span/flight event re-timed onto the
    OFFSET-CORRECTED shared wall axis (reference = first scrape,
    corrections from the mesh's own pong-piggyback offset gauges;
    earliest corrected anchor = 0)."""
    if corrections is None:
        corrections = clock_corrections(scrapes)
    t0 = _fleet_t0(scrapes, corrections)
    events: list[dict] = []
    for pid, s in enumerate(scrapes):
        events.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": s.name},
            }
        )
        corr = corrections.get(s.name, 0.0)
        epoch = s.wall_epoch
        if epoch is not None:
            shift_us = (epoch - corr - t0) * 1e6
            for e in s.span_events():
                e2 = dict(e, pid=pid)
                e2["ts"] = round(e.get("ts", 0.0) + shift_us, 1)
                events.append(e2)
            # keep per-thread track names readable under the node pid
            for e in s.trace.get("traceEvents", ()):
                if e.get("ph") == "M" and e.get("name") == "thread_name":
                    events.append(dict(e, pid=pid))
        for ev in s.flight:
            if "t" not in ev:
                continue
            events.append(
                {
                    "name": ev.get("kind", "event"),
                    "ph": "i",
                    "s": "p",
                    "pid": pid,
                    "tid": 0,
                    "ts": round((ev["t"] - corr - t0) * 1e6, 1),
                    "cat": "flight",
                    "args": {
                        k: v for k, v in ev.items() if k not in ("t",)
                    },
                }
            )
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "wall_epoch": t0,
            "nodes": [s.name for s in scrapes],
            "clock_corrections": corrections,
            "scrape_errors": {
                s.name: s.error for s in scrapes if s.error
            },
        },
    }


# -- height stitching -----------------------------------------------------


def stitch_heights(
    scrapes: list[NodeScrape],
    corrections: dict[str, float] | None = None,
) -> dict[int, dict]:
    """Join each node's span fragments into per-height trees.

    A height is COMPLETE when the fleet saw its proposal land, at
    least one gossip hop (``p2p/recv_hop``), a quorum mark, and a
    commit (``height/pipeline`` root) — with the hop origins telling
    us how many distinct nodes' sends are in the tree.  Wall times
    come from each ring's ``wall_epoch`` anchor and are mapped onto
    the reference clock via :func:`clock_corrections` (commit ends by
    the SCRAPING node's correction, origin send stamps by the ORIGIN
    node's); ``first_send_wall`` is then the earliest corrected
    origin send stamp anywhere in the fleet (the network-inclusive
    start), ``commit_end_wall`` the latest corrected commit
    completion (the network-inclusive end).
    """
    if corrections is None:
        corrections = clock_corrections(scrapes)
    origin_corr = _origin_corrections(scrapes, corrections)
    heights: dict[int, dict] = {}

    def h_entry(h) -> dict:
        return heights.setdefault(
            int(h),
            {
                "proposal": False,
                "quorum": False,
                "commit": False,
                "hops": 0,
                "origins": set(),
                "committed_on": set(),
                "first_send_wall": None,
                "commit_end_wall": None,
            },
        )

    def corrected_send(args) -> float | None:
        sw = args.get("send_wall") or args.get("origin_send_wall")
        if sw is None:
            return None
        return sw - origin_corr.get(args.get("origin") or "", 0.0)

    for s in scrapes:
        epoch = s.wall_epoch
        corr = corrections.get(s.name, 0.0)
        for e in s.span_events():
            args = e.get("args") or {}
            h = args.get("height")
            if h is None:
                continue
            name = e.get("name")
            if name == _SPAN_COMMIT:
                ent = h_entry(h)
                ent["commit"] = True
                ent["committed_on"].add(s.name)
                if epoch is not None:
                    end = (
                        epoch - corr
                        + (e.get("ts", 0.0) + e.get("dur", 0.0)) / 1e6
                    )
                    if (
                        ent["commit_end_wall"] is None
                        or end > ent["commit_end_wall"]
                    ):
                        ent["commit_end_wall"] = end
            elif name == _SPAN_HOP:
                ent = h_entry(h)
                ent["hops"] += 1
                if args.get("origin"):
                    ent["origins"].add(args["origin"])
                sw = corrected_send(args)
                if sw is not None and (
                    ent["first_send_wall"] is None
                    or sw < ent["first_send_wall"]
                ):
                    ent["first_send_wall"] = sw
            elif name in (_SPAN_PROPOSAL, _SPAN_ORIGIN_WALL):
                ent = h_entry(h)
                ent["proposal"] = True
                sw = corrected_send(args)
                if sw is not None and (
                    ent["first_send_wall"] is None
                    or sw < ent["first_send_wall"]
                ):
                    ent["first_send_wall"] = sw
            elif name in _SPAN_QUORUM:
                h_entry(h)["quorum"] = True
    return heights


def complete_heights(
    stitched: dict[int, dict], min_origins: int = 2
) -> list[int]:
    """Heights whose tree has every stage plus hops from at least
    ``min_origins`` distinct origin nodes."""
    return sorted(
        h
        for h, ent in stitched.items()
        if ent["proposal"]
        and ent["quorum"]
        and ent["commit"]
        and ent["hops"] > 0
        and len(ent["origins"]) >= min_origins
    )


def height_latencies_ms(stitched: dict[int, dict]) -> dict[int, float]:
    """Cross-node proposal→commit latency per height: earliest origin
    send stamp to latest commit completion, fleet-wide."""
    out = {}
    for h, ent in sorted(stitched.items()):
        if ent["first_send_wall"] is None or ent["commit_end_wall"] is None:
            continue
        out[h] = max(
            0.0, (ent["commit_end_wall"] - ent["first_send_wall"]) * 1e3
        )
    return out


def percentile(values: list[float], p: float) -> float:
    """Nearest-rank percentile (the ledger's latency rows use p95)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = max(0, min(len(vs) - 1, math.ceil(p / 100.0 * len(vs)) - 1))
    return vs[idx]


# -- fleet rollup ---------------------------------------------------------

#: node labels whose fleet_height_lag child the last rollup set — a
#: repointed CMT_TPU_FLEET_PEERS or a newly-erroring peer must retire
#: its child (the p2p plane's departed-peer convention), not leave a
#: frozen lag tripping alerts for a node that no longer reports.
#: Guarded by _LAG_MTX: /debug/fleet is served by per-request threads
#: (and the JSON-RPC route by another server), so two concurrent
#: rollups would otherwise race the retire-then-replace sequence.
_LAG_NODES_SET: set[str] = set()
_LAG_MTX = cmtsync.Mutex()


def fleet_rollup(scrapes: list[NodeScrape]) -> dict:
    """The skew/lag table: per-node commit height (+lag behind the
    fleet max), one-hot dispatch tier, verify-queue depths, gossip-hop
    aggregates, peer count, clock offsets.  Feeds the FleetMetrics
    gauges on the aggregating node."""
    from cometbft_tpu.metrics import fleet_metrics

    nodes = []
    heights = {}
    for s in scrapes:
        tier = None
        for lbl, v in series(s, "crypto_dispatch_current_tier"):
            if v >= 1.0:
                tier = lbl.get("tier")
                break
        queue_depth = {
            lbl.get("priority", ""): v
            for lbl, v in series(s, "crypto_verify_queue_depth")
        }
        hop_count = sum(
            v for _, v in series(s, "p2p_gossip_hop_seconds_count")
        )
        hop_sum = sum(v for _, v in series(s, "p2p_gossip_hop_seconds_sum"))
        height = series_value(s, "consensus_latest_block_height")
        if height is not None:
            heights[s.name] = int(height)
        nodes.append(
            {
                "node": s.name,
                "target": s.target,
                "error": s.error,
                "height": None if height is None else int(height),
                "dispatch_tier": tier,
                "verify_queue_depth": queue_depth,
                "peers": series_value(s, "p2p_peers"),
                "gossip_hops": int(hop_count),
                "gossip_hop_avg_ms": (
                    round(hop_sum / hop_count * 1e3, 3) if hop_count else None
                ),
                "clock_offsets": {
                    lbl.get("peer_id", "")[:16]: v
                    for lbl, v in series(s, "p2p_peer_clock_offset_seconds")
                },
            }
        )
    max_h = max(heights.values()) if heights else 0
    skew = (max_h - min(heights.values())) if heights else 0
    lag_set = set()
    for n in nodes:
        n["height_lag"] = (
            None if n["height"] is None else max_h - n["height"]
        )
        if n["height"] is not None:
            lag_set.add(n["node"])
    with _LAG_MTX:
        for n in nodes:
            if n["height"] is not None:
                fleet_metrics().height_lag.labels(node=n["node"]).set(
                    n["height_lag"]
                )
        for stale in _LAG_NODES_SET - lag_set:
            fleet_metrics().height_lag.remove(node=stale)
        _LAG_NODES_SET.clear()
        _LAG_NODES_SET.update(lag_set)
    fleet_metrics().nodes.set(len(scrapes))
    fleet_metrics().height_skew.set(skew)
    return {
        "nodes": nodes,
        "max_height": max_h,
        "height_skew": skew,
        "scrape_errors": sum(1 for s in scrapes if s.error),
    }


def fleet_payload(
    scrapes: list[NodeScrape], include_trace: bool = False
) -> dict:
    """The ``/debug/fleet`` JSON: rollup + stitched-height summary (+
    the full merged Chrome trace on request)."""
    corrections = clock_corrections(scrapes)
    stitched = stitch_heights(scrapes, corrections=corrections)
    lat = height_latencies_ms(stitched)
    payload = {
        "rollup": fleet_rollup(scrapes),
        "stitched_heights": {
            h: {
                **{
                    k: (sorted(v) if isinstance(v, set) else v)
                    for k, v in ent.items()
                },
                "latency_ms": round(lat[h], 3) if h in lat else None,
            }
            for h, ent in sorted(stitched.items())
        },
        "complete_heights": complete_heights(stitched),
        "height_latency_p95_ms": (
            round(percentile(list(lat.values()), 95.0), 3) if lat else None
        ),
    }
    payload["clock_corrections"] = corrections
    # scenario plane: the active scenario this node was launched
    # under (wan/byzantine/churn runner sets CMT_TPU_SCENARIO), so a
    # /debug/fleet reader knows WHICH conditions produced the numbers
    from cometbft_tpu.utils.env import name_from_env

    payload["scenario"] = name_from_env("CMT_TPU_SCENARIO", None)
    # attribution plane: each committed height's wall decomposed into
    # the critpath stage taxonomy on the same corrected axis (the
    # stage budget an operator reads AFTER the p95 row says "slow")
    try:
        from cometbft_tpu.utils import critpath

        budgets = critpath.stage_budgets(
            scrapes, corrections=corrections
        )
        payload["stage_budgets"] = {
            h: d for h, d in sorted(budgets.items())
        }
        p95 = critpath.budget_at_percentile(budgets, 95.0)
        payload["stage_budget_p95"] = p95
        if p95 is not None:
            payload["critical_stage_p95"] = critpath.dominant_stage(
                p95["stages"]
            )
    except Exception:  # noqa: BLE001 — diagnostics, never the payload
        payload["stage_budgets"] = {}
        payload["stage_budget_p95"] = None
    if include_trace:
        payload["merged_trace"] = merge_traces(
            scrapes, corrections=corrections
        )
    return payload


def fleet_peer_targets(env_value: str | None) -> list[str]:
    """Parse CMT_TPU_FLEET_PEERS (comma-separated metrics addresses).
    Empty/None means this node aggregates only itself."""
    if not env_value:
        return []
    return [t.strip() for t in env_value.split(",") if t.strip()]


__all__ = [
    "NodeScrape",
    "clock_corrections",
    "complete_heights",
    "fleet_payload",
    "fleet_peer_targets",
    "fleet_rollup",
    "height_latencies_ms",
    "merge_traces",
    "node_identities",
    "parse_prom_text",
    "percentile",
    "scrape_fleet",
    "scrape_node",
    "self_scrape",
    "series",
    "series_value",
    "stitch_heights",
]
