"""Deadlock-detecting locks + thread-leak checking — the framework's
analog of the reference's race/deadlock tooling (SURVEY.md §5:
`go test -race` CI-wide, the `deadlock` build tag swapping
cmtsync.Mutex for go-deadlock, and fortytw2/leaktest).

CPython's GIL rules out Go-style data races on single attributes, but
lock-ordering deadlocks and leaked threads are just as real here.  Two
tools, both zero-cost when disabled:

- ``Mutex()`` / ``RMutex()``: factory returning a plain
  threading.Lock/RLock normally; with ``CMT_TPU_DEADLOCK=1`` (the
  build-tag analog — tests.mk:61 in the reference) every acquire gets
  a watchdog timeout (CMT_TPU_DEADLOCK_TIMEOUT seconds, default 30):
  on expiry it dumps every thread's stack and raises
  PotentialDeadlock instead of hanging the node forever.  Core
  components (consensus, mempool, switch, evidence, stores) create
  their locks through this seam.
- ``assert_no_thread_leaks()``: leaktest-style context manager for
  tests — snapshots live threads on entry and fails if new non-daemon
  threads survive exit (after a grace period for teardown races).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

_ENABLED = bool(os.environ.get("CMT_TPU_DEADLOCK"))
_TIMEOUT = float(os.environ.get("CMT_TPU_DEADLOCK_TIMEOUT", "30"))


class PotentialDeadlock(Exception):
    """An acquire exceeded the deadlock watchdog timeout."""


def _dump_all_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(
            line.rstrip() for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


class _WatchdogLock:
    """Lock wrapper that refuses to block forever (go-deadlock's
    DeadlockTimeout behavior)."""

    __slots__ = ("_lock", "_timeout", "_owner_stack")

    def __init__(self, inner, timeout: float):
        self._lock = inner
        self._timeout = timeout
        self._owner_stack = ""

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking:
            ok = self._lock.acquire(False)
            if ok:
                self._remember()
            return ok
        limit = self._timeout if timeout in (-1, None) else min(
            timeout, self._timeout
        )
        ok = self._lock.acquire(True, limit)
        if not ok and timeout not in (-1, None) and timeout <= self._timeout:
            # the CALLER's finite timeout was the binding constraint —
            # timed-acquire semantics must be preserved in debug mode:
            # return False, don't diagnose a deadlock that isn't one
            return False
        if not ok:
            dump = _dump_all_stacks()
            sys.stderr.write(
                f"POTENTIAL DEADLOCK: lock held for > {limit}s\n"
                f"last acquirer:\n{self._owner_stack}\n"
                f"all threads:\n{dump}\n"
            )
            raise PotentialDeadlock(
                f"could not acquire lock within {limit}s "
                f"(last acquired at:\n{self._owner_stack})"
            )
        self._remember()
        return True

    def _remember(self) -> None:
        self._owner_stack = "".join(traceback.format_stack(limit=6)[:-1])

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._lock, "locked", None)
        if fn is not None:  # Lock always; RLock only on Python >= 3.14
            return fn()
        if self._lock._is_owned():
            return True
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __getattr__(self, name: str):
        # threading.Condition probes the lock for _is_owned /
        # _release_save / _acquire_restore and falls back to generic
        # (non-reentrant-safe) versions on AttributeError.  Forward
        # them when the inner lock provides them (RLock) so
        # Condition(RMutex()) keeps correct ownership semantics —
        # the generic fallback's acquire(False) probe succeeds
        # REENTRANTLY on an owned RLock and concludes it is unheld.
        if name in ("_is_owned", "_release_save", "_acquire_restore"):
            return getattr(self._lock, name)
        raise AttributeError(name)


def Mutex():
    """threading.Lock, or the watchdog wrapper under CMT_TPU_DEADLOCK."""
    lock = threading.Lock()
    return _WatchdogLock(lock, _TIMEOUT) if _ENABLED else lock


def RMutex():
    """threading.RLock, or the watchdog wrapper under CMT_TPU_DEADLOCK."""
    lock = threading.RLock()
    return _WatchdogLock(lock, _TIMEOUT) if _ENABLED else lock


class assert_no_thread_leaks:
    """(leaktest analog) fail if the body leaks non-daemon threads.

    with assert_no_thread_leaks(grace=2.0):
        svc = SomeService(); svc.start(); svc.stop()
    """

    def __init__(self, grace: float = 2.0):
        self.grace = grace

    def __enter__(self):
        self._before = set(threading.enumerate())
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        deadline = time.monotonic() + self.grace
        while True:
            leaked = [
                t
                for t in threading.enumerate()
                if t not in self._before
                and t.is_alive()
                and not t.daemon
            ]
            if not leaked:
                return False
            if time.monotonic() > deadline:
                raise AssertionError(
                    "leaked non-daemon threads: "
                    + ", ".join(t.name for t in leaked)
                )
            time.sleep(0.05)


__all__ = [
    "Mutex",
    "PotentialDeadlock",
    "RMutex",
    "assert_no_thread_leaks",
]
