"""Concurrency-correctness seam — the framework's analog of the
reference's race/deadlock tooling (SURVEY.md §5: `go test -race`
CI-wide, the `deadlock` build tag swapping cmtsync.Mutex for
go-deadlock, and fortytw2/leaktest).

CPython's GIL rules out Go-style torn writes on single attributes, but
lock-ordering deadlocks, lost updates, and invariant races across
threads are just as real here.  Four tools, all zero-cost when
disabled (the factories return plain threading locks):

- ``Mutex()`` / ``RMutex()``: with ``CMT_TPU_DEADLOCK=1`` (the
  build-tag analog — tests.mk:61 in the reference) every acquire gets
  a watchdog timeout (CMT_TPU_DEADLOCK_TIMEOUT seconds, default 30):
  on expiry it dumps every thread's stack and raises
  PotentialDeadlock instead of hanging the node forever.  ALL core
  components construct their locks through this seam (enforced by
  ``tools/lockcheck.py``).
- ``CMT_TPU_LOCKGRAPH=1`` (go-deadlock's lock-order detection): every
  acquire records the thread's held-lock set into a global
  acquisition-order graph.  A cycle — lock B acquired under A
  somewhere, A acquired under B somewhere else — is reported
  immediately with BOTH acquisition stacks and raised as
  LockOrderError, even if the interleaving that would actually
  deadlock never fires in this run.
- ``CMT_TPU_RACE=1`` (a GIL-aware TSan-lite): classes decorated with
  ``@guarded`` declare a ``_GUARDED_BY = {"field": "_mtx"}`` registry;
  every access to a registered field records (thread, guard-held).
  An UNGUARDED WRITE observed cross-thread raises RaceError with both
  access stacks.  Unguarded reads are the static lint's domain
  (``# unguarded: <reason>`` waivers in tools/lockcheck.py) — under
  the GIL they can't tear, and flagging them at runtime would
  contradict the waivers the lint audits.
- ``assert_no_thread_leaks()``: leaktest-style context manager for
  tests — snapshots live threads on entry and fails if new non-daemon
  threads survive exit (after a grace period for teardown races).
"""

from __future__ import annotations

import itertools
import os
import sys
import threading
import time
import traceback
import weakref

from cometbft_tpu.utils.env import flag_from_env, float_from_env

_ENABLED = flag_from_env("CMT_TPU_DEADLOCK")
_TIMEOUT = float_from_env("CMT_TPU_DEADLOCK_TIMEOUT", 30.0, minimum=0.001)
_LOCKGRAPH = flag_from_env("CMT_TPU_LOCKGRAPH")
_RACE = flag_from_env("CMT_TPU_RACE")


class PotentialDeadlock(Exception):
    """An acquire exceeded the deadlock watchdog timeout."""


class LockOrderError(Exception):
    """Two locks are acquired in both orders somewhere in the program —
    a potential ABBA deadlock, even if this run never interleaved into
    the actual hang (go-deadlock's lock-order report)."""


class RaceError(Exception):
    """A guarded field was written without its guard while another
    thread also touches it — a lost-update/invariant race the GIL does
    not prevent."""


def _dump_all_stacks() -> str:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} ({tid}) ---")
        out.extend(
            line.rstrip() for line in traceback.format_stack(frame)
        )
    return "\n".join(out)


# -- per-thread held-lock tracking (lockgraph + race modes) -------------

_tls = threading.local()


def _held_locks() -> list:
    lst = getattr(_tls, "held", None)
    if lst is None:
        lst = []
        _tls.held = lst
    return lst


def _held_remove(lock) -> None:
    held = _held_locks()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return


# -- acquisition-order graph (CMT_TPU_LOCKGRAPH) ------------------------
#
# Nodes are lock identities (a monotonic token, immune to id() reuse);
# a directed edge a->b means "b was acquired while a was held", stamped
# with the stack that first created it.  A new edge whose reverse path
# already exists is a potential ABBA deadlock.

_graph_mtx = threading.Lock()  # guards the dicts below; deliberately
# a RAW lock — the graph must never instrument itself
_order_adj: dict[int, set[int]] = {}
_order_edge_stacks: dict[tuple[int, int], str] = {}
_lock_names: dict[int, str] = {}
_lock_refs: dict[int, "weakref.ref"] = {}  # gid -> wrapper (liveness)
_gid_counter = itertools.count(1)
_MAX_EDGES = 20_000  # sweep threshold: per-height locks (VoteSet et
# al. mint a fresh Mutex every height) would otherwise grow the graph
# without bound on soak runs; dead locks' edges are garbage-collected
# at the threshold so detection stays LIVE instead of going blind
_graph_saturated = False


def _sweep_dead_locks() -> None:
    """Drop nodes/edges whose lock has been garbage-collected (holds
    _graph_mtx)."""
    dead = {g for g, ref in _lock_refs.items() if ref() is None}
    if not dead:
        return
    for g in dead:
        _lock_refs.pop(g, None)
        _lock_names.pop(g, None)
        _order_adj.pop(g, None)
    for g, nxt in _order_adj.items():
        nxt -= dead
    for key in [
        k for k in _order_edge_stacks if k[0] in dead or k[1] in dead
    ]:
        del _order_edge_stacks[key]


def _find_path(src: int, dst: int) -> list[int] | None:
    """DFS over the order graph; returns the node path src..dst."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _order_adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _lock_name(gid: int) -> str:
    return _lock_names.get(gid, f"lock#{gid}")


def _note_order(lock, held: list) -> None:
    """Record held->lock edges; raise on a cycle.  Called BEFORE the
    actual acquire so a potential deadlock is caught even when this
    run's interleaving would have sailed through."""
    b = lock._gid
    stack_now = None  # captured only when a NEW edge appears (hot path
    # re-walks known edges on every acquire; stacks are debug payload)
    with _graph_mtx:
        for h in held:
            a = h._gid
            if a == b or (a, b) in _order_edge_stacks:
                continue
            if stack_now is None:
                stack_now = "".join(traceback.format_stack(limit=16)[:-2])
            path = _find_path(b, a)
            if path is not None:
                first_edge = (path[0], path[1])
                prior = _order_edge_stacks.get(first_edge, "<unknown>")
                chain = " -> ".join(_lock_name(g) for g in path + [b])
                msg = (
                    "POTENTIAL LOCK-ORDER CYCLE: acquiring "
                    f"{_lock_name(b)} while holding {_lock_name(a)}, "
                    f"but the reverse order {chain} is already "
                    "established\n"
                    f"--- this acquisition ({threading.current_thread().name}):\n"
                    f"{stack_now}"
                    f"--- prior acquisition of {_lock_name(path[1])} "
                    f"under {_lock_name(path[0])}:\n{prior}"
                )
                # the flight recorder tail rides along: what the node
                # was DOING when the cycle appeared (utils/flight.py)
                from cometbft_tpu.utils.flight import flight_tail

                msg += flight_tail()
                sys.stderr.write(msg + "\n")
                raise LockOrderError(msg)
            if len(_order_edge_stacks) >= _MAX_EDGES:
                _sweep_dead_locks()
            if len(_order_edge_stacks) < _MAX_EDGES:
                _order_edge_stacks[(a, b)] = stack_now
                _order_adj.setdefault(a, set()).add(b)
            else:
                global _graph_saturated
                if not _graph_saturated:  # warn ONCE, don't go blind silently
                    _graph_saturated = True
                    sys.stderr.write(
                        "cmtsync: lock-order graph saturated "
                        f"({_MAX_EDGES} edges, all locks live) — new "
                        "order edges are no longer recorded\n"
                    )


def lock_order_edges() -> list[tuple[str, str]]:
    """The recorded acquisition-order edges as (held, acquired) name
    pairs — the documented lock inventory in docs/concurrency.md is
    generated from a run with CMT_TPU_LOCKGRAPH=1."""
    with _graph_mtx:
        return sorted(
            (_lock_name(a), _lock_name(b)) for a, b in _order_edge_stacks
        )


def _reset_lock_graph() -> None:
    """Test helper: drop all recorded edges."""
    global _graph_saturated
    with _graph_mtx:
        _order_adj.clear()
        _order_edge_stacks.clear()
        _lock_names.clear()
        _lock_refs.clear()
        _graph_saturated = False


# -- race detection (CMT_TPU_RACE) --------------------------------------
#
# Keyed by (id(obj), field) -> (objref, {thread_id: access_record});
# one record per thread, so a same-thread access can never mask an
# earlier cross-thread one (``x += 1`` reads before it writes — a
# single-slot record would overwrite the other thread's entry and the
# write would then only be compared against our own read).  The
# weakref invalidates stale entries when id() is reused.  A record
# whose thread has exited is dropped at compare time — a dead thread's
# access happened-before ours (the start/join handoff pattern), and
# thread idents get reused.  Record layout:
# (guard_held, is_write, thread_obj, stack)

_race_mtx = threading.Lock()  # raw on purpose, like _graph_mtx
_race_state: dict[tuple[int, str], tuple] = {}
_MAX_RACE_ENTRIES = 65_536
_MAX_THREADS_PER_FIELD = 16


def _race_note(obj, field: str, lockname: str, is_write: bool) -> None:
    try:
        lock = object.__getattribute__(obj, lockname)
    except AttributeError:
        return  # guard not constructed yet
    if not isinstance(lock, _WatchdogLock):
        return  # plain lock: ownership unknowable, nothing to judge
    held = any(h is lock for h in _held_locks())
    tid = threading.get_ident()
    stack = "".join(traceback.format_stack(limit=12)[:-2])
    me = threading.current_thread()
    tname = me.name
    key = (id(obj), field)
    with _race_mtx:
        entry = _race_state.get(key)
        if entry is not None and entry[0] is not None and entry[0]() is not obj:
            entry = None  # id() reuse: records belong to a dead object
        if entry is None:
            try:
                ref = weakref.ref(obj)
            except TypeError:
                ref = None
            if len(_race_state) >= _MAX_RACE_ENTRIES:
                _race_state.clear()
            entry = (ref, {})
            _race_state[key] = entry
        records = entry[1]
        for other_tid, rec in list(records.items()):
            if other_tid == tid:
                continue
            o_held, o_write, o_thread, o_stack = rec
            if not o_thread.is_alive():
                # exited thread: its access happened-before this one
                # (and its ident may be reused) — retire the record
                del records[other_tid]
                continue
            o_name = o_thread.name
            if (is_write and not held) or (o_write and not o_held):
                kind_now = "write" if is_write else "read"
                kind_prev = "write" if o_write else "read"
                msg = (
                    f"RACE on {type(obj).__name__}.{field} (guarded by "
                    f"{lockname}): {kind_now} "
                    f"{'WITHOUT' if not held else 'with'} the guard on "
                    f"thread {tname}, racing a {kind_prev} "
                    f"{'WITHOUT' if not o_held else 'with'} the guard on "
                    f"thread {o_name}\n"
                    f"--- this access ({tname}):\n{stack}"
                    f"--- previous access ({o_name}):\n{o_stack}"
                )
                from cometbft_tpu.utils.flight import flight_tail

                msg += flight_tail()
                sys.stderr.write(msg + "\n")
                raise RaceError(msg)
        if len(records) >= _MAX_THREADS_PER_FIELD:
            records.clear()
        records[tid] = (held, is_write, me, stack)


def _reset_race_state() -> None:
    """Test helper: forget all recorded accesses."""
    with _race_mtx:
        _race_state.clear()


def guarded(cls):
    """Class decorator activating runtime guarded-by checking under
    CMT_TPU_RACE=1.  Reads the class's ``_GUARDED_BY`` registry
    ({field: lock_attr}, merged over the MRO) — the same registry
    tools/lockcheck.py verifies statically — and intercepts attribute
    access so an unguarded cross-thread write raises RaceError with
    both stacks.  A no-op (returns ``cls`` unchanged) when race mode
    is off, so production classes carry zero overhead."""
    if not _RACE:
        return cls
    gb: dict[str, str] = {}
    for klass in reversed(cls.__mro__):
        gb.update(getattr(klass, "_GUARDED_BY", None) or {})
    if not gb:
        return cls

    orig_init = cls.__init__
    orig_setattr = cls.__setattr__
    orig_getattribute = cls.__getattribute__

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        # accesses during construction are single-threaded by
        # definition; arm the checker only once the object can escape
        object.__setattr__(self, "_cmt_race_live", True)

    def __setattr__(self, name, value):
        if name in gb and object.__getattribute__(self, "__dict__").get(
            "_cmt_race_live"
        ):
            _race_note(self, name, gb[name], True)
        orig_setattr(self, name, value)

    def __getattribute__(self, name):
        if name in gb and object.__getattribute__(self, "__dict__").get(
            "_cmt_race_live"
        ):
            _race_note(self, name, gb[name], False)
        return orig_getattribute(self, name)

    cls.__init__ = __init__
    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__
    return cls


# -- the instrumented lock wrapper --------------------------------------


class _WatchdogLock:
    """Lock wrapper carrying the debug instrumentation: watchdog
    timeout (go-deadlock's DeadlockTimeout behavior) when constructed
    with one, plus held-set/order-graph bookkeeping whenever lockgraph
    or race mode is on."""

    __slots__ = (
        "_lock", "_timeout", "_owner_stack", "_gid", "name", "__weakref__",
    )

    def __init__(self, inner, timeout: float | None = None, name: str = ""):
        self._lock = inner
        self._timeout = timeout
        self._owner_stack = ""
        self._gid = next(_gid_counter)
        self.name = name or f"lock#{self._gid}"
        if _LOCKGRAPH:
            with _graph_mtx:
                _lock_names[self._gid] = self.name
                _lock_refs[self._gid] = weakref.ref(self)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        track = _LOCKGRAPH or _RACE
        held = _held_locks() if track else None
        reentrant = track and any(h is self for h in held)
        if _LOCKGRAPH and blocking and not reentrant:
            # order edges are recorded (and cycles raised) BEFORE
            # blocking, so a potential deadlock is caught even when
            # this run's interleaving would not actually hang
            _note_order(self, held)
        ok = self._acquire_inner(blocking, timeout)
        if ok and track:
            held.append(self)
        return ok

    def _acquire_inner(self, blocking: bool, timeout: float):
        if not blocking:
            ok = self._lock.acquire(False)
            if ok and self._timeout is not None:
                self._remember()
            return ok
        if self._timeout is None:  # no watchdog: plain blocking acquire
            return self._lock.acquire(
                True, timeout if timeout not in (-1, None) else -1
            )
        limit = self._timeout if timeout in (-1, None) else min(
            timeout, self._timeout
        )
        ok = self._lock.acquire(True, limit)
        if not ok and timeout not in (-1, None) and timeout <= self._timeout:
            # the CALLER's finite timeout was the binding constraint —
            # timed-acquire semantics must be preserved in debug mode:
            # return False, don't diagnose a deadlock that isn't one
            return False
        if not ok:
            dump = _dump_all_stacks()
            sys.stderr.write(
                f"POTENTIAL DEADLOCK: lock held for > {limit}s\n"
                f"last acquirer:\n{self._owner_stack}\n"
                f"all threads:\n{dump}\n"
            )
            raise PotentialDeadlock(
                f"could not acquire lock within {limit}s "
                f"(last acquired at:\n{self._owner_stack})"
            )
        self._remember()
        return True

    def _remember(self) -> None:
        self._owner_stack = "".join(traceback.format_stack(limit=6)[:-1])

    def release(self) -> None:
        if _LOCKGRAPH or _RACE:
            _held_remove(self)
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._lock, "locked", None)
        if fn is not None:  # Lock always; RLock only on Python >= 3.14
            return fn()
        if self._lock._is_owned():
            return True
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    # threading.Condition probes the lock for _is_owned /
    # _release_save / _acquire_restore and falls back to generic
    # (non-reentrant-safe) versions on AttributeError.  Forward
    # them when the inner lock provides them (RLock) so
    # Condition(RMutex()) keeps correct ownership semantics —
    # the generic fallback's acquire(False) probe succeeds
    # REENTRANTLY on an owned RLock and concludes it is unheld.
    # Implemented as real methods (not bare forwarding) so cond.wait's
    # release/reacquire keeps the held-set bookkeeping consistent.

    def _is_owned(self):
        fn = getattr(self._lock, "_is_owned", None)
        if fn is not None:
            return fn()
        # plain-Lock probe, same semantics as Condition's own fallback
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _release_save(self):
        fn = getattr(self._lock, "_release_save", None)
        if fn is not None:
            depth = 0
            if _LOCKGRAPH or _RACE:
                # RLock._release_save drops EVERY recursion level; the
                # held-set must drop (and later restore) the same count
                # or a guarded write after cond.wait inside a nested
                # `with` would be misjudged as unguarded
                held = _held_locks()
                depth = sum(1 for h in held if h is self)
                held[:] = [h for h in held if h is not self]
            return ("cmtsync-rlock", depth, fn())
        if _LOCKGRAPH or _RACE:
            _held_remove(self)
        self._lock.release()
        return None

    def _acquire_restore(self, state):
        fn = getattr(self._lock, "_acquire_restore", None)
        if fn is not None:
            tag, depth, inner_state = state
            assert tag == "cmtsync-rlock"
            fn(inner_state)
            if _LOCKGRAPH or _RACE:
                _held_locks().extend([self] * max(depth, 1))
        else:
            # plain-Lock path: a full wrapper acquire, so the watchdog
            # still bounds a cond.wait reacquire and the held-set/order
            # bookkeeping happens in one place
            self.acquire()


def _creation_site() -> str:
    f = sys._getframe(2)
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def Mutex():
    """threading.Lock, or the instrumented wrapper when any of the
    debug modes (CMT_TPU_DEADLOCK / CMT_TPU_LOCKGRAPH / CMT_TPU_RACE)
    is on."""
    lock = threading.Lock()
    if _ENABLED or _LOCKGRAPH or _RACE:
        return _WatchdogLock(
            lock,
            _TIMEOUT if _ENABLED else None,
            name=_creation_site() if _LOCKGRAPH else "",
        )
    return lock


def RMutex():
    """threading.RLock, or the instrumented wrapper when any of the
    debug modes is on."""
    lock = threading.RLock()
    if _ENABLED or _LOCKGRAPH or _RACE:
        return _WatchdogLock(
            lock,
            _TIMEOUT if _ENABLED else None,
            name=_creation_site() if _LOCKGRAPH else "",
        )
    return lock


class assert_no_thread_leaks:
    """(leaktest analog) fail if the body leaks non-daemon threads.

    with assert_no_thread_leaks(grace=2.0):
        svc = SomeService(); svc.start(); svc.stop()

    ``daemons_too=True`` counts daemon threads as leaks as well — the
    wire plane (MConnection send/recv/ping, switch accept) runs
    entirely on daemon threads, which the default mode would wave
    through; its loopback suites gate with this flag.
    """

    def __init__(self, grace: float = 2.0, daemons_too: bool = False):
        self.grace = grace
        self.daemons_too = daemons_too

    def __enter__(self):
        self._before = set(threading.enumerate())
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        deadline = time.monotonic() + self.grace
        while True:
            leaked = [
                t
                for t in threading.enumerate()
                if t not in self._before
                and t.is_alive()
                and (self.daemons_too or not t.daemon)
            ]
            if not leaked:
                return False
            if time.monotonic() > deadline:
                raise AssertionError(
                    "leaked non-daemon threads: "
                    + ", ".join(t.name for t in leaked)
                )
            time.sleep(0.05)


__all__ = [
    "LockOrderError",
    "Mutex",
    "PotentialDeadlock",
    "RMutex",
    "RaceError",
    "assert_no_thread_leaks",
    "guarded",
    "lock_order_edges",
]
