"""Structured leveled logging (reference: libs/log — logfmt TMLogger).

Keeps the reference's shape: ``logger.info(msg, **kv)``, ``with_fields`` to
bind module context, per-module level filtering, and logfmt or JSON output.
"""

from __future__ import annotations

import io
import json
import sys
import threading
import time
from typing import Any, TextIO

LEVELS = {"debug": 0, "info": 1, "error": 2, "none": 3}


def _logfmt_value(v: Any) -> str:
    if isinstance(v, bytes):
        v = v.hex().upper()
    s = str(v)
    if not s or any(c in ' "=' for c in s) or any(ord(c) < 0x20 for c in s):
        return json.dumps(s)
    return s


class Logger:
    """A leveled, key-value logger bound to a set of context fields."""

    def __init__(
        self,
        sink: TextIO | None = None,
        level: str = "info",
        fmt: str = "logfmt",
        fields: dict[str, Any] | None = None,
        module_levels: dict[str, str] | None = None,
        lock: threading.Lock | None = None,
    ):
        self._sink = sink if sink is not None else sys.stderr
        self._level_name = level
        self._level = LEVELS[level]
        self._fmt = fmt
        self._fields = dict(fields or {})
        self._module_levels = module_levels or {}
        self._lock = lock or threading.Lock()

    def with_fields(self, **fields: Any) -> "Logger":
        merged = dict(self._fields)
        merged.update(fields)
        return Logger(
            sink=self._sink,
            level=self._level_name,
            fmt=self._fmt,
            fields=merged,
            module_levels=self._module_levels,
            lock=self._lock,
        )

    def _enabled(self, level: int) -> bool:
        mod = self._fields.get("module")
        if mod is not None and mod in self._module_levels:
            return level >= LEVELS[self._module_levels[mod]]
        return level >= self._level

    def _emit(self, level_name: str, msg: str, kv: dict[str, Any]) -> None:
        record: dict[str, Any] = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "level": level_name,
            **self._fields,
            "msg": msg,
            **kv,
        }
        if self._fmt == "json":
            line = json.dumps(record, default=str)
        else:
            buf = io.StringIO()
            for k, v in record.items():
                buf.write(f"{k}={_logfmt_value(v)} ")
            line = buf.getvalue().rstrip()
        with self._lock:
            self._sink.write(line + "\n")

    def debug(self, msg: str, **kv: Any) -> None:
        if self._enabled(0):
            self._emit("debug", msg, kv)

    def info(self, msg: str, **kv: Any) -> None:
        if self._enabled(1):
            self._emit("info", msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        if self._enabled(2):
            self._emit("error", msg, kv)


class NopLogger(Logger):
    def __init__(self) -> None:
        super().__init__(sink=io.StringIO(), level="none")

    def _enabled(self, level: int) -> bool:  # noqa: ARG002
        return False


_default: Logger | None = None
_default_mtx = threading.Lock()


def default_logger() -> Logger:
    global _default
    with _default_mtx:
        if _default is None:
            _default = Logger()
        return _default


def set_default_logger(logger: Logger) -> None:
    global _default
    with _default_mtx:
        _default = logger


def parse_log_level(spec: str, default: str = "info") -> tuple[str, dict[str, str]]:
    """Parse ``"p2p:debug,consensus:info,*:error"`` style level specs
    (reference: libs/log/filter.go semantics via config ``log_level``)."""
    base = default
    per_module: dict[str, str] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if ":" in item:
            mod, lvl = item.split(":", 1)
            if lvl not in LEVELS:
                raise ValueError(f"unknown log level {lvl!r}")
            if mod == "*":
                base = lvl
            else:
                per_module[mod] = lvl
        else:
            if item not in LEVELS:
                raise ValueError(f"unknown log level {item!r}")
            base = item
    return base, per_module
