"""Thread-safe bit vector (reference: internal/bits/bit_array.go:17).

Used for vote/part presence gossip: each peer advertises which votes or
block parts it has, and the gossip routines pick what to send from the
set difference.
"""

from __future__ import annotations

import random
import threading


class BitArray:
    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative bit count")
        self._bits = bits
        self._elems = bytearray((bits + 7) // 8)
        self._mtx = threading.Lock()

    @property
    def size(self) -> int:
        return self._bits

    def get_index(self, i: int) -> bool:
        with self._mtx:
            if i < 0 or i >= self._bits:
                return False
            return bool(self._elems[i // 8] & (1 << (i % 8)))

    def set_index(self, i: int, v: bool) -> bool:
        with self._mtx:
            if i < 0 or i >= self._bits:
                return False
            if v:
                self._elems[i // 8] |= 1 << (i % 8)
            else:
                self._elems[i // 8] &= ~(1 << (i % 8))
            return True

    def copy(self) -> "BitArray":
        out = BitArray(self._bits)
        with self._mtx:
            out._elems = bytearray(self._elems)
        return out

    def _masked(self) -> bytearray:
        """Internal elems with trailing bits beyond size zeroed."""
        elems = bytearray(self._elems)
        extra = len(elems) * 8 - self._bits
        if extra and elems:
            elems[-1] &= 0xFF >> extra
        return elems

    def or_(self, other: "BitArray") -> "BitArray":
        """Union; result size is max(sizes) (bit_array.go Or)."""
        out = BitArray(max(self._bits, other._bits))
        with self._mtx:
            a = self._masked()
        with other._mtx:
            b = other._masked()
        for i in range(len(out._elems)):
            av = a[i] if i < len(a) else 0
            bv = b[i] if i < len(b) else 0
            out._elems[i] = av | bv
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self._bits, other._bits))
        with self._mtx:
            a = self._masked()
        with other._mtx:
            b = other._masked()
        for i in range(len(out._elems)):
            out._elems[i] = a[i] & b[i]
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self._bits)
        with self._mtx:
            for i in range(len(self._elems)):
                out._elems[i] = ~self._elems[i] & 0xFF
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (bit_array.go Sub)."""
        out = BitArray(self._bits)
        with self._mtx:
            a = self._masked()
        with other._mtx:
            b = other._masked()
        for i in range(len(out._elems)):
            bv = b[i] if i < len(b) else 0
            out._elems[i] = a[i] & ~bv & 0xFF
        return out

    def is_empty(self) -> bool:
        with self._mtx:
            return not any(self._masked())

    def is_full(self) -> bool:
        with self._mtx:
            elems = self._masked()
        for i in range(self._bits):
            if not (elems[i // 8] & (1 << (i % 8))):
                return False
        return True

    def true_indices(self) -> list[int]:
        with self._mtx:
            elems = self._masked()
        return [
            i for i in range(self._bits) if elems[i // 8] & (1 << (i % 8))
        ]

    def pick_random(self, rng: random.Random | None = None) -> tuple[int, bool]:
        """A uniformly random set bit, or (0, False) when empty
        (bit_array.go PickRandom — used by pickVoteToSend)."""
        trues = self.true_indices()
        if not trues:
            return 0, False
        r = rng or random
        return r.choice(trues), True

    def to_bytes(self) -> bytes:
        with self._mtx:
            return bytes(self._masked())

    @classmethod
    def from_bytes(cls, bits: int, data: bytes) -> "BitArray":
        out = cls(bits)
        n = min(len(out._elems), len(data))
        out._elems[:n] = data[:n]
        out._elems = bytearray(out._masked())
        return out

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, BitArray):
            return NotImplemented
        return self._bits == other._bits and self.to_bytes() == other.to_bytes()

    def __repr__(self) -> str:
        bits = "".join(
            "x" if self.get_index(i) else "_" for i in range(min(self._bits, 64))
        )
        return f"BA{{{self._bits}:{bits}}}"
