"""Embedded key-value storage (reference analog: cometbft-db).

The reference sits every store (blocks, state, indexes, evidence, light)
on a small ordered-KV interface with pluggable backends (goleveldb
default, rocksdb/pebble optional).  We keep the same seam: an ordered
``DB`` interface with an in-memory backend for tests and a persistent
SQLite backend (stdlib, crash-safe WAL journaling) for nodes.  Storage
is host-side and never on the device path (SURVEY.md §2.9).
"""

from __future__ import annotations

import abc
import bisect
import sqlite3
import threading
from typing import Iterator
from cometbft_tpu.utils import sync as cmtsync


class DBError(Exception):
    pass


class DB(abc.ABC):
    """Ordered byte-keyed store (cometbft-db types.go DB interface)."""

    @abc.abstractmethod
    def get(self, key: bytes) -> bytes | None: ...

    @abc.abstractmethod
    def set(self, key: bytes, value: bytes) -> None: ...

    @abc.abstractmethod
    def delete(self, key: bytes) -> None: ...

    @abc.abstractmethod
    def iterator(
        self, start: bytes | None = None, end: bytes | None = None
    ) -> Iterator[tuple[bytes, bytes]]:
        """Ascending iteration over [start, end)."""

    @abc.abstractmethod
    def reverse_iterator(
        self, start: bytes | None = None, end: bytes | None = None
    ) -> Iterator[tuple[bytes, bytes]]:
        """Descending iteration over [start, end)."""

    @abc.abstractmethod
    def write_batch(self, ops: list[tuple[bytes, bytes | None]]) -> None:
        """Atomically apply (key, value) sets and (key, None) deletes."""

    @abc.abstractmethod
    def close(self) -> None: ...

    def compact(self) -> None:
        """Reclaim space (cometbft-db Compact; `compact-db` command).
        Default: nothing to do."""

    def has(self, key: bytes) -> bool:
        return self.get(key) is not None

    def prefix_iterator(self, prefix: bytes) -> Iterator[tuple[bytes, bytes]]:
        return self.iterator(prefix, prefix_end(prefix))


def prefix_end(prefix: bytes) -> bytes | None:
    """Smallest key greater than every key with this prefix."""
    if not prefix:
        return None
    buf = bytearray(prefix)
    for i in reversed(range(len(buf))):
        if buf[i] != 0xFF:
            buf[i] += 1
            return bytes(buf[: i + 1])
    return None  # prefix is all 0xFF: no upper bound


class MemDB(DB):
    """Sorted in-memory backend (cometbft-db memdb)."""

    def __init__(self):
        self._mtx = cmtsync.RMutex()
        self._keys: list[bytes] = []
        self._data: dict[bytes, bytes] = {}

    def get(self, key: bytes) -> bytes | None:
        with self._mtx:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        if not isinstance(value, (bytes, bytearray)):
            raise DBError("value must be bytes")
        with self._mtx:
            if key not in self._data:
                bisect.insort(self._keys, key)
            self._data[key] = bytes(value)

    def delete(self, key: bytes) -> None:
        with self._mtx:
            if key in self._data:
                del self._data[key]
                i = bisect.bisect_left(self._keys, key)
                del self._keys[i]

    def _range(self, start: bytes | None, end: bytes | None) -> list[bytes]:
        lo = bisect.bisect_left(self._keys, start) if start else 0
        hi = bisect.bisect_left(self._keys, end) if end else len(self._keys)
        return self._keys[lo:hi]

    def iterator(self, start=None, end=None):
        with self._mtx:
            keys = self._range(start, end)
        for k in keys:
            v = self.get(k)
            if v is not None:
                yield k, v

    def reverse_iterator(self, start=None, end=None):
        with self._mtx:
            keys = self._range(start, end)
        for k in reversed(keys):
            v = self.get(k)
            if v is not None:
                yield k, v

    def write_batch(self, ops):
        with self._mtx:
            for key, value in ops:
                if value is None:
                    self.delete(key)
                else:
                    self.set(key, value)

    def close(self) -> None:
        pass


class SQLiteDB(DB):
    """Persistent backend on stdlib sqlite3 with WAL journaling.

    Plays goleveldb's role in the reference (default `db_backend`,
    docs/references/config/config.toml.md:117): an ordered, crash-safe
    embedded store with atomic batches.  BLOB keys preserve bytewise
    order, so range iteration matches MemDB exactly.
    """

    def __init__(self, path: str):
        self._path = path
        self._local = threading.local()
        self._conns: list[sqlite3.Connection] = []
        self._conns_mtx = cmtsync.Mutex()
        conn = self._conn()
        with conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS kv"
                " (k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID"
            )

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False so close() can tear down every
            # connection regardless of which thread created it; each
            # thread still uses its own connection for isolation.
            conn = sqlite3.connect(
                self._path, timeout=30.0, check_same_thread=False
            )
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
            with self._conns_mtx:
                self._conns.append(conn)
        return conn

    def get(self, key: bytes) -> bytes | None:
        row = self._conn().execute(
            "SELECT v FROM kv WHERE k = ?", (key,)
        ).fetchone()
        return bytes(row[0]) if row else None

    def set(self, key: bytes, value: bytes) -> None:
        conn = self._conn()
        with conn:
            conn.execute(
                "INSERT INTO kv (k, v) VALUES (?, ?)"
                " ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                (key, bytes(value)),
            )

    def delete(self, key: bytes) -> None:
        conn = self._conn()
        with conn:
            conn.execute("DELETE FROM kv WHERE k = ?", (key,))

    def _iter(self, start, end, desc: bool):
        clauses, params = [], []
        if start is not None:
            clauses.append("k >= ?")
            params.append(start)
        if end is not None:
            clauses.append("k < ?")
            params.append(end)
        where = ("WHERE " + " AND ".join(clauses)) if clauses else ""
        order = "DESC" if desc else "ASC"
        cur = self._conn().execute(
            f"SELECT k, v FROM kv {where} ORDER BY k {order}", params
        )
        for k, v in cur:
            yield bytes(k), bytes(v)

    def iterator(self, start=None, end=None):
        return self._iter(start, end, desc=False)

    def reverse_iterator(self, start=None, end=None):
        return self._iter(start, end, desc=True)

    def write_batch(self, ops):
        conn = self._conn()
        with conn:
            for key, value in ops:
                if value is None:
                    conn.execute("DELETE FROM kv WHERE k = ?", (key,))
                else:
                    conn.execute(
                        "INSERT INTO kv (k, v) VALUES (?, ?)"
                        " ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                        (key, bytes(value)),
                    )

    def compact(self) -> None:
        """VACUUM: rebuild the file, reclaiming deleted-row space
        (what goleveldb compaction does for the reference)."""
        conn = self._conn()
        conn.commit()
        conn.execute("VACUUM")

    def close(self) -> None:
        with self._conns_mtx:
            for conn in self._conns:
                try:
                    conn.close()
                except sqlite3.Error:
                    pass
            self._conns.clear()
        self._local = threading.local()


class CometKVDB(DB):
    """Native log-structured engine (native/kv/cometkv.cpp) behind the
    ordered-KV interface — the framework's goleveldb-class backend
    (reference selects goleveldb/rocksdb/badger/pebble via cometbft-db;
    config.toml.md:117-120).  Bitcask design: append-only CRC-framed
    log + in-memory ordered index; write_batch is the durability
    boundary (one fsync), matching how the stores commit blocks."""

    def __init__(self, path: str):
        from cometbft_tpu.utils.kv_native import CometKV

        try:
            self._kv = CometKV(path)
        except RuntimeError as exc:
            raise DBError(str(exc)) from exc

    def get(self, key: bytes) -> bytes | None:
        return self._kv.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._kv.put(key, value)

    def delete(self, key: bytes) -> None:
        self._kv.delete(key)

    def iterator(self, start=None, end=None):
        yield from self._kv.iterate(start, end, reverse=False)

    def reverse_iterator(self, start=None, end=None):
        yield from self._kv.iterate(start, end, reverse=True)

    def write_batch(self, ops):
        self._kv.batch(ops)

    def compact(self) -> None:
        self._kv.compact()

    def close(self) -> None:
        self._kv.close()


def open_db(name: str, backend: str = "memdb", dir_: str = ".") -> DB:
    """Backend dispatch (cometbft-db NewDB)."""
    if backend == "memdb":
        return MemDB()
    if backend == "sqlite":
        import os

        os.makedirs(dir_, exist_ok=True)
        return SQLiteDB(os.path.join(dir_, f"{name}.db"))
    if backend == "cometkv":
        import os

        os.makedirs(dir_, exist_ok=True)
        return CometKVDB(os.path.join(dir_, f"{name}.ckv"))
    raise DBError(f"unknown db backend {backend!r}")
