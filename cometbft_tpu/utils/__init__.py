"""Foundation utilities (reference: libs/ — log, service, sync, bytes, time)."""
