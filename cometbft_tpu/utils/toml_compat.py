"""TOML reader compat: stdlib ``tomllib`` (Python >= 3.11) or the
``tomli`` backport it was vendored from (identical API).  One shim so
the version gate lives in exactly one place:

    from cometbft_tpu.utils.toml_compat import tomllib
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:  # pragma: no cover - Python < 3.11
    import tomli as tomllib  # type: ignore[no-redef]

__all__ = ["tomllib"]
