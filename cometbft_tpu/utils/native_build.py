"""Shared build-on-demand loader for the in-tree C++ components.

One implementation of the pattern crypto/bls_native.py and
utils/kv_native.py previously each carried: compile the single-file
source with g++ when the .so is missing, load via ctypes, degrade
gracefully when the toolchain or library is unavailable.  The temp
output is pid-unique so concurrent builders (parallel test workers on
a clean checkout) cannot replace each other's half-written object.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class NativeLib:
    """Lazily built + loaded shared library handle."""

    def __init__(self, src_rel: str, out_name: str, disable_env: str,
                 configure=None) -> None:
        self.src = os.path.join(REPO, src_rel)
        self.out = os.path.join(REPO, "native", "build", out_name)
        self.disable_env = disable_env
        self._configure = configure  # one-time ctypes signature setup
        self._lock = threading.Lock()
        self._lib: ctypes.CDLL | None = None
        self._tried = False

    def _build(self) -> bool:
        os.makedirs(os.path.dirname(self.out), exist_ok=True)
        tmp = f"{self.out}.tmp.{os.getpid()}"
        try:
            # -O3 + native tuning: these libs are built ON the box they
            # run on (never shipped), and the BLS pairing is pure
            # bigint arithmetic where vectorized/unrolled codegen is
            # measurably faster than -O2
            proc = subprocess.run(
                [
                    "g++", "-O3", "-march=native", "-funroll-loops",
                    "-shared", "-fPIC", "-std=c++17",
                    self.src, "-o", tmp,
                ],
                capture_output=True,
                timeout=300,
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
        if proc.returncode != 0:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False
        os.replace(tmp, self.out)
        return True

    def load(self) -> ctypes.CDLL | None:
        """The ctypes library, or None when unavailable."""
        if self._lib is not None or self._tried:
            return self._lib
        with self._lock:
            if self._lib is not None or self._tried:
                return self._lib
            self._tried = True
            if os.environ.get(self.disable_env):
                return None
            if os.path.exists(self.src):
                # a cached .so older than its source is STALE — loading
                # it would silently serve the previous build (and miss
                # any symbol the source has since grown).  Rebuild; if
                # the rebuild fails and an old .so exists, fall through
                # and load that (callers probe symbols defensively).
                try:
                    stale = os.path.exists(self.out) and (
                        os.path.getmtime(self.src)
                        > os.path.getmtime(self.out)
                    )
                except OSError:
                    stale = False
                if not os.path.exists(self.out) or stale:
                    if not self._build() and not os.path.exists(self.out):
                        return None
            if not os.path.exists(self.out):
                return None
            try:
                lib = ctypes.CDLL(self.out)
            except OSError:
                return None
            if self._configure is not None:
                self._configure(lib)
            self._lib = lib
            return self._lib
