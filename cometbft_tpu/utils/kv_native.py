"""ctypes binding for the native cometkv storage engine.

The reference ships pluggable storage backends (goleveldb default,
rocksdb/badger/pebble selectable); `native/kv/cometkv.cpp` is this
framework's native engine — a Bitcask-style append-only log with an
in-memory ordered index (see the C++ header comment for the format).
Build-on-demand with graceful absence, same pattern as
crypto/bls_native.py; select with db_backend = "cometkv".
"""

from __future__ import annotations

import ctypes

from cometbft_tpu.utils.native_build import NativeLib


def _configure(lib) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ckv_open.restype = ctypes.c_void_p
    lib.ckv_open.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.ckv_get.restype = ctypes.c_int
    lib.ckv_get.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int,
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int),
    ]
    lib.ckv_free.argtypes = [u8p]
    lib.ckv_put.restype = ctypes.c_int
    lib.ckv_put.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int, u8p, ctypes.c_int,
    ]
    lib.ckv_del.restype = ctypes.c_int
    lib.ckv_del.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int]
    lib.ckv_batch.restype = ctypes.c_int
    lib.ckv_batch.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int]
    lib.ckv_iter.restype = ctypes.c_void_p
    lib.ckv_iter.argtypes = [
        ctypes.c_void_p, u8p, ctypes.c_int, u8p, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.ckv_iter_next.restype = ctypes.c_int
    lib.ckv_iter_next.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int),
    ]
    lib.ckv_iter_close.argtypes = [ctypes.c_void_p]
    lib.ckv_compact.restype = ctypes.c_int
    lib.ckv_compact.argtypes = [ctypes.c_void_p]
    lib.ckv_sync.restype = ctypes.c_int
    lib.ckv_sync.argtypes = [ctypes.c_void_p]
    lib.ckv_count.restype = ctypes.c_uint64
    lib.ckv_count.argtypes = [ctypes.c_void_p]
    lib.ckv_dead_bytes.restype = ctypes.c_uint64
    lib.ckv_dead_bytes.argtypes = [ctypes.c_void_p]
    lib.ckv_close.argtypes = [ctypes.c_void_p]


_NATIVE = NativeLib(
    "native/kv/cometkv.cpp", "libcmtkv.so", "CMT_TPU_NO_NATIVE_KV",
    configure=_configure,
)


def load():
    """The ctypes library (signatures configured), or None."""
    return _NATIVE.load()


def available() -> bool:
    return load() is not None


def _u8(b: bytes):
    return ctypes.cast(
        ctypes.create_string_buffer(b, len(b) or 1),
        ctypes.POINTER(ctypes.c_uint8),
    )


class CometKV:
    """Thin handle wrapper; cometbft_tpu.utils.db.CometKVDB adapts it
    to the DB interface.  An op lock serializes native calls against
    close(): an in-flight operation finishes before close() releases
    the handle, and post-close calls raise RuntimeError — never a NULL
    or freed-handle deref (iterators are protected C-side by the
    engine's deferred-free refcount)."""

    def __init__(self, path: str):
        import threading

        lib = load()
        if lib is None:
            raise RuntimeError("native cometkv unavailable")
        self._lib = lib
        self._oplock = threading.Lock()
        err = ctypes.create_string_buffer(256)
        self._h = lib.ckv_open(path.encode(), err, 256)
        if not self._h:
            raise RuntimeError(
                f"cometkv open failed: {err.value.decode()}"
            )

    def _handle(self):
        """The live native handle (call under self._oplock)."""
        h = self._h
        if not h:
            raise RuntimeError("cometkv handle is closed")
        return h

    def get(self, key: bytes) -> bytes | None:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = ctypes.c_int()
        with self._oplock:
            rc = self._lib.ckv_get(
                self._handle(), _u8(key), len(key), ctypes.byref(out),
                ctypes.byref(n),
            )
        if rc < 0:
            raise RuntimeError("cometkv get failed")
        if rc == 0:
            return None
        try:
            return ctypes.string_at(out, n.value)
        finally:
            self._lib.ckv_free(out)

    def put(self, key: bytes, value: bytes) -> None:
        with self._oplock:
            rc = self._lib.ckv_put(
                self._handle(), _u8(key), len(key), _u8(value), len(value)
            )
        if rc != 0:
            raise RuntimeError("cometkv put failed")

    def delete(self, key: bytes) -> None:
        with self._oplock:
            rc = self._lib.ckv_del(self._handle(), _u8(key), len(key))
        if rc != 0:
            raise RuntimeError("cometkv delete failed")

    def batch(self, ops: list[tuple[bytes, bytes | None]]) -> None:
        buf = bytearray()
        for key, value in ops:
            if value is None:
                buf.append(1)
                buf += len(key).to_bytes(4, "little")
                buf += key
            else:
                buf.append(0)
                buf += len(key).to_bytes(4, "little")
                buf += key
                buf += len(value).to_bytes(4, "little")
                buf += value
        with self._oplock:
            rc = self._lib.ckv_batch(
                self._handle(), _u8(bytes(buf)), len(buf)
            )
        if rc != 0:
            raise RuntimeError("cometkv batch failed")

    def iterate(self, start: bytes | None, end: bytes | None,
                reverse: bool = False):
        s = start or b""
        e = end or b""
        with self._oplock:
            it = self._lib.ckv_iter(
                self._handle(), _u8(s), len(s), _u8(e), len(e),
                int(reverse),
            )
        if not it:
            raise RuntimeError("cometkv iterator failed")
        k = ctypes.POINTER(ctypes.c_uint8)()
        v = ctypes.POINTER(ctypes.c_uint8)()
        kl = ctypes.c_int()
        vl = ctypes.c_int()
        try:
            while True:
                rc = self._lib.ckv_iter_next(
                    it, ctypes.byref(k), ctypes.byref(kl),
                    ctypes.byref(v), ctypes.byref(vl),
                )
                if rc < 0:
                    raise RuntimeError("cometkv iteration failed")
                if rc == 0:
                    return
                yield (
                    ctypes.string_at(k, kl.value),
                    ctypes.string_at(v, vl.value),
                )
        finally:
            self._lib.ckv_iter_close(it)

    def compact(self) -> None:
        with self._oplock:
            rc = self._lib.ckv_compact(self._handle())
        if rc == -2:
            return  # live iterators; skip this cycle
        if rc == -3:
            raise RuntimeError(
                "cometkv compact completed but directory sync failed; "
                "durability across power loss uncertain until the next "
                "successful sync"
            )
        if rc != 0:
            raise RuntimeError("cometkv compact failed")

    def sync(self) -> None:
        with self._oplock:
            rc = self._lib.ckv_sync(self._handle())
        if rc != 0:
            raise RuntimeError("cometkv sync failed")

    def count(self) -> int:
        with self._oplock:
            return int(self._lib.ckv_count(self._handle()))

    def dead_bytes(self) -> int:
        with self._oplock:
            return int(self._lib.ckv_dead_bytes(self._handle()))

    def close(self) -> None:
        with self._oplock:
            if self._h:
                self._lib.ckv_close(self._h)
                self._h = None
