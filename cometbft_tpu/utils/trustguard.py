"""Runtime wire-provenance guard — the trust-boundary analog of the
concurrency runtime modes in utils/sync.py and the device guard in
ops/jitguard.py.  Static half: tools/trustcheck.py; manual:
docs/trust_boundary.md.

The static lint proves the *call graph* routes wire-derived values
through validators; this module holds the *live system* to the same
registries.  With ``CMT_TPU_TRUSTGUARD=1``:

- every reactor seam (the ``receive`` implementations, the consensus
  message-queue dequeue, the RPC tx ingress) stamps a thread-local
  **wire context** on the decoded envelope via :func:`wire_context`;
- every registered validator marks the active context via
  :func:`note_validated` when its check actually ran;
- every registered sink calls :func:`check_sink` at its mutation
  point: if a wire context is active and NO validator has run in it,
  the guard increments ``consensus_trust_guard_trips_total{sink}``,
  records a ``trust_guard_trip`` flight event, and raises
  :class:`TrustGuardError` — the state is never mutated.

A sink reached with no active wire context (WAL replay, timeout-driven
commits, administrative paths) is NOT checked: provenance is only
asserted for values that demonstrably crossed the wire this call
chain.  Known runtime limits (the static pass covers them): contexts
are thread-local, so work handed to another thread (blocksync's apply
routine, the RPC async tx pool worker) re-stamps at the worker seam or
is out of guard scope.

Zero-cost when off: every entry point returns immediately on the
cached flag.
"""

from __future__ import annotations

import functools
import threading
from contextlib import contextmanager

from cometbft_tpu.utils.env import flag_from_env
from cometbft_tpu.utils.flight import FLIGHT

_ENABLED = flag_from_env("CMT_TPU_TRUSTGUARD")
_TLS = threading.local()

#: the node's ConsensusMetrics, installed at node assembly (the
#: process-wide-sink pattern of metrics.install_crypto_metrics: the
#: sinks live in types/ with no node handle).  None -> trips still
#: flight-record and raise, just without the counter.
_METRICS = None


class TrustGuardError(Exception):
    """A wire-derived value reached a registered consensus sink with
    no registered validator run in its wire context."""


def enabled() -> bool:
    return _ENABLED


def install_metrics(metrics) -> None:
    """Install the node's ConsensusMetrics as the trip counter sink
    (None resets)."""
    global _METRICS
    _METRICS = metrics


def reset(enable: bool | None = None) -> None:
    """Test helper: clear this thread's context stack and optionally
    override the enabled flag (None re-reads the environment)."""
    global _ENABLED
    _ENABLED = flag_from_env("CMT_TPU_TRUSTGUARD") if enable is None \
        else enable
    _TLS.stack = []


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


@contextmanager
def wire_context(origin: str):
    """Stamp everything in the dynamic extent of this block as
    wire-derived from ``origin`` (a reactor seam name).  Re-entrant:
    nested seams (a reactor calling into the syncer) push their own
    frame, so validation is asserted per innermost envelope."""
    if not _ENABLED:
        yield
        return
    st = _stack()
    st.append({"origin": origin, "validated": []})
    try:
        yield
    finally:
        st.pop()


def guarded_seam(origin: str):
    """Decorator form of :func:`wire_context` for reactor seams —
    everything the decorated function does runs under a wire context
    named ``origin``.  One flag check of overhead when the guard is
    off."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not _ENABLED:
                return fn(*args, **kwargs)
            with wire_context(origin):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def note_validated(validator: str) -> None:
    """Record that a registered validator ran for the innermost wire
    context (no-op outside one)."""
    if not _ENABLED:
        return
    st = _stack()
    if st:
        st[-1]["validated"].append(validator)


def check_sink(sink: str) -> None:
    """Assert at a registered sink's mutation point that a validator
    ran for the innermost wire context.  No-op when the guard is off
    or no wire context is active (local/replay/administrative paths
    carry no wire provenance)."""
    if not _ENABLED:
        return
    st = _stack()
    if not st:
        return
    frame = st[-1]
    if frame["validated"]:
        return
    if _METRICS is not None:
        _METRICS.trust_guard_trips_total.labels(sink=sink).inc()
    FLIGHT.record("trust_guard_trip", sink=sink, origin=frame["origin"])
    raise TrustGuardError(
        f"wire-derived value from seam '{frame['origin']}' reached "
        f"sink '{sink}' with no registered validator run in this "
        "context — the trust boundary was crossed unvalidated; see "
        "docs/trust_boundary.md"
    )


__all__ = [
    "TrustGuardError",
    "check_sink",
    "enabled",
    "guarded_seam",
    "install_metrics",
    "note_validated",
    "reset",
    "wire_context",
]
