"""Always-on sampling profiler — span-tagged folded stacks, stdlib only.

The span tracer (utils/trace.py) says WHICH pipeline stage owned a
height's wall; this module says WHAT CODE the CPU ran inside it.  A
daemon thread samples ``sys._current_frames()`` at a validated
``CMT_TPU_PROFILE_HZ`` (default 19 Hz — deliberately prime, so the
sampler can't phase-lock to a 10/20/50 ms periodic loop and
systematically miss it; 0 disables), folds each thread's stack into
the collapsed format flame-graph tooling eats directly
(``frame;frame;frame count``), and prefixes every sample with the
sampled thread's innermost open span (``span:store/save_block;...``)
so a flame graph is attributable to the critical-path taxonomy in
utils/critpath.py.

Design constraints, in order:

- **Hot-path cost**: ~19 stack walks per second across all threads —
  microseconds per tick; the sampled threads pay nothing (the GIL
  serializes the walk, same as any profiler built on
  ``sys._current_frames``).
- **Bounded retention**: samples land in a ``deque(maxlen=N)`` tick
  ring (CMT_TPU_PROFILE_RING, default 4096 ticks ≈ 3.5 min at 19 Hz)
  for windowed ``?seconds=N`` queries, plus a since-start counter
  capped at the same N distinct stacks (overflow counts in
  ``dropped``, never grows).
- **No dependencies**: stdlib only, importable from every plane.

Env knobs (the documented fail-loudly contract — node assembly
validates them the way it validates the ring-size vars):

- ``CMT_TPU_PROFILE_HZ`` — samples/second; integer >= 0, 0 disables
  (default 19).
- ``CMT_TPU_PROFILE_DEPTH`` — max frames kept per stack (default 48).
- ``CMT_TPU_PROFILE_RING`` — tick-ring / distinct-stack capacity
  (default 4096).

Surfaces: ``/debug/profile?seconds=N`` on the metrics server (add
``&format=collapsed`` for text), the ``debug/profile`` JSON-RPC route
(inspect mode included), and bench.py's per-row ``hotspots``
provenance (docs/observability.md "Attribution plane").
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.flight import ring_size_from_env

_DEFAULT_HZ = 19
_DEFAULT_DEPTH = 48
_DEFAULT_RING = 4096
_MAX_HZ = 1000

#: the span tag given to samples from threads with no open span
UNTAGGED = "-"


def profile_hz_from_env(
    var: str = "CMT_TPU_PROFILE_HZ", default: int = _DEFAULT_HZ
) -> int:
    """Sampling rate from the environment, fail-loudly (the
    ``ring_size_from_env`` contract): unset/empty means ``default``,
    anything else must parse as an integer in [0, 1000] — 0 disables
    the profiler, a typo'd value raises instead of silently sampling
    at a default the operator didn't choose."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        hz = int(raw)
    except ValueError:
        raise ValueError(
            f"{var}={raw!r} is not an integer (expected 0..{_MAX_HZ}; "
            "0 disables the profiler)"
        ) from None
    if hz < 0 or hz > _MAX_HZ:
        raise ValueError(
            f"{var}={hz} out of range (expected 0..{_MAX_HZ}; "
            "0 disables the profiler)"
        )
    return hz


def profile_depth_from_env() -> int:
    return ring_size_from_env(
        "CMT_TPU_PROFILE_DEPTH", _DEFAULT_DEPTH, minimum=4
    )


def profile_ring_from_env() -> int:
    return ring_size_from_env("CMT_TPU_PROFILE_RING", _DEFAULT_RING)


def _frame_label(code) -> str:
    """``pkg/module.py:function`` — short enough to read in a flame
    graph, long enough to disambiguate same-named functions."""
    fn = code.co_filename.replace("\\", "/")
    parts = fn.rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) >= 2 else fn
    return f"{short}:{code.co_name}"


class SamplingProfiler:
    """The sampler thread plus its two bounded stores (tick ring for
    windowed queries, capped counter for since-start totals)."""

    def __init__(
        self,
        hz: int | None = None,
        depth: int | None = None,
        capacity: int | None = None,
        tracer=None,
    ):
        self.hz = profile_hz_from_env() if hz is None else int(hz)
        self.depth = profile_depth_from_env() if depth is None else depth
        self.capacity = (
            profile_ring_from_env() if capacity is None else capacity
        )
        if tracer is None:
            from cometbft_tpu.utils.trace import TRACER

            tracer = TRACER
        self._tracer = tracer
        #: (wall_time, tuple-of-folded-stacks) per sampler tick
        self._ring: deque = deque(maxlen=max(self.capacity, 1))
        self._totals: dict[str, int] = {}
        #: interned folded-stack strings: samples repeat heavily, so
        #: the ring holds ~capacity references, not ~capacity copies
        self._intern: dict[str, str] = {}
        self._mtx = cmtsync.Mutex()
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None
        self._samples = 0
        self._dropped = 0
        self._started_wall: float | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Start the sampler thread (no-op when hz == 0 or already
        running)."""
        if self.hz <= 0 or self._thread is not None:
            return
        self._stop_evt.clear()
        self._started_wall = time.time()
        self._thread = threading.Thread(
            target=self._run, name="profiler-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop and JOIN the sampler — the thread is gone when this
        returns, so the PR 3 leak gate (assert_no_thread_leaks,
        daemons_too) covers it."""
        t = self._thread
        if t is None:
            return
        self._stop_evt.set()
        t.join(timeout=5.0)
        self._thread = None

    def is_running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _run(self) -> None:
        period = 1.0 / self.hz
        while not self._stop_evt.wait(period):
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 — a diagnostics plane
                pass  # must never take the process down

    # -- sampling ------------------------------------------------------

    def _sample_once(self) -> None:
        own = threading.get_ident()
        frames = sys._current_frames()
        spans = self._tracer.current_spans()
        now = time.time()
        folded: list[str] = []
        for tid, frame in frames.items():
            if tid == own:
                continue  # the sampler never profiles itself
            stack: list[str] = []
            f, n = frame, 0
            while f is not None and n < self.depth:
                stack.append(_frame_label(f.f_code))
                f = f.f_back
                n += 1
            stack.reverse()
            key = (
                f"span:{spans.get(tid, UNTAGGED)};" + ";".join(stack)
            )
            cached = self._intern.get(key)
            if cached is None:
                if len(self._intern) >= 4 * max(self.capacity, 1):
                    self._intern.clear()  # bounded, rebuilt on demand
                self._intern[key] = cached = key
            folded.append(cached)
        with self._mtx:
            self._samples += 1
            self._ring.append((now, tuple(folded)))
            for key in folded:
                if key in self._totals:
                    self._totals[key] += 1
                elif len(self._totals) < max(self.capacity, 1):
                    self._totals[key] = 1
                else:
                    self._dropped += 1

    # -- queries -------------------------------------------------------

    def stacks(self, seconds: float | None = None) -> dict[str, int]:
        """folded stack -> sample count; ``seconds`` limits to the
        trailing window (None = since start, from the capped
        counter)."""
        with self._mtx:
            if seconds is None:
                return dict(self._totals)
            cutoff = time.time() - max(float(seconds), 0.0)
            out: dict[str, int] = {}
            for t, keys in self._ring:
                if t < cutoff:
                    continue
                for k in keys:
                    out[k] = out.get(k, 0) + 1
            return out

    def collapsed(self, seconds: float | None = None) -> str:
        """Brendan-Gregg collapsed-stack text — pipe straight into
        flamegraph.pl / speedscope."""
        got = self.stacks(seconds)
        return "\n".join(
            f"{k} {c}"
            for k, c in sorted(got.items(), key=lambda kv: -kv[1])
        )

    def span_seconds(self, seconds: float | None = None) -> dict[str, int]:
        """span tag -> sample count: the cheap 'which stage burns CPU'
        rollup (sample counts, convert via hz for seconds)."""
        out: dict[str, int] = {}
        for k, c in self.stacks(seconds).items():
            tag = k.split(";", 1)[0][len("span:"):]
            out[tag] = out.get(tag, 0) + c
        return out

    def top_functions(
        self, k: int = 5, seconds: float | None = None
    ) -> list[dict]:
        """Leaf-frame hotspots: [{frame, count, share}] sorted by
        count — what bench.py records as per-row ``hotspots``
        provenance."""
        leaves: dict[str, int] = {}
        total = 0
        for key, c in self.stacks(seconds).items():
            leaf = key.rsplit(";", 1)[-1]
            leaves[leaf] = leaves.get(leaf, 0) + c
            total += c
        return [
            {
                "frame": frame,
                "count": count,
                "share": round(count / total, 4) if total else 0.0,
            }
            for frame, count in sorted(
                leaves.items(), key=lambda kv: -kv[1]
            )[: max(k, 0)]
        ]

    def payload(self, seconds: float | None = None) -> dict:
        """The ``/debug/profile`` JSON: folded stacks + per-span
        rollup + leaf hotspots for the requested window."""
        got = self.stacks(seconds)
        with self._mtx:
            samples, dropped = self._samples, self._dropped
        return {
            "enabled": True,
            "hz": self.hz,
            "depth": self.depth,
            "capacity": self.capacity,
            "running": self.is_running(),
            "seconds": seconds,
            "samples": samples,
            "dropped_stacks": dropped,
            "started_wall": self._started_wall,
            "stacks": [
                {"stack": k, "count": c}
                for k, c in sorted(got.items(), key=lambda kv: -kv[1])
            ],
            "spans": self.span_seconds(seconds),
            "hotspots": self.top_functions(10, seconds),
        }

    def clear(self) -> None:
        with self._mtx:
            self._ring.clear()
            self._totals.clear()
            self._intern.clear()
            self._samples = 0
            self._dropped = 0


# -- the process-wide profiler (sink pattern, crypto/fleet analog) --------

_PROFILER: SamplingProfiler | None = None


def profiler() -> SamplingProfiler | None:
    """The installed process-wide profiler, or None when disabled."""
    return _PROFILER


def install_profiler(p: SamplingProfiler | None) -> None:
    global _PROFILER
    _PROFILER = p


def start_from_env(logger=None) -> SamplingProfiler | None:
    """Validate the env knobs (fail-loudly — a malformed
    CMT_TPU_PROFILE_HZ must fail node assembly, not silently profile
    at a rate the operator didn't choose), then start and install the
    process-wide sampler.  Returns None when disabled (hz == 0)."""
    hz = profile_hz_from_env()
    profile_depth_from_env()
    profile_ring_from_env()
    if hz == 0:
        return None
    p = SamplingProfiler(hz=hz)
    p.start()
    install_profiler(p)
    if logger is not None:
        logger.info("sampling profiler started", hz=hz)
    return p


def profile_payload(seconds: float | None = None) -> dict:
    """The ``/debug/profile`` payload — honest about being off."""
    p = profiler()
    if p is None:
        return {
            "enabled": False,
            "hz": 0,
            "samples": 0,
            "stacks": [],
            "spans": {},
            "hotspots": [],
            "hint": "set CMT_TPU_PROFILE_HZ (default 19; 0 disables)",
        }
    return p.payload(seconds)


__all__ = [
    "SamplingProfiler",
    "UNTAGGED",
    "install_profiler",
    "profile_depth_from_env",
    "profile_hz_from_env",
    "profile_payload",
    "profile_ring_from_env",
    "profiler",
    "start_from_env",
]
