"""Device-plugin environment handling shared by every CPU-mode path.

The TPU plugin registers itself from sitecustomize at interpreter
start and (a) with its env vars present and the tunnel wedged, backend
initialization hangs in C where no Python signal handler runs, and
(b) its registration overrides the JAX_PLATFORMS env var at the CONFIG
level (jax.config.update("jax_platforms", "axon,cpu")).  Anything that
wants a guaranteed-CPU jax — bench fallbacks, subprocess localnet
nodes, the driver dryrun — must scrub the plugin env from CHILD
environments before exec, and force the config back in-process.
ONE definition of the prefix list lives here.
"""

from __future__ import annotations

from typing import MutableMapping

#: env prefixes owned by the device plugin/tunnel
PLUGIN_ENV_PREFIXES = ("AXON_", "PALLAS_AXON")


def scrub_plugin_env(env: MutableMapping[str, str]) -> None:
    """Remove the device plugin's env vars from ``env`` in place
    (pass a copy of os.environ for subprocess children)."""
    for key in [k for k in env if k.startswith(PLUGIN_ENV_PREFIXES)]:
        env.pop(key, None)


def force_cpu_platform() -> None:
    """In-process: undo the plugin registration's jax_platforms
    override so only the CPU backend can initialize.  Call before any
    jax computation; safe to call repeatedly."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def probe_device_count(timeout_s: float = 20.0) -> int:
    """Count visible accelerator devices from a FRESH subprocess with a
    parent-enforced deadline; 0 on any failure or timeout.

    Pipe-safety matters here: subprocess.run(capture_output=True)
    drains pipes to EOF after a timeout-kill, and a tunnel helper
    grandchild holding the write end would block the parent forever —
    the exact hang class the probe exists to dodge.  Output goes to a
    temp file and the child gets its own session so the WHOLE process
    group is killed on timeout."""
    import os
    import signal
    import subprocess
    import sys
    import tempfile

    with tempfile.TemporaryFile() as out:
        try:
            proc = subprocess.Popen(
                [
                    sys.executable,
                    "-c",
                    "import jax; print(len(jax.devices()))",
                ],
                stdout=out,
                stderr=subprocess.DEVNULL,
                stdin=subprocess.DEVNULL,
                start_new_session=True,
            )
        except OSError:
            return 0
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
            return 0
        if rc != 0:
            return 0
        out.seek(0)
        try:
            return int(out.read().strip() or 0)
        except ValueError:
            return 0
