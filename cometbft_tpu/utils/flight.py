"""Always-on flight recorder for the replication plane.

The metrics plane answers "how much / how often", the span tracer
answers "what happened inside THIS commit" — but both lose the story
when the node wedges: a dashboard shows the stall started, a trace ring
full of healthy heights shows nothing.  This module keeps a bounded
ring of the most recent replication EVENTS — consensus step
transitions, WAL writes/fsyncs, ABCI calls, blocksync requests,
statesync chunks, store saves, peer errors — so the last ~2k things the
node did before a wedge survive to the post-mortem.

Design constraints, in order:

- **Always on**: unlike tracing there is no off switch — by the time
  you know you needed it, it is too late to enable.  That forces the
  record path to be as cheap as possible.
- **Lock-cheap**: the ring is a ``deque(maxlen=N)``; ``append`` on a
  bounded deque is atomic under the GIL, so ``record()`` takes NO lock
  (the ``recorded_total`` counter is best-effort under concurrency —
  it is diagnostics, not accounting).
- **Bounded**: depth from ``CMT_TPU_FLIGHT_DEPTH`` (default 2048,
  validated); a long-running node keeps a sliding window, never an
  unbounded log.
- **No dependencies**: stdlib only, importable from every plane
  (``utils/sync.py`` attaches the tail to LockOrderError/RaceError
  reports, ``ops/jitguard.py`` to RetraceError) without cycles.

Surfaces: the metrics HTTP server serves ``/debug/flight`` next to
``/metrics`` and ``/trace``; the JSON-RPC server exposes a
``debug/flight`` route (inspect mode included); and the error classes
above carry ``format_tail()`` in their messages.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

DEFAULT_DEPTH = 2048


def ring_size_from_env(var: str, default: int, minimum: int = 16) -> int:
    """Shared ring-size validator for CMT_TPU_FLIGHT_DEPTH and
    CMT_TPU_TRACE_RING (one contract, documented together in
    docs/observability.md): a positive integer >= ``minimum`` (smaller
    rings can't hold even one height's worth of events); anything else
    fails loudly at import with the variable and constraint named."""
    raw = os.environ.get(var)
    if raw is None or raw.strip() == "":
        return default
    try:
        size = int(raw)
    except ValueError:
        raise ValueError(
            f"{var} must be an integer >= {minimum}, got {raw!r}"
        ) from None
    if size < minimum:
        raise ValueError(f"{var} must be >= {minimum}, got {size}")
    return size


class FlightRecorder:
    """Bounded lock-free ring of recent replication events."""

    def __init__(self, depth: int | None = None):
        if depth is None:
            depth = ring_size_from_env("CMT_TPU_FLIGHT_DEPTH", DEFAULT_DEPTH)
        elif depth < 1:
            raise ValueError(f"flight depth must be >= 1, got {depth}")
        self.depth = depth
        self._ring: deque[dict] = deque(maxlen=depth)
        # best-effort under concurrency (unlocked += is not atomic);
        # used for the dropped-events estimate, not accounting
        self.recorded_total = 0

    # -- recording -------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one event.  ``fields`` must be JSON-able primitives
        (call sites hex() bytes); the hot path builds one dict and
        appends — no lock, no I/O.

        ``t`` is WALL clock (``time.time()``), by contract: the fleet
        aggregator merges flight rings from N nodes onto one timeline
        keyed on it, so a monotonic stamp here would force per-ring
        offset archaeology.  One clock read per event — nothing else
        on this path may add a syscall."""
        self._ring.append(
            {
                "t": time.time(),
                "thread": threading.current_thread().name,
                "kind": kind,
                **fields,
            }
        )
        self.recorded_total += 1

    # -- reading ---------------------------------------------------------

    def events(self) -> list[dict]:
        """Snapshot, oldest first."""
        return list(self._ring)

    def tail(self, n: int = 20) -> list[dict]:
        return self.events()[-n:]

    def export(self) -> dict:
        """The ``/debug/flight`` payload."""
        events = self.events()
        return {
            "depth": self.depth,
            "recorded_total": self.recorded_total,
            "dropped": max(0, self.recorded_total - len(events)),
            # event "t" stamps are wall clock — the fleet aggregator
            # merges rings across nodes on this promise
            "clock": "wall",
            "events": events,
        }

    def format_tail(self, n: int = 20) -> str:
        """Human-readable tail for attaching to error reports
        (RetraceError / LockOrderError / RaceError, consensus panic
        log lines)."""
        lines = [f"--- flight recorder tail (last {n} of "
                 f"{self.recorded_total} events) ---"]
        for ev in self.tail(n):
            extra = " ".join(
                f"{k}={v}"
                for k, v in ev.items()
                if k not in ("t", "thread", "kind")
            )
            lines.append(
                f"  {ev['t']:.6f} [{ev['thread']}] {ev['kind']}"
                + (f" {extra}" if extra else "")
            )
        if len(lines) == 1:
            lines.append("  <empty>")
        return "\n".join(lines)

    def clear(self) -> None:
        self._ring.clear()
        self.recorded_total = 0


#: process-wide recorder — every plane records here, all surfaces read
#: here (mirrors utils/trace.TRACER)
FLIGHT = FlightRecorder()


def flight_tail(n: int = 20) -> str:
    """Convenience for error constructors: a newline-prefixed tail that
    can be appended to any message (empty-ring safe)."""
    return "\n" + FLIGHT.format_tail(n)


__all__ = [
    "DEFAULT_DEPTH",
    "FLIGHT",
    "FlightRecorder",
    "flight_tail",
    "ring_size_from_env",
]
