"""Service lifecycle base class.

Every long-lived component embeds this, mirroring the reference's
``service.BaseService`` (libs/service/service.go): idempotent
start/stop, a quit event, and overridable on_start/on_stop hooks.
"""

from __future__ import annotations

import threading

from cometbft_tpu.utils.log import Logger, default_logger


class ServiceError(RuntimeError):
    pass


class AlreadyStartedError(ServiceError):
    pass


class AlreadyStoppedError(ServiceError):
    pass


class NotStartedError(ServiceError):
    pass


class BaseService:
    """Idempotent start/stop lifecycle (libs/service/service.go:99).

    Subclasses override :meth:`on_start` / :meth:`on_stop` and may wait on
    :meth:`quit_event` in background threads.
    """

    def __init__(self, name: str | None = None, logger: Logger | None = None):
        self._name = name or type(self).__name__
        self.logger = logger or default_logger().with_fields(module=self._name)
        self._mtx = threading.Lock()
        self._started = False
        self._stopped = False
        self._quit = threading.Event()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        with self._mtx:
            if self._started:
                raise AlreadyStartedError(f"{self._name} already started")
            if self._stopped:
                raise AlreadyStoppedError(f"{self._name} already stopped")
            self._started = True
        self.logger.info("service start")
        try:
            self.on_start()
        except BaseException:
            with self._mtx:
                self._started = False
            raise

    def stop(self) -> None:
        with self._mtx:
            if not self._started:
                raise NotStartedError(f"{self._name} not started")
            if self._stopped:
                return  # stop is idempotent once started
            self._stopped = True
        self.logger.info("service stop")
        self._quit.set()
        self.on_stop()

    def is_running(self) -> bool:
        with self._mtx:
            return self._started and not self._stopped

    def quit_event(self) -> threading.Event:
        return self._quit

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the service is stopped."""
        return self._quit.wait(timeout)

    # -- overridables --------------------------------------------------

    def on_start(self) -> None:  # pragma: no cover - trivial
        pass

    def on_stop(self) -> None:  # pragma: no cover - trivial
        pass

    def __repr__(self) -> str:
        state = "running" if self.is_running() else "stopped"
        return f"<{self._name} {state}>"
