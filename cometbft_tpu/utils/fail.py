"""Crash-point injection (reference: internal/fail/fail.go).

``fail_point()`` is sprinkled through ApplyBlock's persistence sequence
(state/execution.go:270,277,317,325); setting ``FAIL_TEST_INDEX=n``
makes the n-th call hard-exit the process, so replay tests can assert
recovery from every crash point.
"""

from __future__ import annotations

import os

_call_index = 0


def reset() -> None:
    global _call_index
    _call_index = 0


def fail_point() -> None:
    global _call_index
    target = os.environ.get("FAIL_TEST_INDEX")
    if target is None or target == "":
        return
    if _call_index == int(target):
        os._exit(1)  # simulate kill -9: no cleanup, no flush
    _call_index += 1
