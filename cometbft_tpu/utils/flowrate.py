"""Flow-rate monitoring and limiting.

Token-bucket style rate accounting used by the connection send/recv
routines and the blocksync pool, mirroring the capability of the
reference's ``internal/flowrate`` (flowrate.go) — a sliding-window
rate monitor with a blocking ``limit`` call.
"""

from __future__ import annotations

import threading
import time


class Monitor:
    """Sliding-EMA transfer-rate monitor (internal/flowrate/flowrate.go:13).

    Tracks bytes transferred and an exponentially-weighted rate sample.
    ``limit(want, rate)`` blocks until transferring ``want`` more bytes
    would not exceed ``rate`` bytes/sec, then returns the permitted count.
    """

    def __init__(self, sample_period: float = 0.1, window: float = 1.0):
        self._mtx = threading.Lock()
        self._sample_period = sample_period
        self._alpha = sample_period / max(window, sample_period)
        self.start = time.monotonic()
        self.bytes_total = 0
        self.rate_avg = 0.0  # EMA bytes/sec
        self.rate_peak = 0.0  # highest EMA sample seen
        self._sample_bytes = 0
        self._sample_start = self.start
        self._window = window
        self._credit = 0.0
        self._credit_time = self.start
        self.active = True

    def update(self, n: int) -> int:
        """Record ``n`` transferred bytes."""
        with self._mtx:
            self._advance_locked()
            self.bytes_total += n
            self._sample_bytes += n
            return n

    def _advance_locked(self) -> None:
        now = time.monotonic()
        elapsed = now - self._sample_start
        while elapsed >= self._sample_period:
            rate = self._sample_bytes / self._sample_period
            self.rate_avg += self._alpha * (rate - self.rate_avg)
            if self.rate_avg > self.rate_peak:
                self.rate_peak = self.rate_avg
            self._sample_bytes = 0
            self._sample_start += self._sample_period
            elapsed -= self._sample_period
            # after an idle gap the remaining windows all carry zero bytes;
            # fast-forward instead of looping unboundedly
            if elapsed > 10 * self._sample_period:
                self.rate_avg *= (1 - self._alpha) ** int(
                    elapsed / self._sample_period
                )
                self._sample_start = now
                break

    def status(self) -> dict:
        with self._mtx:
            self._advance_locked()
            dur = max(time.monotonic() - self.start, 1e-9)
            return {
                "bytes": self.bytes_total,
                "duration": dur,
                "rate_avg": self.rate_avg,
                "rate_peak": self.rate_peak,
                "rate_mean": self.bytes_total / dur,
            }

    def limit(self, want: int, rate: int) -> int:
        """Block until ``want`` bytes may be transferred without exceeding
        ``rate`` B/s; returns bytes permitted (== want).

        Token bucket with burst capped at one window's worth of bytes —
        idle time earns at most ``rate * window`` credit, so a peer that
        sleeps then floods is still throttled to the configured rate
        (flowrate.go Monitor.Limit, as used by MConnection's
        sendRoutine — p2p/conn/connection.go:43-44).
        """
        if rate <= 0 or want <= 0:
            return max(want, 0)
        burst = max(rate * self._window, float(want))
        while True:
            with self._mtx:
                now = time.monotonic()
                self._credit = min(
                    burst, self._credit + (now - self._credit_time) * rate
                )
                self._credit_time = now
                if self._credit >= want:
                    self._credit -= want
                    return want
                wait = (want - self._credit) / rate
            if not self.active:
                return 0
            time.sleep(min(wait, 0.1))

    def done(self) -> None:
        self.active = False


__all__ = ["Monitor"]
