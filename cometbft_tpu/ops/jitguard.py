"""Runtime jit/retrace + transfer guard — the device-path analog of
the concurrency runtime modes in utils/sync.py (CMT_TPU_LOCKGRAPH /
CMT_TPU_RACE).  Static half: tools/jitcheck.py; manual:
docs/device_contracts.md.

The throughput story (PAPERS.md: committee-signature verification
lives or dies on a stable compiled program staying on-device) has two
silent failure modes that neither tests nor dashboards saw before
this module:

- **Silent retraces.**  Every compiled kernel is memoized behind a
  registered seam (the ``_compiled*`` functions in ops/ed25519_verify,
  ops/precompute, parallel/mesh).  A key drifting off the
  pow2/bucket/chunk ladder recompiles a multi-second XLA program in
  the middle of the steady state — ~100ms of verify work stalls for
  the compile and the jit cache grows without bound.
- **Implicit host<->device transfers.**  A stray ``np.asarray`` on a
  device value, or a numpy operand reaching a compiled function
  without ``jax.device_put``, silently pays the link round trip
  (~70ms on the tunneled axon backend) per call.

``CMT_TPU_JITGUARD=1`` arms both checks, zero-cost when off:

- every compile-cache miss is counted per seam (CryptoMetrics
  ``crypto_jit_cache_misses{seam=...}``) and its call stack recorded;
- after ``seal()`` (the warmup boundary — benches call it once their
  first launches have compiled), ANY further compile raises
  ``RetraceError`` carrying the offending key signature, the seam,
  this compile's stack AND the seam's previous compile-site stack;
- ``transfer_window()`` (armed by TpuBatchVerifier.verify around the
  device dispatch) applies ``jax.transfer_guard("disallow")`` once
  sealed, so an implicit transfer raises at the offending line
  instead of stalling; trips increment
  ``crypto_guard_trips{kind=transfer}``.

Compile-cache miss COUNTING is always on (an int increment plus a
no-op metrics call) so bench provenance can report warmup compile
counts without the guard armed; stacks are recorded and errors raised
only under CMT_TPU_JITGUARD=1.
"""

from __future__ import annotations

import os
import traceback
from contextlib import contextmanager

from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.utils.env import flag_from_env

_ENABLED = flag_from_env("CMT_TPU_JITGUARD")

def _is_transfer_guard_error(exc: Exception) -> bool:
    """Attribute a trip to the metrics counter only for the error the
    jax.transfer_guard context actually raises (XlaRuntimeError whose
    message anchors on 'Disallowed ... transfer') — a stray exception
    that merely mentions 'transfer' must not fire the dashboard
    counter.  The original exception always propagates unchanged."""
    msg = str(exc).lower()
    return (
        type(exc).__name__ == "XlaRuntimeError"
        and "disallow" in msg
        and "transfer" in msg
    )


class RetraceError(Exception):
    """A compile-cache seam recompiled after the warmup boundary —
    steady state hit a multi-second XLA compile.  Carries the seam,
    the offending key signature, and both compile-site stacks (this
    one and the seam's previous compile)."""


_counts: dict[str, int] = {}          # seam -> lifetime compile count
_last_site: dict[str, tuple] = {}     # seam -> (key, stack) of last compile
_sealed = False


def enabled() -> bool:
    return _ENABLED


def note_compile(seam: str, key) -> None:
    """Record a compile-cache miss at a registered seam.  Called by
    the ``_compiled*`` memoizers BEFORE building the jit wrapper, so a
    post-warmup retrace raises before any compile time is spent."""
    _counts[seam] = _counts.get(seam, 0) + 1
    _crypto_metrics().jit_cache_misses.labels(seam=seam).inc()
    if not _ENABLED:
        return
    stack = "".join(traceback.format_stack(limit=16)[:-1])
    if _sealed:
        prior_key, prior_stack = _last_site.get(
            seam, (None, "<no compile before seal()>")
        )
        _crypto_metrics().guard_trips.labels(kind="retrace").inc()
        from cometbft_tpu.utils.flight import flight_tail

        raise RetraceError(
            f"RETRACE after warmup at seam '{seam}': key {key!r} has no "
            f"compiled program (cache warmed with e.g. {prior_key!r}).\n"
            "A steady-state arg signature drifted off the "
            "pow2/bucket/chunk ladder — see docs/device_contracts.md.\n"
            f"--- this compile request:\n{stack}"
            f"--- previous compile at seam '{seam}':\n{prior_stack}"
            + flight_tail()
        )
    _last_site[seam] = (key, stack)


def compile_counts() -> dict[str, int]:
    """Per-seam lifetime compile counts — BENCH provenance reads this
    after warmup so future perf PRs can assert steady state compiled
    nothing new."""
    return dict(_counts)


def sealed() -> bool:
    return _sealed


def seal() -> None:
    """End the warmup phase: from here on (with CMT_TPU_JITGUARD=1)
    any compile-cache miss raises RetraceError and transfer_window()
    arms jax.transfer_guard("disallow")."""
    global _sealed
    _sealed = True


def reset() -> None:
    """Test/bench helper: forget counts, sites and the seal."""
    global _sealed
    _sealed = False
    _counts.clear()
    _last_site.clear()


@contextmanager
def transfer_window():
    """Arm ``jax.transfer_guard("disallow")`` around a steady-state
    verify window: implicit host<->device transfers (a numpy operand
    reaching a compiled call, ``float()``/``np.asarray`` on a device
    value) raise at the offending line instead of silently paying the
    link RTT.  Explicit ``jax.device_put`` / ``jax.device_get`` — the
    audited transfer idioms of the dispatch path — stay allowed.

    A no-op until the guard is enabled AND sealed: warmup compiles
    legitimately stage trace-time constants, so only the steady state
    is held to the no-implicit-transfers bar.
    """
    if not (_ENABLED and _sealed):
        yield
        return
    import jax

    try:
        with jax.transfer_guard("disallow"):
            yield
    except Exception as exc:
        if _is_transfer_guard_error(exc):
            _crypto_metrics().guard_trips.labels(kind="transfer").inc()
        raise


__all__ = [
    "RetraceError",
    "compile_counts",
    "enabled",
    "note_compile",
    "reset",
    "seal",
    "sealed",
    "transfer_window",
]
