"""Kernel shape/dtype contracts — deviceless verification of the
device-path ABI.

Every public kernel in ``cometbft_tpu/ops`` declares its traced-input
and output shapes/dtypes in a module-level ``_CONTRACTS`` dict of PURE
LITERALS (so tools/jitcheck.py can verify the declarations statically,
without importing jax), e.g.::

    _CONTRACTS = {
        "verify_kernel_packed": {
            "args": {"buf": ("u8", ("100+bucket", "B"))},
            "static": ("bucket", "nblocks"),
            "out": ("bool", ("B",)),
        },
    }

Spec grammar (checked by jitcheck, interpreted here):

- a LEAF spec is ``(dtype, shape)`` — dtype one of DTYPES, shape a
  tuple of dims; a dim is an int or a string arithmetic expression
  over the symbols in ``ladder_env`` (``B``, ``bucket``, ``nblocks``,
  ``NLIMBS``, ``nwin``, ``nent``, ``cap``, ...);
- a LIST groups specs into a tuple-valued arg/output (e.g. an
  extended point is four ``("i32", ("NLIMBS", "B"))`` leaves).

``check_contract`` builds ``jax.ShapeDtypeStruct`` inputs from the
spec, runs the kernel through ``jax.eval_shape`` (abstract evaluation:
no device, no FLOPs — tier-1 CPU CI runs the whole bucket ladder in
milliseconds), and diffs the result leaves against the declared
output.  A shape or dtype regression in any kernel therefore fails in
CI before ever touching a TPU (the int32-limb / uint8-packed-buffer
representation is load-bearing: docs/device_contracts.md).
"""

from __future__ import annotations

import ast
import functools

DTYPES = {
    "u8": "uint8",
    "i32": "int32",
    "i64": "int64",
    "u64": "uint64",
    "bool": "bool_",
}

#: symbols a dim expression may reference (jitcheck enforces this
#: statically; ladder_env binds them for the eval_shape sweep).
#: ``ndev`` is the mesh device count — shard-local kernel contracts
#: (parallel/mesh.py) express their dims as global//ndev.
DIM_SYMBOLS = frozenset(
    {"B", "bucket", "nblocks", "NLIMBS", "nwin", "nent", "cap", "M",
     "ndev"}
)


def eval_dim(dim, env: dict) -> int:
    """An int dim, or a string arithmetic expression over DIM_SYMBOLS
    (+ - * // and parentheses; ``/`` resolves as integer division)."""
    if isinstance(dim, int):
        return dim
    node = ast.parse(str(dim), mode="eval").body

    def ev(n) -> int:
        if isinstance(n, ast.Constant) and isinstance(n.value, int):
            return n.value
        if isinstance(n, ast.Name):
            return int(env[n.id])
        if isinstance(n, ast.BinOp):
            a, b = ev(n.left), ev(n.right)
            if isinstance(n.op, ast.Add):
                return a + b
            if isinstance(n.op, ast.Sub):
                return a - b
            if isinstance(n.op, ast.Mult):
                return a * b
            if isinstance(n.op, (ast.FloorDiv, ast.Div)):
                return a // b
        raise ValueError(f"unsupported dim expression: {dim!r}")

    return ev(node)


def dim_names(dim) -> set[str]:
    """The symbols a dim expression references (static check)."""
    if isinstance(dim, int):
        return set()
    return {
        n.id
        for n in ast.walk(ast.parse(str(dim), mode="eval"))
        if isinstance(n, ast.Name)
    }


def is_leaf(spec) -> bool:
    return (
        isinstance(spec, tuple)
        and len(spec) == 2
        and isinstance(spec[0], str)
    )


def _leaves(spec) -> list[tuple]:
    if is_leaf(spec):
        return [spec]
    out: list[tuple] = []
    for s in spec:
        out.extend(_leaves(s))
    return out


def _build(spec, env: dict):
    """Spec -> ShapeDtypeStruct (leaf) or tuple thereof (list)."""
    import jax
    import jax.numpy as jnp

    if is_leaf(spec):
        dtype, shape = spec
        return jax.ShapeDtypeStruct(
            tuple(eval_dim(d, env) for d in shape),
            getattr(jnp, DTYPES[dtype]),
        )
    return tuple(_build(s, env) for s in spec)


def ladder_env(batch: int, bucket: int = 128, window_bits: int = 8,
               cap: int | None = None, ndev: int = 1) -> dict:
    """The dim bindings for one rung of the batch/bucket ladder —
    exactly the quantities the dispatch path derives (ed25519_verify:
    nblocks from the bucket; precompute: nwin/nent from the window
    width; cap from the pool ladder; parallel/mesh: ndev the mesh
    device count, which must divide ``batch`` and ``cap`` the way the
    lane router / table placement pad them)."""
    from cometbft_tpu.ops import field as F
    from cometbft_tpu.ops.ed25519_verify import nblocks_for_bucket

    return {
        "B": batch,
        "bucket": bucket,
        "M": bucket,
        "nblocks": nblocks_for_bucket(bucket),
        "NLIMBS": F.NLIMBS,
        "window_bits": window_bits,
        "nwin": 256 // window_bits,
        "nent": 1 << window_bits,
        "cap": cap if cap is not None else batch,
        "ndev": ndev,
    }


def check_contract(fn, contract: dict, env: dict) -> list[str]:
    """eval_shape ``fn`` against one contract at one env binding.
    Returns a list of mismatch descriptions (empty = conforming)."""
    import jax

    # traced args go by KEYWORD so static params interleaved in the
    # signature (sha512_padded(buf, nblocks, nblocks_lane)) bind right
    args = {
        name: _build(spec, env) for name, spec in contract["args"].items()
    }
    static = {name: env[name] for name in contract.get("static", ())}
    try:
        got = jax.eval_shape(functools.partial(fn, **static), **args)
    except Exception as exc:  # noqa: BLE001 — report, don't crash sweep
        return [f"{fn.__name__}: eval_shape failed at {env}: {exc!r}"]
    got_leaves = jax.tree_util.tree_leaves(got)
    want = _leaves(contract["out"])
    errors: list[str] = []
    if len(got_leaves) != len(want):
        errors.append(
            f"{fn.__name__}: {len(got_leaves)} output leaves, contract "
            f"declares {len(want)}"
        )
        return errors
    import numpy as np

    for i, (leaf, (dtype, shape)) in enumerate(zip(got_leaves, want)):
        want_shape = tuple(eval_dim(d, env) for d in shape)
        want_dtype = np.dtype(DTYPES[dtype])
        if tuple(leaf.shape) != want_shape:
            errors.append(
                f"{fn.__name__} out[{i}]: shape {tuple(leaf.shape)} != "
                f"contract {want_shape} (dims {shape}) at {env}"
            )
        if np.dtype(leaf.dtype) != want_dtype:
            errors.append(
                f"{fn.__name__} out[{i}]: dtype {leaf.dtype} != "
                f"contract {want_dtype} at {env}"
            )
    return errors


def check_module(module, env: dict) -> list[str]:
    """Sweep every contract a module declares at one env binding."""
    errors: list[str] = []
    for name, contract in getattr(module, "_CONTRACTS", {}).items():
        errors.extend(check_contract(getattr(module, name), contract, env))
    return errors
