"""Device-resident per-validator precomputation for the verify kernel.

The reference caches expanded public keys in an LRU sized to the
validator set because the same keys verify every block
(crypto/ed25519/ed25519.go:43,62-68 — a 4k-entry ExpandedPublicKey
cache).  On TPU the analogous (and much larger) win is keeping whole
scalar-multiplication tables device-resident: steady-state commit
verification then does only SHA-512, the R decompression, and comb
table adds — no per-launch point decompression or window-table build.

Two table families:

- **Fixed base B** (shared, host-built once): an 8-bit comb
  ``B_COMB8[w][j] = j * 256^w * B`` in affine-Niels form — 32 mixed
  adds for [S]B instead of 64.

- **Per-validator-set tables** (device-built): for each key A, comb
  entries ``j * (2^wb)^w * (-A)`` in *projective* Niels form
  (Y+X, Y-X, 2Z, 2dT) — keeping Z projective skips the batched field
  inversion at build time for one extra field mul per add
  (curve.pt_add_pniels).  Window width adapts to the set size: 8-bit
  combs (32 adds/verify, ~3.4 MB/key) for sets up to KEY8_MAX keys,
  4-bit (64 adds, ~430 KB/key) above.

Tables are cached per validator *set* (hash of the sorted unique
pubkeys) in an LRU bounded by CMT_TPU_TABLE_CACHE_MB.  Set-granular
caching rebuilds on any rotation, but a build costs ~10 verifies per
key and a set serves every block until it changes — the steady-state
amortization the reference's per-key LRU is after.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.crypto import edwards as _ref
from cometbft_tpu.ops import curve as C
from cometbft_tpu.ops import field as F
from cometbft_tpu.ops.ed25519_verify import _next_pow2

#: largest set that gets 8-bit per-key combs (3.4 MB/key on device)
KEY8_MAX = int(os.environ.get("CMT_TPU_KEY8_MAX", 256))
#: largest set we precompute tables for at all
TABLE_MAX_KEYS = int(os.environ.get("CMT_TPU_TABLE_MAX_KEYS", 16384))
#: total device bytes across cached sets before LRU eviction
TABLE_CACHE_MB = int(os.environ.get("CMT_TPU_TABLE_CACHE_MB", 6144))


# -- fixed-base 8-bit comb (host-built, shared) ------------------------

_B8_LOCK = threading.Lock()
_B8: np.ndarray | None = None


def b_comb8() -> np.ndarray:
    """(32, 3, 26, 256) affine-Niels comb of B, gather-friendly layout
    (entry index on the minor axis). Built lazily: ~8k host EC ops."""
    global _B8
    with _B8_LOCK:
        if _B8 is None:
            table = np.zeros((32, 256, 3, F.NLIMBS), dtype=np.int32)
            base = _ref.B_POINT
            for w in range(32):
                acc = _ref.IDENTITY
                for j in range(256):
                    if j == 0:
                        table[w, j] = np.stack([F.ONE, F.ONE, F.ZERO])
                    else:
                        acc = _ref.pt_add(acc, base)
                        ax, ay = _ref.pt_to_affine(acc)
                        table[w, j] = C._niels_from_affine(ax, ay)
                for _ in range(8):
                    base = _ref.pt_double(base)
            _B8 = np.ascontiguousarray(table.transpose(0, 2, 3, 1))
        return _B8


def comb_mul_base8(s_bytes):
    """[S]B via the 8-bit Niels comb: s_bytes (32, *batch) uint8 (LE
    scalar encoding; the comb is exact for any 256-bit integer)."""
    table = jnp.asarray(b_comb8())
    idx = s_bytes.astype(jnp.int32)

    def body(acc, xs):
        tbl_w, byte = xs  # (3, 26, 256), (*batch,)
        e = jnp.take(tbl_w, byte, axis=-1)  # (3, 26, *batch)
        return C.pt_add_niels(acc, (e[0], e[1], e[2])), None

    acc, _ = lax.scan(body, C.identity(s_bytes.shape[1:]), (table, idx))
    return acc


# -- per-key projective-Niels comb builder (device) --------------------

_BX, _BY = _ref.pt_to_affine(_ref.B_POINT)
_B_AFFINE = (F.from_int(_BX), F.from_int(_BY))


def build_tables_kernel(pub, window_bits: int):
    """pub (32, n) uint8 -> (table, valid).

    table: (nwin, 4, 26, n * nent) int32 — window-major projective
    Niels entries ``j * (2^wb)^w * (-A_key)``, minor axis ordered
    (key, entry) so a verify gathers with ``key_id * nent + window``.
    valid: (n,) bool — ZIP-215 decompression validity per key; invalid
    keys get B's table (harmless) and must be masked by callers.
    """
    n = pub.shape[-1]
    nwin = 256 // window_bits
    nent = 1 << window_bits
    a_pt, valid = C.decompress(pub)
    # keep the formulas on-curve for invalid encodings: substitute B
    bx = F.cvec(_B_AFFINE[0], pub.ndim)
    by = F.cvec(_B_AFFINE[1], pub.ndim)
    one = F.cvec(F.ONE, pub.ndim)
    x = F.select(valid, a_pt[0], jnp.broadcast_to(bx, a_pt[0].shape))
    y = F.select(valid, a_pt[1], jnp.broadcast_to(by, a_pt[1].shape))
    z = jnp.broadcast_to(one, y.shape)
    base = C.pt_neg((x, y, z, F.mul(x, y)))

    def win_body(p, _):
        out = p
        for _ in range(window_bits):
            p = C.pt_double(p)
        return p, out

    _, bases = lax.scan(win_body, base, None, length=nwin)
    # (nwin, 26, n) per coord -> windows into the batch: (26, nwin*n)
    base_flat = tuple(
        jnp.moveaxis(c, 0, 1).reshape(F.NLIMBS, nwin * n) for c in bases
    )

    def ent_body(acc, _):
        return C.pt_add(acc, base_flat), acc  # collect j, carry j+1

    _, entries = lax.scan(
        ent_body, C.identity((nwin * n,)), None, length=nent
    )
    # scan stacked the entry axis in front: (nent, 26, nwin*n) per
    # coord; field ops want limbs first.
    ex, ey, ez, et = (jnp.moveaxis(c, 0, 1) for c in entries)
    t2d = F.mul(et, F.cvec(C.TWO_D_LIMBS, et.ndim))
    pn = jnp.stack([ey + ex, ey - ex, ez + ez, t2d])  # (4, 26, nent, nwin*n)
    pn = pn.reshape(4, F.NLIMBS, nent, nwin, n)
    # -> (nwin, 4, 26, n, nent) -> (nwin, 4, 26, n*nent)
    pn = jnp.transpose(pn, (3, 0, 1, 4, 2))
    return pn.reshape(nwin, 4, F.NLIMBS, n * nent), valid


_build_cache: dict[tuple[int, int], object] = {}


def _compiled_build(n: int, window_bits: int):
    key = (n, window_bits)
    fn = _build_cache.get(key)
    if fn is None:
        fn = jax.jit(lambda p: build_tables_kernel(p, window_bits))
        _build_cache[key] = fn
    return fn


def comb_mul_keyed(table, key_ids, windows, window_bits: int):
    """Per-key comb: table from build_tables_kernel, key_ids (*batch,)
    int32, windows (nwin, *batch) int32 LE digit decomposition of k.
    Returns [k](-A_key) per lane as an extended point."""
    nent = 1 << window_bits
    base_idx = key_ids * nent

    def body(acc, xs):
        tbl_w, win = xs  # (4, 26, m), (*batch,)
        e = jnp.take(tbl_w, base_idx + win, axis=-1)  # (4, 26, *batch)
        return C.pt_add_pniels(acc, (e[0], e[1], e[2], e[3])), None

    acc, _ = lax.scan(body, C.identity(key_ids.shape), (table, windows))
    return acc


# -- per-set table cache ----------------------------------------------


@dataclass
class KeySetTables:
    """A validator set's device-resident tables."""

    sethash: bytes
    window_bits: int
    key_index: dict[bytes, int]  # pubkey bytes -> table row
    table: object                # device array (nwin, 4, 26, n*nent)
    valid: np.ndarray            # (n,) bool
    nbytes: int

    def key_ids(self, pubs: list[bytes]) -> np.ndarray:
        return np.fromiter(
            (self.key_index[p] for p in pubs), dtype=np.int32, count=len(pubs)
        )




class KeyTableCache:
    """LRU of per-validator-set device tables, bounded by device bytes.

    The reference analog is the expanded-pubkey LRU sized to the
    validator set (ed25519.go:43); here a whole set is one entry and
    the bound is device memory, not entry count.
    """

    def __init__(self, cap_bytes: int = TABLE_CACHE_MB << 20) -> None:
        self._cap = cap_bytes
        self._lock = threading.Lock()
        self._sets: OrderedDict[bytes, KeySetTables] = OrderedDict()
        self._building: dict[bytes, threading.Event] = {}

    def lookup_or_build(self, pubs: list[bytes]) -> KeySetTables | None:
        """Device tables covering every key in ``pubs``, building them
        on a miss; None when the unique-key count is out of policy.
        Concurrent misses for the same set (consensus addVote + light
        client racing on a rotation) build ONCE: losers wait on the
        winner's latch instead of duplicating the device build."""
        unique = sorted(set(pubs))
        n = len(unique)
        if n == 0 or n > TABLE_MAX_KEYS:
            return None
        h = hashlib.sha256(b"".join(unique)).digest()
        while True:
            with self._lock:
                entry = self._sets.get(h)
                if entry is not None:
                    self._sets.move_to_end(h)
                    return entry
                latch = self._building.get(h)
                if latch is None:
                    self._building[h] = threading.Event()
                    break
            latch.wait()
        try:
            entry = self._build(h, unique)
            with self._lock:
                self._sets[h] = entry
                total = sum(e.nbytes for e in self._sets.values())
                while total > self._cap and len(self._sets) > 1:
                    _, old = self._sets.popitem(last=False)
                    total -= old.nbytes
        finally:
            with self._lock:
                self._building.pop(h).set()
        return entry

    def _build(self, h: bytes, unique: list[bytes]) -> KeySetTables:
        n = len(unique)
        window_bits = 8 if n <= KEY8_MAX else 4
        n_pad = _next_pow2(n)
        pub = np.zeros((32, n_pad), dtype=np.uint8)
        for i, p in enumerate(unique):
            pub[:, i] = np.frombuffer(p, dtype=np.uint8)
        # pad lanes with B's encoding (a valid key) to keep shapes pow2
        if n_pad > n:
            benc = np.frombuffer(
                _ref.encode_point(_ref.B_POINT), dtype=np.uint8
            )
            pub[:, n:] = benc[:, None]
        fn = _compiled_build(n_pad, window_bits)
        table, valid = fn(jax.device_put(pub))
        return KeySetTables(
            sethash=h,
            window_bits=window_bits,
            key_index={p: i for i, p in enumerate(unique)},
            table=table,
            valid=np.asarray(valid),
            nbytes=int(np.prod(table.shape)) * 4,
        )

    def clear(self) -> None:
        with self._lock:
            self._sets.clear()


TABLE_CACHE = KeyTableCache()
