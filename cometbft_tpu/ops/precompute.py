"""Device-resident per-validator precomputation for the verify kernel.

The reference caches expanded public keys in an LRU sized to the
validator set because the same keys verify every block
(crypto/ed25519/ed25519.go:43,62-68 — a 4k-entry ExpandedPublicKey
cache).  On TPU the analogous (and much larger) win is keeping whole
scalar-multiplication tables device-resident: steady-state commit
verification then does only SHA-512, the R decompression, and comb
table adds — no per-launch point decompression or window-table build.

Two table families:

- **Fixed base B** (shared, host-built once): an 8-bit comb
  ``B_COMB8[w][j] = j * 256^w * B`` in affine-Niels form — 32 mixed
  adds for [S]B instead of 64.

- **Per-validator-set tables** (device-built): for each key A, comb
  entries ``j * (2^wb)^w * (-A)`` in *projective* Niels form
  (Y+X, Y-X, 2Z, 2dT) — keeping Z projective skips the batched field
  inversion at build time for one extra field mul per add
  (curve.pt_add_pniels).  Window width adapts to the set size: 8-bit
  combs (32 adds/verify, ~3.4 MB/key) for sets up to KEY8_MAX keys,
  4-bit (64 adds, ~430 KB/key) above.

Tables are cached PER KEY in a device pool (``_KeyPool``) bounded by
CMT_TPU_TABLE_CACHE_MB, matching the reference's per-key LRU
(crypto/ed25519/ed25519.go:43,62-68): a set lookup EC-builds pages only
for keys not already pooled, so rotating one validator out of 150 (or
10,000) costs one key's build (~10 verifies), not the whole set's.
``KeySetTables`` entries are immutable snapshots of the pool, memoized
per set-hash while the pool is unchanged.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.crypto import edwards as _ref
from cometbft_tpu.metrics import crypto_metrics as _crypto_metrics
from cometbft_tpu.ops import curve as C
from cometbft_tpu.ops import field as F
from cometbft_tpu.ops import jitguard
from cometbft_tpu.ops.ed25519_verify import _next_pow2
from cometbft_tpu.utils import sync as cmtsync
from cometbft_tpu.utils.env import int_from_env

#: largest set that gets 8-bit per-key combs (3.4 MB/key on device)
KEY8_MAX = int_from_env("CMT_TPU_KEY8_MAX", 256)
#: largest set we precompute tables for at all
TABLE_MAX_KEYS = int_from_env("CMT_TPU_TABLE_MAX_KEYS", 16384)
#: total device bytes across cached sets before LRU eviction
TABLE_CACHE_MB = int_from_env("CMT_TPU_TABLE_CACHE_MB", 6144)


# -- fixed-base 8-bit comb (host-built, shared) ------------------------

_B8_LOCK = cmtsync.Mutex()
_B8: np.ndarray | None = None


def b_comb8() -> np.ndarray:
    """(32, 3, 26, 256) affine-Niels comb of B, gather-friendly layout
    (entry index on the minor axis). Built lazily: ~8k host EC ops."""
    global _B8
    with _B8_LOCK:
        if _B8 is None:
            table = np.zeros((32, 256, 3, F.NLIMBS), dtype=np.int32)
            base = _ref.B_POINT
            for w in range(32):
                acc = _ref.IDENTITY
                for j in range(256):
                    if j == 0:
                        table[w, j] = np.stack([F.ONE, F.ONE, F.ZERO])
                    else:
                        acc = _ref.pt_add(acc, base)
                        ax, ay = _ref.pt_to_affine(acc)
                        table[w, j] = C._niels_from_affine(ax, ay)
                for _ in range(8):
                    base = _ref.pt_double(base)
            _B8 = np.ascontiguousarray(table.transpose(0, 2, 3, 1))
        return _B8


def comb_mul_base8(s_bytes):
    """[S]B via the 8-bit Niels comb: s_bytes (32, *batch) uint8 (LE
    scalar encoding; the comb is exact for any 256-bit integer)."""
    table = jnp.asarray(b_comb8())
    idx = s_bytes.astype(jnp.int32)

    def body(acc, xs):
        tbl_w, byte = xs  # (3, 26, 256), (*batch,)
        e = jnp.take(tbl_w, byte, axis=-1)  # (3, 26, *batch)
        return C.pt_add_niels(acc, (e[0], e[1], e[2])), None

    acc, _ = lax.scan(body, C.identity(s_bytes.shape[1:]), (table, idx))
    return acc


# -- per-key projective-Niels comb builder (device) --------------------

_BX, _BY = _ref.pt_to_affine(_ref.B_POINT)
_B_AFFINE = (F.from_int(_BX), F.from_int(_BY))


def build_tables_kernel(pub, window_bits: int):
    """pub (32, n) uint8 -> (table, valid).

    table: (nwin, 4, 26, n * nent) int32 — window-major projective
    Niels entries ``j * (2^wb)^w * (-A_key)``, minor axis ordered
    (key, entry) so a verify gathers with ``key_id * nent + window``.
    valid: (n,) bool — ZIP-215 decompression validity per key; invalid
    keys get B's table (harmless) and must be masked by callers.
    """
    n = pub.shape[-1]
    nwin = 256 // window_bits
    nent = 1 << window_bits
    a_pt, valid = C.decompress(pub)
    # keep the formulas on-curve for invalid encodings: substitute B
    bx = F.cvec(_B_AFFINE[0], pub.ndim)
    by = F.cvec(_B_AFFINE[1], pub.ndim)
    one = F.cvec(F.ONE, pub.ndim)
    x = F.select(valid, a_pt[0], jnp.broadcast_to(bx, a_pt[0].shape))
    y = F.select(valid, a_pt[1], jnp.broadcast_to(by, a_pt[1].shape))
    z = jnp.broadcast_to(one, y.shape)
    base = C.pt_neg((x, y, z, F.mul(x, y)))

    def win_body(p, _):
        out = p
        for _ in range(window_bits):
            p = C.pt_double(p)
        return p, out

    _, bases = lax.scan(win_body, base, None, length=nwin)
    # (nwin, 26, n) per coord -> windows into the batch: (26, nwin*n)
    base_flat = tuple(
        jnp.moveaxis(c, 0, 1).reshape(F.NLIMBS, nwin * n) for c in bases
    )

    def ent_body(acc, _):
        return C.pt_add(acc, base_flat), acc  # collect j, carry j+1

    _, entries = lax.scan(
        ent_body, C.identity((nwin * n,)), None, length=nent
    )
    # scan stacked the entry axis in front: (nent, 26, nwin*n) per
    # coord; field ops want limbs first.
    ex, ey, ez, et = (jnp.moveaxis(c, 0, 1) for c in entries)
    t2d = F.mul(et, F.cvec(C.TWO_D_LIMBS, et.ndim))
    pn = jnp.stack([ey + ex, ey - ex, ez + ez, t2d])  # (4, 26, nent, nwin*n)
    pn = pn.reshape(4, F.NLIMBS, nent, nwin, n)
    # -> (nwin, 4, 26, n, nent) -> (nwin, 4, 26, n*nent)
    pn = jnp.transpose(pn, (3, 0, 1, 4, 2))
    return pn.reshape(nwin, 4, F.NLIMBS, n * nent), valid


_build_cache: dict[tuple[int, int], object] = {}


def _compiled_build(n: int, window_bits: int):
    key = (n, window_bits, F.trace_config())
    fn = _build_cache.get(key)
    if fn is None:
        jitguard.note_compile("table_build", key)
        fn = jax.jit(lambda p: build_tables_kernel(p, window_bits))
        _build_cache[key] = fn
    return fn


def comb_mul_keyed(table, key_ids, windows, window_bits: int):
    """Per-key comb: table from build_tables_kernel, key_ids (*batch,)
    int32, windows (nwin, *batch) int32 LE digit decomposition of k.
    Returns [k](-A_key) per lane as an extended point."""
    nent = 1 << window_bits
    base_idx = key_ids * nent

    def body(acc, xs):
        tbl_w, win = xs  # (4, 26, m), (*batch,)
        e = jnp.take(tbl_w, base_idx + win, axis=-1)  # (4, 26, *batch)
        return C.pt_add_pniels(acc, (e[0], e[1], e[2], e[3])), None

    acc, _ = lax.scan(body, C.identity(key_ids.shape), (table, windows))
    return acc


# -- per-key incremental table cache ----------------------------------


@dataclass
class KeySetTables:
    """A validator set's view into the device-resident key-table pool.

    ``key_index`` maps each pubkey to its POOL SLOT; ``table``/``valid``
    are immutable snapshots of the pool arrays, so an entry stays
    self-consistent even after later rotations grow, compact, or evict
    the pool underneath it.
    """

    sethash: bytes
    window_bits: int
    key_index: dict[bytes, int]  # pubkey bytes -> pool slot
    table: object                # device array (nwin, 4, 26, cap*nent)
    valid: np.ndarray            # (cap,) bool
    nbytes: int                  # bytes of ``table`` (whole pool)
    set_nbytes: int = 0          # bytes attributable to this set's keys
    _valid_dev: object = None    # lazy device copy of ``valid``
    #: per-mesh device placements hung off this entry (sharded shards,
    #: replicated copies): placement key -> (value, device bytes).
    #: These are EXTRA device copies beyond the base pool array, so the
    #: cache's budget accounting sums them (``placement_bytes``) —
    #: before this, an 8-chip replicated placement held 8x the pool
    #: bytes in HBM that the TABLE_CACHE_MB budget never saw.
    #: Guarded by ``_mtx``: verify threads place concurrently with the
    #: cache's budget sweep reading the dict under the cache lock (the
    #: entry lock is always innermost, never taken around cache calls,
    #: so the cache-lock -> entry-lock order is acyclic).
    placements: dict = field(default_factory=dict)
    _mtx: object = field(default_factory=cmtsync.Mutex)

    def key_ids(self, pubs: list[bytes]) -> np.ndarray:
        return np.fromiter(
            (self.key_index[p] for p in pubs), dtype=np.int32, count=len(pubs)
        )

    def valid_device(self):
        """The validity mask as a device array, transferred EXPLICITLY
        once per entry — the keyed dispatch previously jnp.asarray'd it
        per launch, an implicit h2d transfer the CMT_TPU_JITGUARD
        window flags (and a wasted transfer per steady-state batch)."""
        if self._valid_dev is None:
            self._valid_dev = jax.device_put(self.valid)
        return self._valid_dev

    def placement_bytes(self) -> int:
        """Device bytes held by this entry's mesh placements (counted
        against TABLE_CACHE_MB alongside the base pools)."""
        with self._mtx:
            return sum(n for _, n in self.placements.values())

    def sharded_tables(self, mesh, table_sharding, valid_sharding,
                       ndev: int):
        """Per-chip shards of this set's table (and validity mask),
        device-resident under the given ``NamedSharding``s — built once
        per (entry, mesh) and cached on the entry.

        Slot ownership is STRIDED round-robin — device ``d`` owns slots
        ``{d, d+ndev, d+2*ndev, ...}`` — because live slots cluster in
        ``[0, n_live)`` after compaction: contiguous block ownership
        would leave the high-block devices with only dead slots (150
        live keys in a 256-slot pool on 8 chips would idle 3 of them
        every launch).  The pages are gathered into per-device
        contiguous order ONCE here (a device gather per placement, same
        cost class as the pad), so on the minor (cap*nent) axis device
        ``d``'s shard block holds its strided slots at LOCAL positions
        ``slot // ndev`` — the shard-local gather with rebased ids
        touches only local HBM and the sharded keyed kernel runs with
        zero collectives.  Returns ``(table, valid, per_cap)``; the
        placement's device bytes are recorded for the cache's budget
        accounting.

        Locking follows the stage_growth pattern: the pool-sized
        device work (pad + gather + sharded device_put, seconds at 10k
        keys on a tunneled link) runs OUTSIDE ``_mtx`` so the cache's
        budget sweep — which reads placement_bytes() under the global
        cache lock — never queues every lookup behind a placement
        build; ``_mtx`` guards only the dict swap.  Two threads racing
        a cold placement may both build; the loser's copy is dropped
        and freed (a transient, bounded duplicate — the same trade
        stage_growth makes)."""
        key = ("sharded", mesh)
        with self._mtx:
            placed = self.placements.get(key)
        if placed is None:
            nent = 1 << self.window_bits
            cap = len(self.valid)
            per_cap = -(-cap // ndev)
            shard_cap = per_cap * ndev
            # A post-seal placement build (validator rotation) runs
            # inside the armed CMT_TPU_JITGUARD transfer window, whose
            # job is catching silent PER-LAUNCH transfers.  This is
            # deliberate ONE-TIME staging per (entry, mesh) — pad
            # constants, the gather-index upload, and the sharded
            # device_puts all move data on purpose — so it opens an
            # audited allow scope the same way warmup does.
            with jax.transfer_guard("allow"):
                table, valid = self.table, self.valid
                if shard_cap > cap:
                    table = jnp.pad(
                        table,
                        [(0, 0), (0, 0), (0, 0),
                         (0, (shard_cap - cap) * nent)],
                    )
                    valid = np.pad(valid, (0, shard_cap - cap))
                # strided -> per-device-contiguous page permutation:
                # position (d*per_cap + j) <- slot (j*ndev + d)
                slot_perm = (
                    np.arange(shard_cap).reshape(per_cap, ndev).T.ravel()
                )
                idx = (
                    slot_perm[:, None] * nent + np.arange(nent)
                ).ravel()
                table = table[..., jax.device_put(idx)]
                valid = valid[slot_perm]
                table = jax.device_put(table, table_sharding)
                valid = jax.device_put(valid, valid_sharding)
            built = (
                (table, valid, per_cap),
                int(table.nbytes) + int(valid.nbytes),
            )
            with self._mtx:
                placed = self.placements.setdefault(key, built)
        return placed[0]


_B_ENC = np.frombuffer(_ref.encode_point(_ref.B_POINT), dtype=np.uint8)


def _pool_cap(nkeys: int) -> int:
    """Pool capacities come from a small fixed ladder (pow2 up to 4096,
    then 2048-slot steps) so the shape-specialized verify kernel only
    retraces a bounded number of times — while avoiding pow2's up-to-2x
    HBM waste at large validator counts (10k keys: 10240 slots =
    4.4 GB at 4-bit, vs 16384 slots = 7 GB)."""
    if nkeys <= 4096:
        return _next_pow2(max(nkeys, 1))
    return -(-nkeys // 2048) * 2048


class _KeyPool:
    """One window width's device pool of per-key comb pages.

    The pool's minor axis holds ``cap`` fixed-size key pages
    (cap * nent entries); a key's page lives at
    ``[slot*nent : (slot+1)*nent]`` so ``comb_mul_keyed``'s
    ``key_id * nent`` indexing works with slot numbers as key ids.
    Capacity follows the ``_pool_cap`` ladder — powers of two up to
    4096 slots, then 2048-slot steps: the compiled keyed-verify kernel
    specializes on the table shape, so growth only retraces at ladder
    boundaries (a bounded count), while large pools avoid pow2's
    up-to-2x HBM waste.
    """

    def __init__(self, window_bits: int) -> None:
        self.window_bits = window_bits
        self.nent = 1 << window_bits
        self.nwin = 256 // window_bits
        self.key_bytes = self.nwin * 4 * F.NLIMBS * self.nent * 4
        self.cap = 0
        self.table = None  # device (nwin, 4, 26, cap*nent) int32
        self.valid = np.zeros(0, dtype=bool)
        self.slots: OrderedDict[bytes, int] = OrderedDict()  # LRU order
        self.free: list[int] = []
        self.version = 0  # bumped on any table-array change

    def nbytes(self) -> int:
        return self.cap * self.key_bytes

    def stage_growth(
        self, version: int, table, cap: int, nkeys: int
    ):
        """Build the grown table array from a (version, table, cap)
        snapshot WITHOUT the cache lock held — the jnp.pad is a device
        copy of the whole pool, and doing it under the lock stalls
        every concurrent cached-set lookup for the copy's duration
        (ADVICE round 5).  Returns (snapshot_version, new_cap,
        grown_table), or None when the snapshot needs no growth;
        ``ensure_capacity(..., staged=...)`` applies it only if the
        pool version is still the snapshot's."""
        if cap >= nkeys:
            return None
        new_cap = _pool_cap(nkeys)
        shape = (self.nwin, 4, F.NLIMBS, new_cap * self.nent)
        if table is None:
            grown = jnp.zeros(shape, dtype=jnp.int32)
        else:
            pad = (new_cap - cap) * self.nent
            grown = jnp.pad(table, [(0, 0), (0, 0), (0, 0), (0, pad)])
        return (version, new_cap, grown)

    def ensure_capacity(self, nkeys: int, staged=None) -> None:
        """Grow to the ladder capacity for ``nkeys``.  Lock held.  A
        ``staged`` pre-grown array (from stage_growth) is swapped in
        when its snapshot version still matches and it is big enough;
        otherwise (concurrent build/compact moved the pool — rare) the
        pad runs here as before."""
        if self.cap >= nkeys:
            return
        new_cap = _pool_cap(nkeys)
        if (
            staged is not None
            and staged[0] == self.version
            and staged[1] >= new_cap
        ):
            new_cap = staged[1]
            self.table = staged[2]
        elif self.table is None:
            shape = (self.nwin, 4, F.NLIMBS, new_cap * self.nent)
            self.table = jnp.zeros(shape, dtype=jnp.int32)
        else:
            pad = (new_cap - self.cap) * self.nent
            self.table = jnp.pad(
                self.table, [(0, 0), (0, 0), (0, 0), (0, pad)]
            )
        self.valid = np.concatenate(
            [self.valid, np.zeros(new_cap - self.cap, dtype=bool)]
        )
        self.free.extend(range(self.cap, new_cap))
        self.cap = new_cap
        self.version += 1
        _crypto_metrics().key_pool_retraces.labels(
            window_bits=str(self.window_bits)
        ).inc()

    def compact(self) -> None:
        """Gather live pages into a fresh ladder-capacity array (device
        gather, no EC recompute) — run after eviction freed enough
        slots that the pool holds mostly dead pages."""
        n_live = len(self.slots)
        new_cap = _pool_cap(n_live)
        if new_cap >= self.cap:
            return
        order = list(self.slots.items())  # preserves LRU order
        gather = np.concatenate(
            [
                np.arange(s * self.nent, (s + 1) * self.nent)
                for _, s in order
            ]
        ) if order else np.zeros(0, dtype=np.int64)
        pad = new_cap * self.nent - len(gather)
        new_table = jnp.pad(
            self.table[..., jnp.asarray(gather)],
            [(0, 0), (0, 0), (0, 0), (0, pad)],
        )
        new_valid = np.zeros(new_cap, dtype=bool)
        new_slots: OrderedDict[bytes, int] = OrderedDict()
        for i, (p, s) in enumerate(order):
            new_slots[p] = i
            new_valid[i] = self.valid[s]
        self.table = new_table
        self.valid = new_valid
        self.slots = new_slots
        self.free = list(range(n_live, new_cap))
        self.cap = new_cap
        self.version += 1
        _crypto_metrics().key_pool_retraces.labels(
            window_bits=str(self.window_bits)
        ).inc()


class KeyTableCache:
    """PER-KEY LRU of device-resident comb-table pages, bounded by
    device bytes across both window widths.

    The reference's expanded-pubkey cache is per-key
    (crypto/ed25519/ed25519.go:43,62-68) precisely so validator churn is
    incremental; this cache matches that: a set lookup builds tables
    ONLY for keys not already pooled, so rotating 1 of 150 (or 10,000)
    validators costs one key's build (~10 verifies), not the whole
    set's.
    """

    def __init__(self, cap_bytes: int = TABLE_CACHE_MB << 20) -> None:
        self._cap = cap_bytes
        self._lock = cmtsync.Mutex()
        self._pools = {8: _KeyPool(8), 4: _KeyPool(4)}
        # pubkey-level build latches: concurrent misses on overlapping
        # keys (consensus addVote + light client racing on a rotation)
        # build each key ONCE — losers wait on the winner's latch
        self._pending: dict[tuple[int, bytes], threading.Event] = {}
        # set-hash -> (pool version, entry) memo so repeat lookups of
        # an unchanged set return the SAME entry object (the mesh path
        # hangs replicated copies off it)
        self._entries: OrderedDict[bytes, tuple[int, KeySetTables]] = (
            OrderedDict()
        )
        self.stats = {"keys_built": 0, "keys_evicted": 0}

    def _set_key(self, pubs: list[bytes]):
        """The dispatch-policy prologue shared by peek and
        lookup_or_build: (unique keys, window pool, set hash), or None
        when the unique-key count is out of table policy.  ONE
        implementation so the size gate / window-width choice / hash
        can never drift between the warm probe and the build path —
        a divergence would make peek probe the wrong pool and silently
        demote warm batches off the keyed tier."""
        unique = sorted(set(pubs))
        n = len(unique)
        if n == 0 or n > TABLE_MAX_KEYS:
            return None
        pool = self._pools[8 if n <= KEY8_MAX else 4]
        return unique, pool, hashlib.sha256(b"".join(unique)).digest()

    def peek(self, pubs: list[bytes]) -> KeySetTables | None:
        """An entry iff EVERY key is already resident — no builds, no
        waiting on in-flight builds.  This is the keyed-by-default
        dispatch probe: a batch below the generic device threshold
        still takes the keyed tier when its tables are warm, and the
        probe must never stall a small batch behind an EC build."""
        sk = self._set_key(pubs)
        if sk is None:
            return None
        unique, pool, h = sk
        with self._lock:
            if any(p not in pool.slots for p in unique):
                return None
            return self._finish_lookup(h, pool, unique)

    def lookup_or_build(self, pubs: list[bytes]) -> KeySetTables | None:
        """An entry covering every key in ``pubs``, building pages only
        for keys not already pooled; None when the unique-key count is
        out of policy."""
        sk = self._set_key(pubs)
        if sk is None:
            return None
        unique, pool, h = sk
        window_bits = pool.window_bits
        while True:
            with self._lock:
                waits = [
                    self._pending[k]
                    for p in unique
                    if (k := (window_bits, p)) in self._pending
                ]
                if not waits:
                    missing = [p for p in unique if p not in pool.slots]
                    if not missing:
                        return self._finish_lookup(h, pool, unique)
                    for p in missing:
                        self._pending[(window_bits, p)] = threading.Event()
            if waits:
                for ev in waits:
                    ev.wait()
                continue
            try:
                pages, page_valid = self._build_pages(missing, window_bits)
                # stage any pool growth outside the lock: the pad is a
                # device copy of the whole table, and cached-set
                # lookups must not queue behind it
                with self._lock:
                    snap = (pool.version, pool.table, pool.cap)
                    need = len(pool.slots) + len(missing)
                staged = pool.stage_growth(*snap, need)
                with self._lock:
                    pool.ensure_capacity(
                        len(pool.slots) + len(missing), staged=staged
                    )
                    slots = [pool.free.pop() for _ in missing]
                    idx = (
                        np.array(slots, dtype=np.int64)[:, None]
                        * pool.nent
                        + np.arange(pool.nent)
                    ).ravel()
                    pool.table = pool.table.at[..., jnp.asarray(idx)].set(
                        pages[..., : len(missing) * pool.nent]
                    )
                    pool.version += 1
                    for i, (p, s) in enumerate(zip(missing, slots)):
                        pool.slots[p] = s
                        pool.valid[s] = page_valid[i]
                    self.stats["keys_built"] += len(missing)
                    _crypto_metrics().key_pool_builds.inc(len(missing))
                    self._evict_over_budget(keep=set(unique))
                    self._update_pool_gauges()
                    # a concurrent lookup's eviction may have dropped
                    # keys of ours that were present before our build
                    # released the lock — loop to rebuild them if so
                    if all(p in pool.slots for p in unique):
                        return self._finish_lookup(h, pool, unique)
            finally:
                with self._lock:
                    for p in missing:
                        self._pending.pop((window_bits, p)).set()

    def _finish_lookup(
        self, h: bytes, pool: _KeyPool, unique: list[bytes]
    ) -> KeySetTables:
        """Touch LRU order and return a (memoized) entry. Lock held."""
        for p in unique:
            pool.slots.move_to_end(p)
        memo = self._entries.get(h)
        if memo is not None and memo[0] == pool.version:
            self._entries.move_to_end(h)
            return memo[1]
        # each memoized entry pins ITS version's full pool array: sweep
        # stale-version entries so the memo never holds device arrays
        # beyond the two live pools (a 64-count bound alone would pin
        # ~64 pool-sized snapshots across rotations — an HBM leak)
        self._sweep_stale_entries()
        entry = KeySetTables(
            sethash=h,
            window_bits=pool.window_bits,
            key_index={p: pool.slots[p] for p in unique},
            table=pool.table,
            valid=pool.valid.copy(),
            nbytes=pool.nbytes(),
            set_nbytes=len(unique) * pool.key_bytes,
        )
        self._entries[h] = (pool.version, entry)
        while len(self._entries) > 64:
            self._entries.popitem(last=False)
        return entry

    def _build_pages(self, missing: list[bytes], window_bits: int):
        """EC-compute comb pages for ``missing`` keys (device kernel,
        pow2-padded with B's encoding). Runs OUTSIDE the cache lock so
        cached-set lookups aren't blocked behind a build."""
        n = len(missing)
        n_pad = _next_pow2(n)
        pub = np.zeros((32, n_pad), dtype=np.uint8)
        for i, p in enumerate(missing):
            pub[:, i] = np.frombuffer(p, dtype=np.uint8)
        if n_pad > n:
            pub[:, n:] = _B_ENC[:, None]
        fn = _compiled_build(n_pad, window_bits)
        from cometbft_tpu.utils.trace import TRACER as _tracer

        with _tracer.span(
            "table_build", cat="device", keys=n, window_bits=window_bits
        ):
            table, valid = fn(jax.device_put(pub))
            valid = jax.device_get(valid)[:n]  # host sync: per-build validity fetch (build path, not the verify hot loop)
        return table, valid

    def _sweep_stale_entries(self) -> None:
        """Drop memoized entries whose pool version moved on.  Lock
        held.  Besides un-pinning stale pool-array snapshots, this also
        releases the entries' mesh PLACEMENTS (sharded shards /
        replicated copies) so their device bytes leave the budget."""
        for k in [
            k
            for k, (v, e) in self._entries.items()
            if v != self._pools[e.window_bits].version
        ]:
            del self._entries[k]

    def placement_bytes(self) -> int:
        """Device bytes held by live memoized entries' mesh placements
        — the per-device sharded/replicated table copies that exist in
        HBM beyond the base pool arrays.  Lock held."""
        return sum(e.placement_bytes() for _, e in self._entries.values())

    def _evict_over_budget(self, keep: set[bytes]) -> None:
        """Drop LRU keys (never ones in ``keep``) until compaction can
        bring the pools under budget, then compact. Lock held. A single
        set larger than the budget stays resident: the ACTIVE set must
        always fit. Eviction is minimal — LRU-first, stopping as soon
        as the post-compaction footprint fits.

        The OVER-BUDGET TRIGGER counts the base pool arrays PLUS live
        entries' mesh placements (placement_bytes): on an 8-chip mesh a
        replicated placement alone is 8x the pool bytes, so ignoring it
        (the pre-mesh accounting) let the real HBM footprint run ~9x
        past TABLE_CACHE_MB.  The eviction loop's STOP condition,
        however, compares only the post-compaction pool footprint:
        compaction bumps the pool versions, staling every memoized
        entry, and the sweep below releases the placements those
        entries pinned — so counting ``placed`` (a term key eviction
        can never reduce) in the stop condition would evict EVERY
        evictable key on each over-budget rotation instead of the
        minimal LRU set.  Steady-state placement overhead is bounded:
        the sharded placement is ~1x the active pool (vs ndev-x for
        the replaced replicated path), one per mesh per live entry."""

        def compacted_bytes(p: _KeyPool) -> int:
            return min(p.cap, _pool_cap(len(p.slots))) * p.key_bytes

        # release placements pinned by already-stale entries FIRST:
        # they are garbage awaiting the sweep, not working set, and
        # dropping them is often enough to get back under budget with
        # zero key evictions (a live entry's placement is the active
        # working set and — like the active key set — stays resident)
        self._sweep_stale_entries()
        placed = self.placement_bytes()
        if (
            sum(p.nbytes() for p in self._pools.values()) + placed
            <= self._cap
        ):
            return
        changed = False
        for pool in self._pools.values():
            evictable = [p for p in pool.slots if p not in keep]  # LRU order
            for p in evictable:
                if (
                    sum(compacted_bytes(q) for q in self._pools.values())
                    <= self._cap
                ):
                    break
                s = pool.slots.pop(p)
                pool.valid[s] = False
                pool.free.append(s)
                self.stats["keys_evicted"] += 1
                _crypto_metrics().key_pool_evictions.inc()
                changed = True
        if changed:
            for pool in self._pools.values():
                pool.compact()
            # compaction bumped versions: stale entries (and the
            # placement bytes they pinned) can go now
            self._sweep_stale_entries()

    def _update_pool_gauges(self) -> None:
        """Refresh the occupancy/capacity gauges for both window
        widths.  Lock held (reads pool.slots / pool.cap)."""
        cm = _crypto_metrics()
        for wb, pool in self._pools.items():
            lbl = str(wb)
            cm.key_pool_keys.labels(window_bits=lbl).set(len(pool.slots))
            cm.key_pool_capacity.labels(window_bits=lbl).set(pool.cap)

    def clear(self) -> None:
        with self._lock:
            self._pools = {8: _KeyPool(8), 4: _KeyPool(4)}
            self._entries.clear()
            self._update_pool_gauges()


TABLE_CACHE = KeyTableCache()


#: kernel shape/dtype contracts (grammar: ops/contracts.py; verified
#: statically by tools/jitcheck.py, swept devicelessly by
#: tests/test_jitcheck.py).  ``windows`` for comb_mul_keyed is the LE
#: digit decomposition of the scalar — one digit per comb window.
_CONTRACTS = {
    "build_tables_kernel": {
        "args": {"pub": ("u8", (32, "B"))},
        "static": ("window_bits",),
        "out": [
            ("i32", ("nwin", 4, "NLIMBS", "B*nent")),
            ("bool", ("B",)),
        ],
    },
    "comb_mul_base8": {
        "args": {"s_bytes": ("u8", (32, "B"))},
        "static": (),
        "out": [
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
        ],
    },
    "comb_mul_keyed": {
        "args": {
            "table": ("i32", ("nwin", 4, "NLIMBS", "cap*nent")),
            "key_ids": ("i32", ("B",)),
            "windows": ("i32", ("nwin", "B")),
        },
        "static": ("window_bits",),
        "out": [
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
            ("i32", ("NLIMBS", "B")),
        ],
    },
}
